"""Driver benchmark: ResNet-50 amp-O2 train-step throughput (img/s/chip).

Mirrors the reference's north-star workload (examples/imagenet/main_amp.py:
ResNet-50 + amp O2 + DDP; BASELINE.json — "metric") on one chip with synthetic
data. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "img/s/chip", "vs_baseline": N}

vs_baseline is relative to the apex O2 V100 per-GPU rate (~820 img/s, NVIDIA
DeepLearningExamples ResNet50v1.5 README — see BASELINE.md; the driver's bar
is >=0.9 on real v5e hardware).

The JSON is self-describing about plausibility (VERDICT round-1 weak #1):
``mfu_est`` is the model-FLOPs utilization implied by the measured rate
against the chip's bf16 peak, and ``implausible: true`` flags any reading
over 1.0.

Round 5: the headline ``value`` is anchored on DEVICE time when the
profiler dump has device lanes (``basis: "device_trace"``). Wall-clock
timing through the axon tunnel is dispatch-dominated — rounds 1-4 recorded
physically impossible rates (BENCH_r04: 93.5k img/s = 5.8x the chip's bf16
peak, 39.5% spread) that carried no hardware signal. The profiler's device
lanes time the silicon itself (the reference's nvprof kernel-time column —
SURVEY §6/§7: time the device, not the python loop), so ``value`` becomes
a real throughput claim: per-window rate = BATCH*STEPS / device span of
the capture (bubbles included; ``duty_cycle`` reports busy/span). The old
wall-clock reading stays in ``wall_clock`` for cross-round continuity.

The line also carries a ``serving`` sub-object (BENCH_SERVING_LEG=0 to
drop it): a smoke-sized paged-vs-contiguous serving capacity
measurement via ``bench_serving.paged_capacity_stats`` — tokens/s,
max-concurrent-requests vs contiguous rows, and HBM-bytes-per-request
reduction — so the serving stack finally has rows in the tracked
BENCH_* trajectory (ROADMAP's "Recent" gap), plus a nested ``chaos``
sub-object (BENCH_SERVING_CHAOS=0 to drop it): goodput under a seeded
fault-injection schedule vs the fault-free rate, failed/requeued
counts and ``token_mismatched_requests`` (expected 0) via
``bench_serving.chaos_stats``, a nested ``speculative``
sub-object (BENCH_SERVING_SPEC=0 to drop it): draft-and-verify
acceptance rate and tokens-per-slot-step vs plain decode with
``token_mismatched_requests`` (expected 0, bitwise) via
``bench_serving.spec_stats``, a nested ``tensor_parallel``
sub-object (BENCH_SERVING_TP=0 to drop it; BENCH_SERVING_TP=N sizes
the mesh): tp=1 vs tp=N CPU device emulation — per-shard KV HBM
bytes, collective inventory, ``token_mismatched_requests`` (expected
0) — run as a subprocess because the mesh leg must force emulated CPU
devices before any backend initializes, and a nested ``quantized_kv``
sub-object (BENCH_SERVING_QUANT=0 to drop it): the int8-capacity leg
— KV-bytes-per-token reduction, concurrency both modes,
``token_match_rate`` vs the bf16 oracle — via
``bench_serving.quantized_kv_stats``, a nested
``quantized_weights`` sub-object (BENCH_SERVING_WQUANT=0 to drop it):
the int8-weights leg — weight-bytes reduction, bytes-per-param,
HBM-bytes-per-request bf16 vs the combined weights+KV tier,
``token_match_rate`` both quantized modes vs the bf16 oracle — via
``bench_serving.quantized_weights_stats``, and a nested
``async_heartbeat`` sub-object (BENCH_SERVING_ASYNC=0 to drop it):
sync vs dispatch-ahead pipelined serving on one engine — heartbeat
wall per emitted token, duty cycle, ``token_mismatched_requests``
(expected 0, bitwise) — via ``bench_serving.async_stats``, and a
nested ``host_tier`` sub-object (BENCH_SERVING_HOST_TIER=0 to drop
it): the hierarchical-KV leg — a prefix working set larger than the
device pool served tier-off vs sync-swap vs ASYNC swap-out (hit
rate, chunks skipped, TTFT, admission-stall p50/p99 sync vs async
from the telemetry histogram, swap traffic, bitwise exactness, and
the BENCH_SERVING_HOST_TIER_TP mesh-composition sub-leg's
per-shard-record pins) — run as a subprocess like the
tensor-parallel leg so the mesh sub-leg can force emulated CPU
devices, and a
nested ``replica_router`` sub-object (BENCH_SERVING_ROUTER=0 to drop
it; BENCH_SERVING_REPLICAS sizes the fleet): the prefix-aware
least-loaded router at 1 vs N replicas — aggregate tokens/s, p99
TTFT, prefix hit rate affinity vs a random-routing control,
``token_mismatched_requests`` (expected 0, bitwise) — via
``bench_serving.replica_router_stats``, and a nested
``disaggregated`` sub-object (BENCH_SERVING_DISAGG=0 to drop it):
the prefill/decode role-split leg — one fleet over one shared host
arena, colocated vs ``Router(roles=[...])`` with CRC'd KV handoff
(bystander TTFT p50/p99 both modes, the decode-replica
heartbeat-tail isolation, handoff traffic + export/import p50/p99,
zero re-prefills, zero leaked arena bytes, bitwise exactness) — via
``bench_serving.disagg_stats``, and a nested ``overload``
sub-object (BENCH_SERVING_OVERLOAD=0 to drop it): the SLO-aware
preemptive-scheduling leg — the same seeded mixed-class stream at
>1x slot capacity served FIFO vs SLO-aware on identical geometry
(interactive TTFT p50/p99 both modes, per-class deadline-miss rate
against one FIFO-calibrated threshold, met-deadline goodput,
preempt/resume churn, bitwise exactness vs the FIFO serve) — via
``bench_serving.overload_stats``, and a nested ``lora`` sub-object
(BENCH_SERVING_LORA=0 to drop it): the multi-tenant adapter leg —
the mixed-tenant stream heterogeneously batched vs per-adapter
sequential at identical geometry (tokens/s + speedup, adapter churn
+ warm-bind rate, zero recompiles for N adapters, bitwise
exactness between batch compositions) — via
``bench_serving.lora_stats``, and a nested ``process_fleet``
sub-object (BENCH_SERVING_FLEET=0 to drop it;
BENCH_SERVING_REPLICAS sizes the fleet): the out-of-process worker
fleet — 1 worker vs N separate OS processes behind the stdlib
transport (aggregate tokens/s + ``scaling_x``, an honest CPU-box
scaling column since workers share no GIL, p99 TTFT, prefix hit
rate, rolling-restart wall time + per-worker p50/max, health
counters, bitwise exactness vs the 1-worker fleet) — via
``bench_serving.process_fleet_stats``.
Failure-isolated at every layer: a broken serving stack puts
{"error": ...} there, never kills the ResNet row.
"""

from __future__ import annotations

import json
import os
import tempfile
import time

# NOTHING heavy imports at module level: the guard contract (every run,
# including one that exhausts its transient retries, ends in a parseable
# JSON line) only holds for failures raised INSIDE guarded main() — a
# module-level jax/optax import crash or a malformed BENCH_* env value
# parsed at import time dies before the guard is armed and leaves a raw
# traceback as the last output (the BENCH_r05 '"parsed": null' shape).
# Heavy imports and env parsing therefore live in main(); a retry re-runs
# them from scratch, which is exactly what a transient backend hiccup
# needs.

METRIC = "resnet50_amp_o2_train_img_per_sec_per_chip"

V100_O2_IMG_PER_SEC = 820.0

# Analytic ResNet-50 cost: ~4.1 GMACs forward per 224x224 image = ~8.2
# GFLOP at mult+add=2 counting; a training step is ~3x forward
# (backward ~2x). Scaled by (IMAGE/224)^2 for non-default resolutions
# (conv cost is proportional to spatial area).
RESNET50_TRAIN_FLOP_PER_IMG_224 = 3 * 8.2e9

# bf16 peak by device kind; conservative default for unknown kinds.
_PEAK_BF16 = {
    "TPU v5 lite": 394e12,   # v5e
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
}


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for key, peak in _PEAK_BF16.items():
        if kind.startswith(key):
            return peak
    return 394e12

def _env_int(name: str, default: str) -> int:
    """BENCH_* env knob as int; a malformed value becomes a clean
    SystemExit INSIDE the guard (one parseable failure line) instead of
    an import-time ValueError before the guard is armed."""
    raw = os.environ.get(name, default)
    try:
        return int(raw)
    except ValueError:
        raise SystemExit(f"{name}={raw!r} is not an integer")


def _read_env() -> dict:
    """All BENCH_* knobs, parsed at main() time (guarded, retry-fresh).

    BENCH_BATCH default 256/chip: the apex-recipe production batch for
    ResNet-50 amp O2 (NVIDIA DeepLearningExamples uses 256/V100-32G; a
    v5e's 16GB holds it in bf16) and large enough that step time is
    compute- rather than dispatch-bound. BENCH_WINDOWS >=3 independent
    windows reported as median+min+spread (VERDICT round-2 weak #1: one
    10-step sample carried no variance information).
    BENCH_TRACE_WINDOWS: device-anchored profiler captures (basis:
    "device_trace"). BENCH_ACCUM_STEPS=N scans N microbatches of
    BATCH/N per optimizer step (amp.make_train_step accum_steps) —
    each jit_step still consumes BATCH images, so img/s stays directly
    comparable to the N=1 rows."""
    return {
        "BATCH": _env_int("BENCH_BATCH", "256"),
        "IMAGE": _env_int("BENCH_IMAGE", "224"),
        "WARMUP": _env_int("BENCH_WARMUP", "2"),
        "STEPS": _env_int("BENCH_STEPS", "10"),
        "WINDOWS": _env_int("BENCH_WINDOWS", "3"),
        "TRACE_WINDOWS": _env_int("BENCH_TRACE_WINDOWS", "3"),
        "ACCUM_STEPS": _env_int("BENCH_ACCUM_STEPS", "1"),
        # BENCH_SERVING_LEG=0 drops the embedded serving capacity row
        "SERVING_LEG": _env_int("BENCH_SERVING_LEG", "1"),
    }


def _median(xs):
    xs = sorted(xs)
    mid = len(xs) // 2
    return xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])


# Smoke geometry for the embedded serving leg: a tiny paged-vs-
# contiguous capacity measurement (~seconds, CPU-safe). Any exported
# BENCH_SERVING_* knob overrides a field (bench_serving._load_env's
# env-beats-smoke contract), so TPU rows can size it up without code
# changes.
_SERVING_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 12, "NEW_TOKENS": 8, "WINDOWS": 1,
}

# The chaos sub-leg's smoke geometry (it serves its stream TWICE —
# rate 0 + injected — so it is sized below the capacity leg's)
_SERVING_CHAOS_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 6, "NEW_TOKENS": 8, "WINDOWS": 1,
}

# The speculative sub-leg's smoke geometry (two streams, each served
# twice — plain + spec — so it matches the chaos leg's sizing)
_SERVING_SPEC_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 6, "NEW_TOKENS": 8, "WINDOWS": 1,
}

# The quantized-KV sub-leg's smoke geometry (the shared-prefix stream
# served twice — bf16 oracle + int8 — so it matches its siblings'
# sizing; BENCH_SERVING_QUANT_SLOTS et al. still win, env-beats-smoke)
_SERVING_QUANT_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 6, "NEW_TOKENS": 8, "WINDOWS": 1,
}

# The quantized-weights sub-leg's smoke geometry (the shared-prefix
# stream served THREE times — bf16 oracle, int8 weights, int8 weights
# + int8 KV — at identical geometry, so it matches its siblings'
# sizing; env knobs still win, env-beats-smoke)
_SERVING_WQUANT_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 6, "NEW_TOKENS": 8, "WINDOWS": 1,
}

# The async-heartbeat sub-leg's smoke geometry (the stream is served
# twice — sync oracle + dispatch-ahead). Sized LONGER than its
# siblings on purpose: pipelining pays fixed fill/drain beats per
# wave, and a too-short stream measures mostly that overhead. On this
# CPU backend the pipelined row reads a small loss REGARDLESS
# (donated-buffer programs execute synchronously inside dispatch —
# see bench_serving's module docstring); exactness + the heartbeat
# split are the CPU-honest fields, the improvement is the TPU claim.
# BENCH_SERVING_ASYNC_DEPTH et al. still win, env-beats-smoke.
_SERVING_ASYNC_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 8, "NEW_TOKENS": 16, "WINDOWS": 2,
}

# (The host-tier sub-leg runs as a SUBPROCESS — see
# _serving_host_tier_leg — so its smoke geometry is the child's own
# HOST_SMOKE preset in bench_serving.py; exported BENCH_SERVING_*
# knobs still win inside the child, env-beats-smoke.)

# The replica-router sub-leg's smoke geometry (the session stream is
# served THREE ways — 1 replica, N affinity, N random control — so it
# is sized small; REQUESTS is SESSIONS per window, 2 turns each;
# CHUNK_LEN stays small so a turn's history spans several reuse
# blocks). BENCH_SERVING_REPLICAS et al. still win, env-beats-smoke.
_SERVING_ROUTER_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 2, "MAX_LEN": 128,
    "PREFILL_LEN": 48, "CHUNK_LEN": 8, "REQUESTS": 4, "NEW_TOKENS": 8,
    "WINDOWS": 1, "PREFIX_POOL": 4,
}

# The disaggregated sub-leg's smoke geometry (the bystander/heavyweight
# stream is served TWICE — colocated, then role-split with KV handoff —
# so it is sized small; every third request is a heavyweight).
# BENCH_SERVING_REPLICAS et al. still win, env-beats-smoke.
_SERVING_DISAGG_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 2, "MAX_LEN": 128,
    "PREFILL_LEN": 48, "CHUNK_LEN": 8, "SHORT_LEN": 6, "REQUESTS": 6,
    "NEW_TOKENS": 8, "WINDOWS": 1, "PREFIX_POOL": 4,
}

# The overload sub-leg's smoke geometry (the mixed-class stream is
# served TWICE on one engine — FIFO, then SLO-aware with preemption —
# at >1x slot capacity; every third request is interactive). The
# interactive deadline is calibrated at BENCH_SERVING_OVERLOAD_DL_PCT
# percent of the measured FIFO window wall and judged identically in
# both modes. BENCH_SERVING_REQUESTS et al. still win,
# env-beats-smoke.
_SERVING_OVERLOAD_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 2, "MAX_LEN": 128,
    "PREFILL_LEN": 48, "CHUNK_LEN": 8, "SHORT_LEN": 6, "REQUESTS": 12,
    "NEW_TOKENS": 10, "WINDOWS": 1, "PREFIX_POOL": 4,
}

# The process-fleet sub-leg's smoke geometry (the session stream is
# served through TWO fleets — 1 worker, then N — and every worker
# spawn pays interpreter + jax import + compile, so it is sized
# small; the stream matches the router sub-leg's so the thread-vs-
# process rows are comparable). BENCH_SERVING_REPLICAS et al. still
# win, env-beats-smoke.
_SERVING_FLEET_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 2, "MAX_LEN": 128,
    "PREFILL_LEN": 48, "CHUNK_LEN": 8, "REQUESTS": 4, "NEW_TOKENS": 8,
    "WINDOWS": 1, "PREFIX_POOL": 4,
}

# The multi-tenant LoRA sub-leg's smoke geometry (the mixed-tenant
# stream is served TWICE — heterogeneously batched, then per-adapter
# sequential — on identically-built engines, so it is sized small).
# BENCH_SERVING_LORA_ADAPTERS et al. still win, env-beats-smoke.
_SERVING_LORA_SMOKE = {
    "SIZE": "tiny", "VOCAB": 512, "SLOTS": 4, "MAX_LEN": 128,
    "PREFILL_LEN": 32, "REQUESTS": 8, "NEW_TOKENS": 12, "WINDOWS": 1,
}


def _serving_leg() -> dict:
    """The serving trajectory row (ROADMAP: bench_serving.py had no
    BENCH_* row): serve a short-prompt stream on the paged engine vs
    the contiguous baseline at identical pool bytes and fold the
    headline fields — tokens/s, max concurrent requests vs rows,
    HBM-bytes-per-request reduction — into bench.py's one JSON line.
    Failure-isolated: a broken serving stack yields {"error": ...}
    here, never a lost ResNet row."""
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_SMOKE))
        _, summary = bench_serving.paged_capacity_stats()
        out = {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s",
            "max_concurrent_requests", "contiguous_slots",
            "logical_concurrency_exceeds_rows",
            "hbm_bytes_per_request", "hbm_bytes_per_request_contiguous",
            "hbm_bytes_per_request_reduction_pct", "pool_mib",
            "token_mismatched_requests", "model")}
        out["chaos"] = _serving_chaos_leg()
        out["speculative"] = _serving_spec_leg()
        out["tensor_parallel"] = _serving_tp_leg()
        out["quantized_kv"] = _serving_quant_leg()
        out["quantized_weights"] = _serving_wquant_leg()
        out["async_heartbeat"] = _serving_async_leg()
        out["replica_router"] = _serving_router_leg()
        out["disaggregated"] = _serving_disagg_leg()
        out["overload"] = _serving_overload_leg()
        out["lora"] = _serving_lora_leg()
        out["process_fleet"] = _serving_process_fleet_leg()
        out["host_tier"] = _serving_host_tier_leg()
        return out
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_chaos_leg() -> dict:
    """The fault-isolation trajectory sub-row: smoke-sized
    goodput-under-injection summary (rate 0 vs BENCH_SERVING_FAULT_PCT)
    from ``bench_serving.chaos_stats``. BENCH_SERVING_CHAOS=0 drops it;
    failure-isolated like its parent — a broken fault layer yields
    {"error": ...} here, never a lost serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_CHAOS", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_CHAOS_SMOKE))
        _, summary = bench_serving.chaos_stats()
        return {k: summary[k] for k in (
            "value", "unit", "goodput_rate0_tokens_per_s",
            "goodput_retention_pct", "fault_pct", "clean_requests",
            "failed_requests", "requeued_retries",
            "token_mismatched_requests", "pages_in_use_at_drain")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_spec_leg() -> dict:
    """The speculative-decoding trajectory sub-row: smoke-sized
    draft-and-verify summary (plain vs spec on the shared-prefix and
    multi-turn streams) from ``bench_serving.spec_stats``.
    BENCH_SERVING_SPEC=0 drops it; failure-isolated like its siblings
    — a broken spec layer yields {"error": ...} here, never a lost
    serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_SPEC", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_SPEC_SMOKE))
        _, summary = bench_serving.spec_stats()
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s", "acceptance_rate",
            "acceptance_p50", "acceptance_p99", "tokens_per_step",
            "tokens_per_step_plain", "multi_turn_acceptance_rate",
            "multi_turn_tokens_per_step", "token_mismatched_requests",
            "spec_k", "verify_traces")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_quant_leg() -> dict:
    """The quantized-KV trajectory sub-row: smoke-sized int8-capacity
    summary (bf16 oracle vs int8 engine at identical pool bytes —
    KV-bytes-per-token reduction, concurrency both modes, greedy
    token-match-rate) from ``bench_serving.quantized_kv_stats``.
    BENCH_SERVING_QUANT=0 drops it; failure-isolated like its siblings
    — a broken quant tier yields {"error": ...} here, never a lost
    serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_QUANT", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_QUANT_SMOKE))
        _, summary = bench_serving.quantized_kv_stats()
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s", "token_match_rate",
            "token_mismatched_requests", "kv_bytes_per_token",
            "kv_bytes_per_token_bf16", "kv_bytes_per_token_reduction_pct",
            "hbm_bytes_per_request", "hbm_bytes_per_request_bf16",
            "hbm_bytes_per_request_reduction_pct",
            "max_concurrent_requests", "max_concurrent_requests_bf16",
            "slots", "slots_bf16", "pool_mib", "quant_scale_absmax",
            "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_wquant_leg() -> dict:
    """The quantized-weights trajectory sub-row: smoke-sized
    int8-weights summary (bf16 oracle vs int8 weights vs int8 weights
    + int8 KV at identical geometry — weight-bytes reduction,
    bytes-per-param, HBM-bytes-per-request, greedy token-match-rate
    both quantized modes) from ``bench_serving.quantized_weights_
    stats``. BENCH_SERVING_WQUANT=0 drops it; failure-isolated like
    its siblings — a broken weight tier yields {"error": ...} here,
    never a lost serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_WQUANT", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_WQUANT_SMOKE))
        _, summary = bench_serving.quantized_weights_stats()
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s",
            "combined_tokens_per_s", "token_match_rate",
            "token_mismatched_requests", "combined_token_match_rate",
            "combined_token_mismatched_requests", "weight_mib",
            "weight_mib_bf16", "weight_bytes_reduction_pct",
            "bytes_per_param", "bytes_per_param_bf16",
            "hbm_bytes_per_request", "hbm_bytes_per_request_bf16",
            "hbm_bytes_per_request_reduction_pct",
            "quant_scale_absmax", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_async_leg() -> dict:
    """The async-heartbeat trajectory sub-row: smoke-sized
    dispatch-ahead summary (sync vs pipeline_depth=N on one engine —
    heartbeat wall per emitted token, duty cycle, tokens/s, bitwise
    exactness) from ``bench_serving.async_stats``.
    BENCH_SERVING_ASYNC=0 drops it; failure-isolated like its siblings
    — a broken pipelined beat yields {"error": ...} here, never a lost
    serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_ASYNC", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_ASYNC_SMOKE))
        _, summary = bench_serving.async_stats()
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s", "pipeline_depth",
            "heartbeat_wall_per_token_ms",
            "heartbeat_wall_per_token_ms_sync",
            "heartbeat_wall_per_token_improvement_pct",
            "duty_cycle", "duty_cycle_sync", "host_s_fraction",
            "discarded_inflight_tokens", "token_mismatched_requests",
            "compiled_programs", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_host_tier_leg() -> dict:
    """The hierarchical-KV trajectory sub-row: smoke-sized
    host-DRAM-tier summary (a prefix working set larger than the
    device pool — tier off vs sync-swap vs ASYNC swap-out: hit rate,
    chunks skipped, TTFT, the telemetry-wired admission-stall p50/p99
    sync vs async, swap traffic, bitwise exactness, plus the
    ``HOST_TIER_TP``-shard mesh-composition sub-leg's
    per-shard-record/token-exactness pins) from
    ``bench_serving.py --host-tier``. Runs as a SUBPROCESS like the
    tensor-parallel leg: the mesh sub-leg must force emulated CPU
    devices BEFORE any jax client initializes, and this process's
    backend is long since live. BENCH_SERVING_HOST_TIER=0 drops it;
    failure-isolated like its siblings — a broken (or timed-out)
    tier yields {"error": ...} here, never a lost serving (or
    ResNet) row."""
    if _env_int("BENCH_SERVING_HOST_TIER", "1") == 0:
        return {"skipped": True}
    try:
        import subprocess
        import sys

        root = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        # CPU + emulated devices for the mesh sub-leg; any exported
        # BENCH_SERVING_* knob still wins inside the child
        # (env-beats-smoke — the child applies its own smoke preset)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench_serving.py"),
             "--host-tier"],
            capture_output=True, text=True, env=env, cwd=root,
            timeout=600)
        lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
        summary = json.loads(lines[-1])      # guard contract: last line
        if "error" in summary:
            return {"error": summary["error"],
                    "transient": summary.get("transient", False)}
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s",
            "sync_swap_tokens_per_s",
            "prefix_hit_rate", "prefix_hit_rate_tier_off",
            "hit_rate_improved", "hit_rate_unchanged_vs_sync",
            "prefill_chunks_skipped",
            "prefill_chunks_skipped_tier_off",
            "prefill_chunks_skipped_pct", "ttft_p50_ms",
            "ttft_p50_ms_tier_off", "ttft_p99_ms",
            "ttft_p99_ms_tier_off", "ttft_improved",
            "admit_stall_p50_ms_sync", "admit_stall_p99_ms_sync",
            "admit_stall_p50_ms_async", "admit_stall_p99_ms_async",
            "admit_stall_p99_reduction_pct",
            "admit_stall_p50_reduction_pct", "admit_stall_reduced",
            "admit_stall_p50_reduced",
            "swap_join_waits", "hit_after_swap",
            "swapped_out_pages", "swapped_in_pages",
            "swap_verify_failed", "host_bytes",
            "prefix_working_set_pages", "pool_pages",
            "token_mismatched_requests", "mesh", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_router_leg() -> dict:
    """The replica-parallel trajectory sub-row: smoke-sized
    prefix-aware-router summary (1 replica vs BENCH_SERVING_REPLICAS,
    affinity vs random-routing control — aggregate tokens/s, p99 TTFT,
    prefix hit rate both policies, bitwise exactness) from
    ``bench_serving.replica_router_stats``. BENCH_SERVING_ROUTER=0
    drops it; failure-isolated like its siblings — a broken router
    yields {"error": ...} here, never a lost serving (or ResNet)
    row."""
    if _env_int("BENCH_SERVING_ROUTER", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_ROUTER_SMOKE))
        _, summary = bench_serving.replica_router_stats()
        return {k: summary[k] for k in (
            "value", "unit", "replicas", "baseline_tokens_per_s",
            "scaling_x", "ttft_p99_ms", "ttft_p99_ms_one_replica",
            "prefix_hit_rate", "prefix_hit_rate_random",
            "reused_tokens_per_request",
            "reused_tokens_per_request_random",
            "affinity_beats_random", "spills",
            "token_mismatched_requests", "compiled_programs", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_disagg_leg() -> dict:
    """The disaggregated-serving trajectory sub-row: smoke-sized
    prefill/decode role-split summary (one fleet over one shared host
    arena, colocated vs role-split with KV handoff — bystander TTFT
    p50/p99 both modes, the decode-replica heartbeat-tail isolation,
    handoff traffic with export/import p50/p99, zero re-prefills /
    zero leaked arena bytes, bitwise exactness) from
    ``bench_serving.disagg_stats``. BENCH_SERVING_DISAGG=0 drops it;
    failure-isolated like its siblings — a broken handoff layer
    yields {"error": ...} here, never a lost serving (or ResNet)
    row."""
    if _env_int("BENCH_SERVING_DISAGG", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_DISAGG_SMOKE))
        _, summary = bench_serving.disagg_stats()
        return {k: summary[k] for k in (
            "value", "unit", "replicas", "decode_replicas",
            "colocated_tokens_per_s",
            "ttft_bystander_p50_ms", "ttft_bystander_p50_ms_colocated",
            "ttft_bystander_p99_ms", "ttft_bystander_p99_ms_colocated",
            "decode_heartbeat_host_p99_ms",
            "decode_heartbeat_host_p99_ms_colocated",
            "decode_beat_tail_improved", "decode_host_p99_isolation_x",
            "decode_isolation", "handoffs", "handoff_bytes",
            "reprefills", "zero_reprefills_clean",
            "handoff_export_p50_ms", "handoff_export_p99_ms",
            "handoff_import_p50_ms", "handoff_import_p99_ms",
            "arena_bytes_after_drain", "token_mismatched_requests",
            "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_overload_leg() -> dict:
    """The SLO-scheduling trajectory sub-row: smoke-sized
    overload summary (the same seeded mixed-class stream at >1x slot
    capacity served FIFO vs SLO-aware on identical geometry —
    interactive TTFT p50/p99 both modes, per-class deadline-miss rate
    against one FIFO-calibrated threshold, goodput of met-deadline
    tokens, preempt/resume churn, bitwise exactness vs the FIFO
    serve) from ``bench_serving.overload_stats``.
    BENCH_SERVING_OVERLOAD=0 drops it; failure-isolated like its
    siblings — a broken SLO layer yields {"error": ...} here, never a
    lost serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_OVERLOAD", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_OVERLOAD_SMOKE))
        _, summary = bench_serving.overload_stats()
        return {k: summary[k] for k in (
            "value", "unit", "goodput_fifo",
            "tokens_per_s", "tokens_per_s_fifo",
            "ttft_interactive_p50_ms", "ttft_interactive_p50_ms_fifo",
            "ttft_interactive_p99_ms", "ttft_interactive_p99_ms_fifo",
            "deadline_miss_rate_interactive",
            "deadline_miss_rate_interactive_fifo",
            "ttft_p99_improved", "miss_rate_improved",
            "preemptions", "resumes", "resume_reprefills",
            "deadline_rejected", "token_exact_vs_fifo",
            "token_mismatched_requests", "deadline_pct_of_fifo_wall",
            "overload_factor", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_lora_leg() -> dict:
    """The multi-tenant LoRA trajectory sub-row: smoke-sized adapter
    summary (the mixed-tenant stream heterogeneously batched vs
    per-adapter sequential at identical geometry — tokens/s both
    modes + speedup_x, adapter churn + warm-bind rate, arena/host
    occupancy, zero recompiles after warmup, bitwise exactness
    between batch compositions) from ``bench_serving.lora_stats``.
    BENCH_SERVING_LORA=0 drops it; failure-isolated like its
    siblings — a broken adapter tier yields {"error": ...} here,
    never a lost serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_LORA", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_LORA_SMOKE))
        _, summary = bench_serving.lora_stats()
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s", "speedup_x",
            "token_mismatched_requests", "adapters", "rank",
            "arena_slots", "lora_hits", "lora_loads",
            "lora_evictions", "warm_bind_rate", "arena_bytes",
            "active_adapters", "compiled_programs",
            "recompiles_after_warmup", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_process_fleet_leg() -> dict:
    """The out-of-process fleet trajectory sub-row: smoke-sized
    process-fleet summary (1 worker vs BENCH_SERVING_REPLICAS
    separate OS processes behind the stdlib transport — aggregate
    tokens/s + scaling_x, the serving bench's one CPU-honest scaling
    column, p99 TTFT, prefix hit rate, rolling-restart timing, health
    counters, bitwise exactness) from
    ``bench_serving.process_fleet_stats``. BENCH_SERVING_FLEET=0
    drops it; failure-isolated like its siblings — a broken fleet
    (or a box that cannot spawn workers) yields {"error": ...} here,
    never a lost serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_FLEET", "1") == 0:
        return {"skipped": True}
    try:
        import bench_serving

        bench_serving._load_env(smoke=dict(_SERVING_FLEET_SMOKE))
        _, summary = bench_serving.process_fleet_stats()
        return {k: summary[k] for k in (
            "value", "unit", "workers", "baseline_tokens_per_s",
            "scaling_x", "scaling_honest_on_cpu", "ttft_p99_ms",
            "ttft_p99_ms_one_worker", "prefix_hit_rate",
            "reused_tokens_per_request", "affinity_hits", "spills",
            "worker_deaths", "hangs_detected", "restarts",
            "restart_wall_s", "restart_p50_s", "restart_max_s",
            "token_mismatched_requests", "model")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def _serving_tp_leg() -> dict:
    """The tensor-parallel trajectory sub-row: the bench_serving.py
    --tensor-parallel smoke (tp=1 vs BENCH_SERVING_TP-shard CPU device
    emulation: tokens/s, per-shard KV HBM bytes, collective inventory,
    token_mismatched_requests — expected 0). Runs as a SUBPROCESS, not
    in-process like its siblings: the leg must force the CPU backend
    with emulated devices BEFORE any jax client initializes, and this
    process's backend is long since live (on axon it is the one real
    TPU). BENCH_SERVING_TP=0 drops it; failure-isolated like its
    siblings — a broken (or timed-out) mesh layer yields
    {"error": ...} here, never a lost serving (or ResNet) row."""
    if _env_int("BENCH_SERVING_TP", "2") == 0:
        return {"skipped": True}
    try:
        import subprocess
        import sys

        root = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        # CPU emulation + smoke geometry; any exported BENCH_SERVING_*
        # knob still wins inside the child (env-beats-smoke)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "bench_serving.py"),
             "--tensor-parallel"],
            capture_output=True, text=True, env=env, cwd=root,
            timeout=600)
        lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
        summary = json.loads(lines[-1])      # guard contract: last line
        if "error" in summary:
            return {"error": summary["error"],
                    "transient": summary.get("transient", False)}
        return {k: summary[k] for k in (
            "value", "unit", "baseline_tokens_per_s", "tp",
            "hbm_bytes_per_shard", "hbm_bytes_per_shard_tp1",
            "hbm_bytes_per_shard_reduction_pct", "psums_per_program",
            "all_gathers_per_program", "token_mismatched_requests",
            "model", "emulated_devices")}
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the row must not die here
        return {"error": f"{type(e).__name__}: {e}"}


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from apex_tpu import amp, pyprof
    from apex_tpu.amp.policy import resolve_policy
    from apex_tpu.models.resnet import create_model

    env = _read_env()
    BATCH, IMAGE, WARMUP, STEPS = (env["BATCH"], env["IMAGE"],
                                   env["WARMUP"], env["STEPS"])
    WINDOWS, TRACE_WINDOWS = env["WINDOWS"], env["TRACE_WINDOWS"]
    ACCUM_STEPS = env["ACCUM_STEPS"]

    # APEX_TPU_TELEMETRY=run.jsonl|stdout streams per-step telemetry
    # (loss/grad_norm/scaler trajectory + step_time_s) from inside the
    # jitted step; unset costs nothing (telemetry baked out at trace time)
    from apex_tpu import telemetry
    tele = telemetry.from_env()

    model = create_model("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    x_init = jnp.ones((BATCH, IMAGE, IMAGE, 3), jnp.float32)
    variables = model.init(rng, x_init, train=True)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})

    policy = resolve_policy(opt_level="O2", loss_scale="dynamic")
    optimizer = optax.sgd(optax.constant_schedule(0.1), momentum=0.9)

    def loss_fn(p, model_state, batch):
        images, labels = batch
        logits, updated = model.apply(
            {"params": p, "batch_stats": model_state}, images, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            jnp.asarray(logits, jnp.float32), labels).mean()
        return loss, updated["batch_stats"]

    if ACCUM_STEPS < 1 or BATCH % ACCUM_STEPS:
        raise SystemExit(f"BENCH_ACCUM_STEPS={ACCUM_STEPS} must be >= 1 "
                         f"and divide BENCH_BATCH={BATCH}")
    init_fn, step_fn = amp.make_train_step(loss_fn, optimizer, policy,
                                           with_model_state=True,
                                           telemetry=tele is not None,
                                           accum_steps=ACCUM_STEPS)
    state = init_fn(params, batch_stats)
    jit_step = jax.jit(step_fn, donate_argnums=(0,))

    images = jax.random.normal(rng, (BATCH, IMAGE, IMAGE, 3), jnp.float32)
    labels = jax.random.randint(rng, (BATCH,), 0, 1000)
    batch = (images, labels)
    batch = amp.to_microbatches(batch, ACCUM_STEPS)

    for _ in range(WARMUP):
        state, _ = jit_step(state, batch)
    jax.block_until_ready(jax.tree_util.tree_leaves(state.params)[0])

    wall_rates = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(STEPS):
            state, metrics = jit_step(state, batch)
        jax.block_until_ready(metrics["loss"])
        dt = time.perf_counter() - t0
        wall_rates.append(BATCH * STEPS / dt)

    if not wall_rates:
        raise SystemExit("BENCH_WINDOWS must be >= 1")
    wall_rates.sort()
    wall_value = _median(wall_rates)
    wall_spread = ((wall_rates[-1] - wall_rates[0]) / wall_value
                   if wall_value else 0.0)

    # Device-anchored windows: each capture's device-lane span times the
    # silicon (bubbles included). Falls back to wall clock when the
    # backend writes no device lanes (e.g. CPU smoke runs).
    dev_rates, duty = [], []
    for _ in range(TRACE_WINDOWS):
        with tempfile.TemporaryDirectory() as td:
            with pyprof.trace(td):
                for _ in range(STEPS):
                    state, metrics = jit_step(state, batch)
                jax.block_until_ready(metrics["loss"])
            try:
                d = pyprof.device_busy(td)
            except FileNotFoundError:
                d = {"span_ms": 0.0, "busy_ms": 0.0}
        if d["span_ms"] > 0:
            dev_rates.append(BATCH * STEPS / (d["span_ms"] / 1e3))
            duty.append(d["busy_ms"] / d["span_ms"])

    dev_rates.sort()
    if dev_rates:
        basis, rates = "device_trace", dev_rates
    else:
        basis, rates = "wall_clock", wall_rates
    img_per_sec = _median(rates)
    spread = (rates[-1] - rates[0]) / img_per_sec if img_per_sec else 0.0
    flop_per_img = RESNET50_TRAIN_FLOP_PER_IMG_224 * (IMAGE / 224.0) ** 2
    mfu = img_per_sec * flop_per_img / peak_flops(jax.devices()[0])
    out = {
        "metric": METRIC,
        "value": round(img_per_sec, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(img_per_sec / V100_O2_IMG_PER_SEC, 4),
        "basis": basis,
        "windows": [round(r, 2) for r in rates],
        "min": round(rates[0], 2),
        "spread_pct": round(100.0 * spread, 2),
        "mfu_est": round(mfu, 4),
        "implausible": bool(mfu > 1.0),
        "device_kind": getattr(jax.devices()[0], "device_kind", "unknown"),
        "accum_steps": ACCUM_STEPS,
        "wall_clock": {
            "value": round(wall_value, 2),
            "windows": [round(r, 2) for r in wall_rates],
            "spread_pct": round(100.0 * wall_spread, 2),
        },
    }
    if duty:
        out["duty_cycle"] = round(_median(duty), 4)
    if env["SERVING_LEG"]:
        # the serving trajectory row (tokens/s + HBM-bytes-per-request
        # finally land in the tracked BENCH_* JSON, per ROADMAP)
        out["serving"] = _serving_leg()
    if tele is not None:
        jax.effects_barrier()      # flush in-flight step callbacks
        tele.emit_snapshot()
        tele.close()
    print(json.dumps(out))


if __name__ == "__main__":
    # crash contract: any failure still ends in one parseable JSON line
    # ({"metric", "error", "rc": 1}) — no more "parsed": null bench rows.
    # Arming the guard must itself be failure-proof: if importing the
    # telemetry package dies (broken env, half-installed deps), fall back
    # to a stdlib-only failure line so the contract holds even then.
    try:
        from apex_tpu.telemetry import guard_bench_main
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the contract is total
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)
        sys.stdout.write(json.dumps({
            "metric": METRIC, "error": f"{type(e).__name__}: {e}",
            "rc": 1, "transient": False}) + "\n")
        sys.stdout.flush()
        raise SystemExit(1)
    guard_bench_main(main, METRIC)
