"""Kernel microbenchmarks: Pallas kernels vs their jnp/XLA compositions on
the SAME backend, at LM-production shapes (VERDICT round-1 item 1b).

Every fused kernel family gets a measured same-device speedup (or a
documented "XLA wins, fallback kept" verdict) — the evidence tier backing
the SURVEY N2/N4/N8/N10/N11 kernel list. Results are recorded in
BASELINE.md. Run on the real chip:

    python bench_kernels.py            # all suites
    python bench_kernels.py flash ln   # a subset

Prints one JSON line per row:
  {"bench": ..., "shape": ..., "pallas_ms": ..., "xla_ms": ...,
   "speedup": ...}
Absolute times on the axon emulator are dispatch-dominated; the speedup
column (same backend, same harness) is the meaningful number.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp


def timeit(fn, *args, warmup=2, steps=10):
    """Per-step DEVICE time of a jitted callable, ms.

    Round 5: anchored on the profiler's device-lane occupancy
    (pyprof.device_busy busy_ms / steps) — wall clock through the axon
    tunnel times dispatch, not silicon, which made every pallas-vs-XLA
    speedup column dispatch-dominated noise (both sides ~the same
    round-trip). Occupancy rather than span because microkernel steps are
    far shorter than the tunnel's enqueue latency: the device sits idle
    between iterations, and that idle is the host's fault, not the
    kernel's. Falls back to median wall time on host-only backends."""
    import tempfile

    from apex_tpu import pyprof

    fn = jax.jit(fn)
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    with tempfile.TemporaryDirectory() as td:
        with pyprof.trace(td):
            for _ in range(steps):
                out = fn(*args)
            jax.block_until_ready(out)
        try:
            d = pyprof.device_busy(td)
        except FileNotFoundError:
            d = {"busy_ms": 0.0}
    if d["busy_ms"] > 0:
        return d["busy_ms"] / steps
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    times.sort()
    return times[len(times) // 2]


HBM_GBPS = 819.0        # v5e HBM bandwidth
PEAK_TFLOPS = 197.0     # v5e bf16 (394 is the int8 figure)


def row(bench, shape, pallas_ms, xla_ms, gbytes=None, gflops=None):
    """One result row, self-describing about plausibility: if the measured
    time implies bandwidth/compute beyond the chip's physical limits the
    row is dispatch-dominated (the axon emulator does not model HBM/MXU
    timing) and its speedup column is NOT meaningful.

    ``roofline_ms`` is the analytic floor on real v5e silicon —
    max(bytes / HBM bandwidth, flops / bf16 peak) — so the first
    real-silicon session reads achieved-vs-roofline immediately
    (``pct_of_roofline`` = roofline/measured; 100 = at the roofline,
    >120 = the clock is non-physical, same condition as ``implausible``)."""
    out = {
        "bench": bench, "shape": shape,
        "pallas_ms": round(pallas_ms, 3), "xla_ms": round(xla_ms, 3),
        "speedup": round(xla_ms / pallas_ms, 2),
    }
    implausible = False
    roofline_s = 0.0
    if gbytes is not None:
        bw = gbytes / (pallas_ms / 1e3)
        out["implied_gbps"] = round(bw, 1)
        roofline_s = max(roofline_s, gbytes / HBM_GBPS)
        implausible |= bw > 1.2 * HBM_GBPS
    if gflops is not None:
        tf = gflops / 1e3 / (pallas_ms / 1e3)
        out["implied_tflops"] = round(tf, 1)
        roofline_s = max(roofline_s, gflops / 1e3 / PEAK_TFLOPS)
        implausible |= tf > 1.2 * PEAK_TFLOPS
    if roofline_s > 0.0:
        out["roofline_ms"] = round(roofline_s * 1e3, 3)
        out["pct_of_roofline"] = round(100.0 * roofline_s * 1e3 / pallas_ms,
                                       1)
    out["implausible"] = bool(implausible)
    print(json.dumps(out), flush=True)


# ------------------------------------------------------------------ flash
def bench_flash():
    from apex_tpu.kernels.flash_attention import flash_attention, \
        mha_reference

    for b, h, s, d in ((8, 8, 2048, 128), (2, 8, 8192, 128)):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
                   for kk in ks)

        def fwd_k(q, k, v):
            return flash_attention(q, k, v, causal=True)

        def fwd_x(q, k, v):
            return mha_reference(q, k, v, causal=True, scale=d ** -0.5)

        # causal fwd: 2 matmuls x 2*b*h*s^2*d flops, halved by tile skip
        gf = 2 * 2 * b * h * s * s * d / 2 / 1e9
        row("flash_fwd_causal", f"b{b} h{h} s{s} d{d}",
            timeit(fwd_k, q, k, v), timeit(fwd_x, q, k, v), gflops=gf)

        def bwd_k(q, k, v):
            return jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, causal=True)
                    .astype(jnp.float32)),
                argnums=(0, 1, 2))(q, k, v)

        def bwd_x(q, k, v):
            return jax.grad(
                lambda q, k, v: jnp.sum(
                    mha_reference(q, k, v, causal=True, scale=d ** -0.5)
                    .astype(jnp.float32)),
                argnums=(0, 1, 2))(q, k, v)

        row("flash_fwd_bwd_causal", f"b{b} h{h} s{s} d{d}",
            timeit(bwd_k, q, k, v), timeit(bwd_x, q, k, v),
            gflops=3.5 * gf)


# --------------------------------------------------------------------- ln
def bench_ln():
    from apex_tpu.kernels.layer_norm import layer_norm, layer_norm_reference

    for rows_, hidden in ((8192, 4096), (4096, 8192)):
        x = jax.random.normal(jax.random.PRNGKey(1), (rows_, hidden),
                              jnp.bfloat16)
        w = jnp.ones((hidden,))
        b = jnp.zeros((hidden,))

        gb = 2 * rows_ * hidden * 2 / 1e9      # read x + write y, bf16
        row("layer_norm_fwd", f"{rows_}x{hidden}",
            timeit(layer_norm, x, w, b),
            timeit(layer_norm_reference, x, w, b), gbytes=gb)

        def bwd_k(x, w, b):
            return jax.grad(lambda x, w, b: jnp.sum(
                layer_norm(x, w, b).astype(jnp.float32)),
                argnums=(0, 1, 2))(x, w, b)

        def bwd_x(x, w, b):
            return jax.grad(lambda x, w, b: jnp.sum(
                layer_norm_reference(x, w, b).astype(jnp.float32)),
                argnums=(0, 1, 2))(x, w, b)

        row("layer_norm_fwd_bwd", f"{rows_}x{hidden}",
            timeit(bwd_k, x, w, b), timeit(bwd_x, x, w, b),
            gbytes=2.5 * gb)


# ---------------------------------------------------------------- xentropy
def bench_xentropy():
    from apex_tpu.kernels.xentropy import (softmax_cross_entropy_loss,
                                           xent_reference)

    n, v = 8192, 32768
    logits = jax.random.normal(jax.random.PRNGKey(2), (n, v), jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(3), (n,), 0, v)

    gb = n * v * 2 / 1e9                       # logits read, bf16
    row("xentropy_fwd", f"{n}x{v}",
        timeit(lambda l: softmax_cross_entropy_loss(l, labels), logits),
        timeit(lambda l: xent_reference(l, labels), logits), gbytes=gb)

    def bwd_k(l):
        return jax.grad(lambda l: jnp.sum(
            softmax_cross_entropy_loss(l, labels)))(l)

    def bwd_x(l):
        return jax.grad(lambda l: jnp.sum(xent_reference(l, labels)))(l)

    row("xentropy_fwd_bwd", f"{n}x{v}",
        timeit(bwd_k, logits), timeit(bwd_x, logits), gbytes=3 * gb)


# ------------------------------------------------------------ lm head
def bench_lm_head():
    """Fused LM-head+CE vs the composed tail (head GEMM + fused CE
    kernel — the exact pair the recipe's --fused-head replaces), both
    differentiated through x and the head weight."""
    from apex_tpu.kernels.lm_head_loss import lm_head_xentropy
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss

    n, h, v = 8184, 768, 32768
    x = jax.random.normal(jax.random.PRNGKey(4), (n, h), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(5), (v, h), jnp.float32) * 0.02
    y = jax.random.randint(jax.random.PRNGKey(6), (n,), 0, v)

    def fused(x, w):
        return jax.grad(lambda x, w: lm_head_xentropy(
            x, w, y, compute_dtype=jnp.bfloat16).mean(),
            argnums=(0, 1))(x, w)

    def composed(x, w):
        def loss(x, w):
            logits = jax.lax.dot_general(
                x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            return softmax_cross_entropy_loss(logits, y).mean()
        return jax.grad(loss, argnums=(0, 1))(x, w)

    # compute floor: 4 GEMM-equivalents (fwd + recomputed fwd + dW + dx)
    gf = 4 * 2 * n * h * v / 1e9
    row("lm_head_fused_vs_composed_f_b", f"{n}x{h} V{v}",
        timeit(fused, x, w), timeit(composed, x, w), gflops=gf)


# ------------------------------------------------------------ multi-tensor
def bench_adam():
    # big-tensor case: few large leaves (optax's per-leaf chain is already
    # one fused elementwise op per leaf here — the launch-count win is small)
    _bench_adam_tree(
        "fused_adam_step", {
            f"w{i}": jax.random.normal(jax.random.PRNGKey(i),
                                       (4096, 1528), jnp.float32)
            for i in range(20)})
    # many-small-tensors case: the scenario multi_tensor_apply exists for
    # (120 leaves from 256 to ~147K elements — conv-net-like sizes)
    leaves = {}
    kidx = 0
    for i in range(40):
        for shape in ((256,), (64, 64), (3, 3, 128, 128)):
            leaves[f"p{kidx}"] = jax.random.normal(
                jax.random.PRNGKey(kidx), shape, jnp.float32)
            kidx += 1
    _bench_adam_tree("fused_adam_step_many_small", leaves)


def _bench_adam_tree(name, leaves):
    """Both fused_adam layouts vs the optax.adamw baseline. The row's
    pallas_ms column is the DEFAULT layout (tree, round 5 — per-leaf
    state, XLA-fused); a second row prices the round-1..4 flat
    superbuffer so its flatten/unflatten cost stays on the record."""
    import optax
    from apex_tpu.optimizers.fused_adam import fused_adam
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-3, p.dtype), leaves)

    tx_o = optax.adamw(1e-3, weight_decay=0.01)
    st_o = tx_o.init(leaves)

    def step_optax(p, s):
        u, s2 = tx_o.update(grads, s, p)
        return optax.apply_updates(p, u), s2

    optax_ms = timeit(step_optax, leaves, st_o)
    n = sum(x.size for x in jax.tree_util.tree_leaves(leaves))
    gb = 7 * n * 4 / 1e9                       # read p,m,v,g; write p,m,v
    for layout in ("tree", "flat"):
        tx_f = fused_adam(1e-3, weight_decay=0.01, layout=layout)
        st_f = tx_f.init(leaves)

        def step_fused(p, s):
            u, s2 = tx_f.update(grads, s, p)
            return optax.apply_updates(p, u), s2

        row(f"{name}_{layout}",
            f"{n / 1e6:.1f}M params, {len(leaves)} tensors",
            timeit(step_fused, leaves, st_f), optax_ms, gbytes=gb)


# ---------------------------------------------------------- causal softmax
def bench_causal_softmax():
    from apex_tpu.kernels.causal_softmax import (causal_softmax,
                                                 causal_softmax_reference)

    x = jax.random.normal(jax.random.PRNGKey(4), (16, 2048, 2048),
                          jnp.bfloat16)
    gb = 2 * 16 * 2048 * 2048 * 2 / 1e9
    row("causal_softmax_fwd", "16x2048x2048",
        timeit(functools.partial(causal_softmax, scale=0.125), x),
        timeit(functools.partial(causal_softmax_reference, scale=0.125), x),
        gbytes=gb)


# ---------------------------------------------------------- masked softmax
def bench_masked_softmax():
    from apex_tpu.kernels.masked_softmax import (masked_softmax,
                                                 masked_softmax_reference)

    b, h, sq, sk = 4, 8, 1024, 1024       # BERT-large-ish padded block
    x = jax.random.normal(jax.random.PRNGKey(6), (b, h, sq, sk),
                          jnp.bfloat16)
    m = jax.random.bernoulli(jax.random.PRNGKey(7), 0.3, (b, 1, sq, sk))
    m = m.at[..., 0].set(False)
    gb = 2 * b * h * sq * sk * 2 / 1e9 + b * sq * sk / 1e9
    row("masked_softmax_fwd", f"{b}x{h}x{sq}x{sk} mask b1",
        timeit(functools.partial(masked_softmax, scale=0.125), x, m),
        timeit(functools.partial(masked_softmax_reference, scale=0.125),
               x, m),
        gbytes=gb)


# ------------------------------------------------------------- group norm
def bench_group_norm():
    from apex_tpu.kernels.group_norm import (group_norm_nhwc,
                                             group_norm_reference)

    n, h, w, c = 8, 64, 64, 512           # diffusion UNet mid-block shape
    x = jax.random.normal(jax.random.PRNGKey(5), (n, h, w, c), jnp.bfloat16)
    g = jnp.ones((c,))
    b = jnp.zeros((c,))
    gb = 2 * n * h * w * c * 2 / 1e9
    row("group_norm_silu_fwd", f"{n}x{h}x{w}x{c} g32",
        timeit(lambda x: group_norm_nhwc(x, 32, g, b, act="silu"), x),
        timeit(lambda x: group_norm_reference(x, 32, g, b, act="silu"), x),
        gbytes=gb)


SUITES = {"flash": bench_flash, "ln": bench_ln, "xentropy": bench_xentropy,
          "lm_head": bench_lm_head,
          "adam": bench_adam, "causal_softmax": bench_causal_softmax,
          "masked_softmax": bench_masked_softmax,
          "group_norm": bench_group_norm}


# ------------------------------------------------------------------ sweep
# Block-shape sweep (VERDICT round-2 item 4): per kernel, time each
# candidate block config on THIS device and emit the best as a tuned-
# overrides JSON consumable by apex_tpu.kernels.vmem.load_overrides /
# APEX_TPU_TUNED. On the axon emulator the ranking carries no signal
# (dispatch-dominated; each row self-flags) — the harness exists so the
# first real-silicon session is one command + one file.

def _sweep_knob(results, key, candidates, measure):
    """Time ``measure()`` under each override value; record the best."""
    from apex_tpu.kernels import vmem

    best_v, best_ms = None, float("inf")
    for v in candidates:
        vmem.set_override(key, v)
        # overrides are read at TRACE time; jit caches key on function
        # identity + avals, so a reused callable (e.g. layer_norm itself)
        # would silently time the first candidate's trace for all values
        jax.clear_caches()
        try:
            ms = measure()
        except Exception as e:  # a config Mosaic rejects is a data point
            print(json.dumps({"sweep": key, "value": v,
                              "error": str(e)[:120]}), flush=True)
            continue
        finally:
            vmem.remove_override(key)  # other pinned knobs stay
        print(json.dumps({"sweep": key, "value": v, "ms": round(ms, 3)}),
              flush=True)
        if ms < best_ms:
            best_v, best_ms = v, ms
    if best_v is not None:
        results[key] = best_v


def sweep(out_path="tuned_blocks.json"):
    from apex_tpu.kernels import vmem

    # sweep from the HEURISTIC baseline: block the packaged per-device
    # tuned file from auto-loading (and drop anything already loaded) so
    # re-tuning on a device kind that ships a file measures the same
    # regime the original sweep did — not candidates layered on top of
    # the previous answers
    vmem._auto_load_done = True
    vmem.clear_overrides()

    results = {}

    # flash attention q/k blocks at the LM shape
    from apex_tpu.kernels.flash_attention import flash_attention
    b, h, s, d = 4, 8, 2048, 128
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
               for kk in ks)

    def flash_ms():
        return timeit(lambda q, k, v: flash_attention(q, k, v, causal=True),
                      q, k, v)

    def flash_bwd_ms():
        def bwd(q, k, v):
            return jax.grad(
                lambda q, k, v: jnp.sum(
                    flash_attention(q, k, v, causal=True)
                    .astype(jnp.float32)), argnums=(0, 1, 2))(q, k, v)
        return timeit(bwd, q, k, v)

    _sweep_knob(results, "flash.block_q", (64, 128, 256, 512), flash_ms)
    if "flash.block_q" in results:
        vmem.set_override("flash.block_q", results["flash.block_q"])
    # block_k is lane-aligned to 128 (values below clamp up — see
    # flash_attention._resolve_blocks), so 64 would duplicate 128
    _sweep_knob(results, "flash.block_k", (128, 256, 512, 1024), flash_ms)
    # backward-specific blocks (flash.bwd_block_q/_k; consulted only when
    # dropout is off — the fwd mask seeds can't replay on another
    # geometry), swept with the fwd bests pinned
    for k_, v_ in results.items():
        vmem.set_override(k_, v_)
    _sweep_knob(results, "flash.bwd_block_q", (64, 128, 256, 512),
                flash_bwd_ms)
    if "flash.bwd_block_q" in results:
        vmem.set_override("flash.bwd_block_q", results["flash.bwd_block_q"])
    _sweep_knob(results, "flash.bwd_block_k", (128, 256, 512, 1024),
                flash_bwd_ms)
    vmem.clear_overrides()

    # layer norm row block
    from apex_tpu.kernels.layer_norm import layer_norm
    x = jax.random.normal(jax.random.PRNGKey(1), (8192, 4096), jnp.bfloat16)
    w, bb = jnp.ones((4096,)), jnp.zeros((4096,))
    _sweep_knob(results, "layer_norm.block_rows", (16, 64, 128, 256, 512),
                lambda: timeit(layer_norm, x, w, bb))

    # xentropy row block (vocab-heavy rows)
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    logits = jax.random.normal(jax.random.PRNGKey(2), (4096, 32768),
                               jnp.bfloat16)
    labels = jax.random.randint(jax.random.PRNGKey(3), (4096,), 0, 32768)
    _sweep_knob(results, "xentropy.block_rows", (8, 16, 32, 64),
                lambda: timeit(
                    lambda l: softmax_cross_entropy_loss(l, labels), logits))

    # multi-tensor superbuffer rows
    from apex_tpu.optimizers.fused_adam import fused_adam
    import optax
    leaves = {f"w{i}": jax.random.normal(jax.random.PRNGKey(i),
                                         (1024, 1528), jnp.float32)
              for i in range(20)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-3, p.dtype), leaves)
    # layout="flat": multi_tensor.block_rows is read only inside the
    # superbuffer Pallas kernel — the tree default never consults it
    tx = fused_adam(1e-3, weight_decay=0.01, layout="flat")
    st = tx.init(leaves)

    def adam_ms():
        def step(p, s):
            u, s2 = tx.update(grads, s, p)
            return optax.apply_updates(p, u), s2
        return timeit(step, leaves, st)

    _sweep_knob(results, "multi_tensor.block_rows", (64, 128, 256, 512),
                adam_ms)

    # causal softmax q block
    from apex_tpu.kernels.causal_softmax import causal_softmax
    xs = jax.random.normal(jax.random.PRNGKey(4), (8, 2048, 2048),
                           jnp.bfloat16)
    _sweep_knob(results, "causal_softmax.block_q", (32, 64, 128, 256, 512),
                lambda: timeit(
                    functools.partial(causal_softmax, scale=0.125), xs))

    # masked softmax q block (v5e: 128->256 closed its gap to XLA parity)
    from apex_tpu.kernels.masked_softmax import masked_softmax
    xm = jax.random.normal(jax.random.PRNGKey(6), (4, 8, 1024, 1024),
                           jnp.bfloat16)
    mm = jax.random.bernoulli(jax.random.PRNGKey(7), 0.9, (4, 1, 1024, 1024))
    _sweep_knob(results, "masked_softmax.block_q", (32, 64, 128, 256, 512),
                lambda: timeit(
                    functools.partial(masked_softmax, scale=0.125), xm, mm))

    # group norm spatial blocks — fwd and bwd separately (on v5e they
    # want opposite extremes: fwd 1024, bwd 128)
    from apex_tpu.kernels.group_norm import group_norm_nhwc
    xg = jax.random.normal(jax.random.PRNGKey(8), (8, 64, 64, 512),
                           jnp.bfloat16)
    gg, gb = jnp.ones((512,)), jnp.zeros((512,))
    _sweep_knob(results, "group_norm.block_spatial",
                (128, 256, 512, 1024, 2048),
                lambda: timeit(lambda x: group_norm_nhwc(
                    x, 32, gg, gb, act="silu"), xg))

    def gn_bwd_ms():
        def bwd(x, g_, b_):
            return jax.grad(lambda x, g_, b_: jnp.sum(
                group_norm_nhwc(x, 32, g_, b_, act="silu")
                .astype(jnp.float32)), argnums=(0, 1, 2))(x, g_, b_)
        return timeit(bwd, xg, gg, gb)

    _sweep_knob(results, "group_norm.bwd_block_spatial",
                (64, 128, 256, 512), gn_bwd_ms)

    with open(out_path, "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)
    print(json.dumps({"sweep_best": results, "written": out_path}),
          flush=True)


def main(argv):
    if argv and argv[0] == "--sweep":
        out = argv[1] if len(argv) > 1 else "tuned_blocks.json"
        print(json.dumps({"device": str(jax.devices()[0]),
                          "backend": jax.default_backend()}), flush=True)
        sweep(out)
        return
    names = argv or list(SUITES)
    bad = [n for n in names if n not in SUITES]
    if bad:
        raise SystemExit(f"unknown suite(s) {', '.join(map(repr, bad))}; "
                         f"pick from {', '.join(sorted(SUITES))}")
    print(json.dumps({"device": str(jax.devices()[0]),
                      "backend": jax.default_backend()}), flush=True)
    for name in names:
        SUITES[name]()


if __name__ == "__main__":
    # crash contract: any failure still ends in one parseable JSON
    # line ({"metric", "error", "rc": 1}) instead of a bare traceback
    from apex_tpu.telemetry import guard_bench_main
    guard_bench_main(lambda: main(sys.argv[1:]), "bench_kernels")
