"""Compiler-priced memory contracts at production shapes.

The counterpart of bench_kernels.py for the evidence the emulator's clock
cannot produce (VERDICT round-3 item 1): each row lowers the SAME
computation with the Pallas kernel and with the jnp/XLA composition,
compiles both on the attached backend (nothing executes — abstract avals,
zero device allocation), and prints the peak-memory delta certified by
XLA buffer assignment. Run on the TPU backend (the CPU backend's
memory_analysis excludes its temp arena and prices nothing):

    python bench_memory.py             # all contracts
    python bench_memory.py xentropy    # a subset

One JSON line per row: {"contract", "shape", "fused_peak_bytes",
"composed_peak_bytes", "saved_peak_bytes", "theory_bytes", "vs_theory"}.
``theory_bytes`` is the analytic size of the buffer the contract says the
fused kernel never materializes (reference claims: xentropy_kernel.cu
bprop-in-fprop — no [N, V] softmax residual; fmhalib — no O(s^2)
probability buffer). The contract setups are shared with the asserting
tests (tests/tpu/test_memory_contracts_on_silicon.py) via
apex_tpu.utils.memory_report, so the asserted and the reported contract
cannot drift; this tool produces the BASELINE.md table at real shapes.
"""

from __future__ import annotations

import json
import sys

import jax
import jax.numpy as jnp

S = jax.ShapeDtypeStruct


def emit(row, shape):
    row["shape"] = shape
    for k in ("fused_peak_bytes", "composed_peak_bytes",
              "saved_peak_bytes", "theory_bytes"):
        if k in row:
            row[k + "_mb"] = round(row[k] / 2**20, 1)
    print(json.dumps(row), flush=True)


def bench_xentropy():
    from apex_tpu.utils.memory_report import (price_contract,
                                              xentropy_contract)

    for n, v in ((8192, 32768), (4096, 50304)):
        fused, composed, avals, theory = xentropy_contract(n, v)
        emit(price_contract("xentropy_fwd_bwd", fused, composed, avals,
                            theory_bytes=theory), f"{n}x{v}")


def bench_lm_head():
    from apex_tpu.utils.memory_report import (lm_head_contract,
                                              price_contract)

    for n, h, v in ((8184, 768, 32768), (8184, 768, 50257)):
        fused, composed, avals, theory = lm_head_contract(n, h, v)
        emit(price_contract("lm_head_xentropy_fwd_bwd", fused, composed,
                            avals, theory_bytes=theory), f"{n}x{h}x{v}")


def bench_flash():
    from apex_tpu.utils.memory_report import flash_contract, price_contract

    d = 128
    for b, h, s in ((2, 8, 2048), (1, 8, 4096)):
        fused, composed, avals, theory = flash_contract(b, h, s, d,
                                                        with_bwd=True)
        emit(price_contract("flash_fwd_bwd", fused, composed, avals,
                            theory_bytes=theory), f"b{b} h{h} s{s} d{d}")

    for b, h, s in ((1, 8, 8192),):
        fused, composed, avals, theory = flash_contract(b, h, s, d,
                                                        with_bwd=False)
        emit(price_contract("flash_fwd", fused, composed, avals,
                            theory_bytes=theory), f"b{b} h{h} s{s} d{d}")


def bench_fused_softmax():
    """Honest rows: the N8 kernels' contract is HALF I/O (bf16 storage,
    per-tile fp32 math), not peak memory. Their custom_vjp saves the
    bf16 probs — exactly the reference's saved softmax_results
    (apex/csrc/megatron/scaled_*_softmax_cuda.cu backward reads them) —
    while XLA's composed path REMATERIALIZES the softmax into the
    backward, keeping ~0 residual. At the module boundary the fused rows
    therefore price NEGATIVE (reference-parity residuals, not a win);
    the bandwidth win is a time quantity the emulator cannot measure."""
    from apex_tpu.utils.memory_report import (causal_softmax_contract,
                                              masked_softmax_contract,
                                              price_contract)

    note = ("saves bf16 probs like the reference backward; XLA "
            "rematerializes instead - peak delta is an honest negative, "
            "the contract is I/O not residency")
    for b, h, s in ((8, 16, 1024), (4, 16, 2048)):
        fused, composed, avals, theory = causal_softmax_contract(
            b, h, s, with_bwd=True)
        row = price_contract("causal_softmax_fwd_bwd", fused, composed,
                             avals, theory_bytes=theory)
        row["note"] = note
        emit(row, f"b{b} h{h} s{s}")
        fused, composed, avals, theory = masked_softmax_contract(
            b, h, s, with_bwd=True)
        row = price_contract("masked_softmax_fwd_bwd", fused, composed,
                             avals, theory_bytes=theory)
        row["note"] = note
        emit(row, f"b{b} h{h} s{s}")


def bench_remat():
    from apex_tpu.utils.memory_report import (lm_step_remat_contract,
                                              price_contract,
                                              remat_mlp_contract)

    n_layers, n, hdim = 12, 2048, 1024
    plain_fn, remat_fn, avals, theory = remat_mlp_contract(n_layers, n,
                                                           hdim)
    # fused = checkpointed, composed = plain autodiff
    emit(price_contract("remat_activation_memory", remat_fn, plain_fn,
                        avals, theory_bytes=theory),
         f"L{n_layers} n{n} h{hdim} (jax.checkpoint per block)")

    # the integrated row: the LM recipe's COMPLETE amp-O2 train step
    # with its own --remat flag on vs off
    size, vocab, seq, batch = "small", 32768, 512, 8
    remat_step, plain_step, avals, theory = lm_step_remat_contract(
        size, vocab, seq, batch)
    emit(price_contract("lm_train_step_remat", remat_step, plain_step,
                        avals, theory_bytes=theory),
         f"{size} v{vocab} s{seq} b{batch} (examples/lm --remat)")


def bench_layer_norm():
    """Honest negative row: LN claims fusion, not memory. At standalone
    microbench shapes the pallas_call boundary even COSTS bytes (the
    sum-loss cotangent must materialize as a real HBM buffer where XLA
    would have fused it away); in a real model that cotangent exists
    anyway. Recorded so BASELINE.md can say it, not hide it."""
    from apex_tpu.kernels.layer_norm import layer_norm, layer_norm_reference
    from apex_tpu.utils.memory_report import price_contract

    n, hdim = 8192, 4096
    avals = [S((n, hdim), jnp.bfloat16), S((hdim,), jnp.float32),
             S((hdim,), jnp.float32)]
    row = price_contract(
        "layer_norm_fwd_bwd (no memory contract claimed)",
        jax.value_and_grad(lambda x, w, b: jnp.sum(
            layer_norm(x, w, b).astype(jnp.float32)), argnums=(0, 1, 2)),
        jax.value_and_grad(lambda x, w, b: jnp.sum(
            layer_norm_reference(x, w, b).astype(jnp.float32)),
            argnums=(0, 1, 2)),
        avals)
    emit(row, f"{n}x{hdim}")

    # round 5: the answer to that negative — apex's memory_efficient
    # flag. Priced fused-vs-fused on a mid-graph input (matmul producer):
    # "fused" = memory_efficient (save y), "composed" = default (save x).
    from apex_tpu.utils.memory_report import ln_memory_efficient_contract

    me, default, avals_me, theory = ln_memory_efficient_contract(
        n, 2048, n_layers=4)
    row = price_contract("layer_norm_memory_efficient_vs_default",
                         me, default, avals_me, theory_bytes=theory)
    row["note"] = ("saved = default-peak - memory_efficient-peak over a "
                   "4-layer pre-LN stack (x <- LN(x) @ W); theory = the "
                   "3 droppable [N,H] bf16 input residuals (apex "
                   "memory_efficient parity)")
    emit(row, f"L4 {n}x2048 (pre-LN stack)")


def bench_configs():
    """Driver configs 2 and 4 at production shape (VERDICT r4 missing
    #4): the COMPLETE north-star train steps, compile-only. No
    fused/composed pair here — the row is peak vs the static state
    floor; the difference is the activation/workspace residency XLA
    schedules for the step."""
    from apex_tpu.utils.memory_report import (bert_large_lamb_step,
                                              compiled_memory,
                                              resnet50_o2_ddp_step)

    fn, avals, floor = resnet50_o2_ddp_step()
    m = compiled_memory(fn, *avals)
    emit({"contract": "config2_resnet50_o2_ddp_step",
          "peak_bytes": m.peak_bytes, "state_floor_bytes": floor,
          "activation_overhead_bytes": m.peak_bytes - floor,
          "peak_mb": round(m.peak_bytes / 2**20, 1),
          "state_floor_mb": round(floor / 2**20, 1)},
         "b256/chip 224x224 data=8 (AOT topology)")

    fn, avals, floor = bert_large_lamb_step()
    m = compiled_memory(fn, *avals)
    emit({"contract": "config4_bert_large_lamb_step",
          "peak_bytes": m.peak_bytes, "state_floor_bytes": floor,
          "activation_overhead_bytes": m.peak_bytes - floor,
          "peak_mb": round(m.peak_bytes / 2**20, 1),
          "state_floor_mb": round(floor / 2**20, 1)},
         "large b8 s512 pred80 (phase-2 shape)")


SUITES = {"xentropy": bench_xentropy, "lm_head": bench_lm_head,
          "flash": bench_flash,
          "fused_softmax": bench_fused_softmax, "remat": bench_remat,
          "layer_norm": bench_layer_norm, "configs": bench_configs}


def main(argv):
    print(json.dumps({"device": str(jax.devices()[0]),
                      "backend": jax.default_backend()}), flush=True)
    bad = [n for n in argv if n not in SUITES]
    if bad:
        raise SystemExit(f"unknown suite(s) {', '.join(map(repr, bad))}; "
                         f"pick from {', '.join(sorted(SUITES))}")
    for name in (argv or list(SUITES)):
        SUITES[name]()


if __name__ == "__main__":
    # crash contract: any failure still ends in one parseable JSON
    # line ({"metric", "error", "rc": 1}) instead of a bare traceback
    from apex_tpu.telemetry import guard_bench_main
    guard_bench_main(lambda: main(sys.argv[1:]), "bench_memory")
