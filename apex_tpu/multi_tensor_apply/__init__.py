"""Tensor-list frontend over the superbuffer kernels — parity with
apex/multi_tensor_apply/multi_tensor_apply.py — class MultiTensorApply and the
``multi_tensor_applier`` instance, plus list-level ops mirroring the amp_C
entry points.

Apex usage: ``multi_tensor_applier(amp_C.multi_tensor_scale, overflow_buf,
[grads, out], scale)``. Functionally we can't write through output lists, so
each op RETURNS the new list(s); the overflow flag is returned rather than
written into a noop buffer.
"""

from __future__ import annotations

from typing import List, Sequence

import jax.numpy as jnp

from ..kernels import multi_tensor as _k
from ..utils.pytree import flatten, unflatten

__all__ = [
    "MultiTensorApply", "multi_tensor_applier", "multi_tensor_scale",
    "multi_tensor_axpby", "multi_tensor_l2norm", "multi_tensor_adam",
    "multi_tensor_sgd", "available",
]

available = True  # apex checks multi_tensor_applier.available


class MultiTensorApply:
    """apex/multi_tensor_apply/multi_tensor_apply.py — class MultiTensorApply.

    chunk_size is accepted for API parity; chunking is the Pallas grid's job.
    """

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = chunk_size

    def __call__(self, op, noop_flag_buffer, tensor_lists, *args, **kwargs):
        return op(noop_flag_buffer, tensor_lists, *args, **kwargs)


multi_tensor_applier = MultiTensorApply()


def multi_tensor_scale(tensors: Sequence[jnp.ndarray], scale,
                       interpret: bool = False):
    """amp_C.multi_tensor_scale over a tensor list → (scaled list, found_inf)."""
    flat = flatten(list(tensors))
    out, found = _k.fused_scale(flat, scale, interpret=interpret)
    return unflatten(out, list(tensors)), found


def multi_tensor_axpby(xs: Sequence[jnp.ndarray], ys: Sequence[jnp.ndarray],
                       a, b, interpret: bool = False):
    """amp_C.multi_tensor_axpby → (a*x+b*y list, found_inf)."""
    fx, fy = flatten(list(xs)), flatten(list(ys))
    out, found = _k.fused_axpby(fx, fy, a, b, interpret=interpret)
    return unflatten(out, list(xs)), found


def multi_tensor_l2norm(tensors: Sequence[jnp.ndarray],
                        per_tensor: bool = False, interpret: bool = False):
    """amp_C.multi_tensor_l2norm → global norm (and per-tensor norms when
    requested, as FusedLAMB's stage-1 does)."""
    norms: List[jnp.ndarray] = []
    if per_tensor:
        norms = [_k.fused_l2norm(jnp.ravel(t), interpret=interpret)
                 for t in tensors]
        total = jnp.sqrt(sum(n * n for n in norms))
        return total, norms
    flat = flatten(list(tensors))
    return _k.fused_l2norm(flat, interpret=interpret)


def multi_tensor_adam(params, exp_avgs, exp_avg_sqs, grads, *, lr, beta1,
                      beta2, eps, step, weight_decay=0.0, adam_w_mode=True,
                      interpret: bool = False):
    """amp_C.multi_tensor_adam over tensor lists → (params, m, v) lists."""
    fp, fm = flatten(list(params)), flatten(list(exp_avgs))
    fv, fg = flatten(list(exp_avg_sqs)), flatten(list(grads))
    p, m, v = _k.fused_adam_step(
        fp, fm, fv, fg, lr=lr, beta1=beta1, beta2=beta2, eps=eps,
        weight_decay=weight_decay, step=step, adam_w_mode=adam_w_mode,
        interpret=interpret)
    return (unflatten(p, list(params)), unflatten(m, list(exp_avgs)),
            unflatten(v, list(exp_avg_sqs)))


def multi_tensor_sgd(params, momentum_bufs, grads, *, lr, momentum=0.0,
                     dampening=0.0, weight_decay=0.0, nesterov=False,
                     wd_after_momentum=False, interpret: bool = False):
    """amp_C.multi_tensor_sgd over tensor lists → (params, buf) lists."""
    fp, fb = flatten(list(params)), flatten(list(momentum_bufs))
    fg = flatten(list(grads))
    p, buf = _k.fused_sgd_step(
        fp, fb, fg, lr=lr, momentum=momentum, dampening=dampening,
        weight_decay=weight_decay, nesterov=nesterov,
        wd_after_momentum=wd_after_momentum, interpret=interpret)
    return unflatten(p, list(params)), unflatten(buf, list(momentum_bufs))
