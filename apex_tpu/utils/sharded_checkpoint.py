"""Sharded (multi-process) checkpointing: each process writes only its
addressable shards; restore re-places shards under any target sharding.

Reference context: apex's DistributedFusedAdam reconstitutes ZeRO-sharded
optimizer state through ``state_dict``/``load_state_dict`` gathers
(apex/contrib/optimizers/distributed_fused_adam.py — SURVEY P32); the
driver-level pattern on TPU pods is orbax-style per-host shard files. This
module provides that shape natively for any pytree of ``jax.Array``s:

- :func:`save_sharded` — every process writes ``shards_p{i}.npz`` holding
  its addressable shards (one entry per (leaf, shard-index) with the global
  slice recorded), plus rank-0 metadata (leaf shapes/dtypes, process count,
  step).
- :func:`load_sharded` — reads exactly the process files named by the
  metadata, verifies every file carries the metadata's step stamp (a
  preempted or mixed-topology save fails loudly instead of restoring mixed-
  step weights), and assembles ONLY the slices intersecting each target
  shard of the TEMPLATE's sharding — so restore memory is per-shard, not
  per-global-array, and the target sharding may differ from the sharding at
  save time (resharded restore: the normal case when pod topology changes).

Each file write is atomic (tmp + rename); cross-file consistency is what
the step stamp enforces at load. Shard data is staged through
``utils.pytree.host_flatten`` (a guaranteed copy — ``np.asarray`` of a
CPU-backend jax array may alias the XLA buffer; see
utils/checkpoint._snapshot). Single-process with a multi-device mesh (the
CI topology) works unchanged: all shards are addressable, one file is
written.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any

import jax
import numpy as np

from .checkpoint import AsyncWriterBase
from .pytree import host_flatten

__all__ = ["save_sharded", "load_sharded", "AsyncShardedCheckpointer"]

_META = "sharded_meta.json"
_STEP_KEY = "__step__"


def _leaf_key(i: int) -> str:
    return f"leaf{i}"


def _slice_spec(index, shape):
    """(start, stop) per dim for a shard's global slice (None → full)."""
    spec = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        spec.append((start, stop))
    return spec


def _collect_shards(state: Any, step: int):
    """Device→host snapshot of this process's shards: the guaranteed-copy
    phase that must complete before any donating step reuses the buffers."""
    leaves, _ = jax.tree_util.tree_flatten(state)
    payload = {_STEP_KEY: np.asarray(step, np.int64)}
    meta_leaves = []
    for i, leaf in enumerate(leaves):
        arr = leaf if isinstance(leaf, jax.Array) else jax.numpy.asarray(leaf)
        meta_leaves.append({"shape": list(arr.shape),
                            "dtype": np.dtype(arr.dtype).name})
        seen = set()
        for n, shard in enumerate(arr.addressable_shards):
            spec = tuple(_slice_spec(shard.index, arr.shape))
            if spec in seen:      # replicated: one copy is enough
                continue
            seen.add(spec)
            data = np.asarray(shard.data)
            # guaranteed copy off the XLA buffer (never alias; the caller
            # may run a donating step while a wrapper is still writing)
            data = host_flatten([data]).reshape(data.shape)
            key = f"{_leaf_key(i)}_s{n}"
            # raw bytes: ml_dtypes (bfloat16 — the default AMP dtype) do not
            # survive the npy descr; dtype is recovered from the metadata
            payload[key] = data.reshape(-1).view(np.uint8)
            payload[key + "_idx"] = np.asarray(spec, np.int64).reshape(-1, 2)
    return payload, meta_leaves


def _write_shards(directory: str, payload: dict, meta_leaves, step: int,
                  pidx: int, n_processes: int) -> str:
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"shards_p{pidx}.npz")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)

    if pidx == 0:
        # tree structure comes from the restore-side template (same contract
        # as load_checkpoint: you load into an already-constructed state)
        meta = {"step": step, "n_leaves": len(meta_leaves),
                "n_processes": n_processes, "leaves": meta_leaves}
        mtmp = os.path.join(directory, _META + ".tmp")
        with open(mtmp, "w") as f:
            json.dump(meta, f)
        os.replace(mtmp, os.path.join(directory, _META))
    return path


def save_sharded(directory: str, state: Any, step: int = 0) -> str:
    """Write this process's shards of ``state`` under ``directory``.

    Every process must call this with the same ``step`` (collective-like,
    but no communication happens); process 0 additionally writes the
    metadata file naming the exact file set a restore must see.
    """
    payload, meta_leaves = _collect_shards(state, step)
    return _write_shards(directory, payload, meta_leaves, step,
                         jax.process_index(), jax.process_count())


class AsyncShardedCheckpointer(AsyncWriterBase):
    """Background-thread sharded writer (the AsyncCheckpointer pattern over
    :func:`save_sharded`): the device→host snapshot copies happen on the
    caller's thread — required before the next donating step — and the
    npz/metadata writes happen on a worker thread so the train loop never
    blocks on disk."""

    def save(self, directory: str, state: Any, step: int = 0):
        payload, meta_leaves = _collect_shards(state, step)
        self._submit(_write_shards, directory, payload, meta_leaves, step,
                     jax.process_index(), jax.process_count())


def _normalize_index(index, shape):
    """Target-shard index → concrete ((start, stop), ...) per dim."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step_ = sl.indices(dim)
        assert step_ == 1
        out.append((start, stop))
    return tuple(out)


def load_sharded(directory: str, template: Any) -> tuple[Any, int]:
    """Restore a pytree saved by :func:`save_sharded`.

    ``template`` supplies tree structure, global shapes/dtypes, and the
    TARGET shardings: each leaf that is a sharded ``jax.Array`` is restored
    with its own sharding (assembling only the slices each local device
    needs); other leaves come back as plain device arrays. Shape or dtype
    mismatches raise — resuming into a different precision configuration
    must fail loudly, never silently change numerics (same contract as
    load_checkpoint).
    """
    with open(os.path.join(directory, _META)) as f:
        meta = json.load(f)

    leaves_t, treedef = jax.tree_util.tree_flatten(template)
    if len(leaves_t) != meta["n_leaves"]:
        raise ValueError(
            f"load_sharded: template has {len(leaves_t)} leaves, "
            f"checkpoint has {meta['n_leaves']}")
    for i, (tleaf, m) in enumerate(zip(leaves_t, meta["leaves"])):
        shape, dtype = tuple(m["shape"]), np.dtype(m["dtype"])
        if tuple(np.shape(tleaf)) != shape:
            raise ValueError(
                f"load_sharded: leaf {i} template shape {np.shape(tleaf)} "
                f"!= checkpoint shape {shape}")
        tdtype = getattr(tleaf, "dtype", None)
        if tdtype is not None and np.dtype(tdtype) != dtype:
            raise ValueError(
                f"load_sharded: leaf {i} template dtype {np.dtype(tdtype)} "
                f"!= checkpoint dtype {dtype} (resuming into a different "
                "precision configuration would silently change numerics)")

    with contextlib.ExitStack() as stack:
        # exactly the files the manifest names — stale shard files from an
        # older save with a different process count are ignored, and every
        # file must carry this manifest's step stamp
        handles = []
        for p in range(meta["n_processes"]):
            path = os.path.join(directory, f"shards_p{p}.npz")
            if not os.path.exists(path):
                raise ValueError(
                    f"load_sharded: missing {path} (checkpoint written by "
                    f"{meta['n_processes']} processes; incomplete save?)")
            z = stack.enter_context(np.load(path))
            fstep = int(z[_STEP_KEY]) if _STEP_KEY in z.files else None
            if fstep != meta["step"]:
                raise ValueError(
                    f"load_sharded: {path} has step {fstep} but the "
                    f"manifest says {meta['step']} — mixed or preempted "
                    "save; refusing to restore mixed-step weights")
            handles.append(z)

        # piece index: leaf -> [(handle, key, spec), ...]
        pieces: list[list] = [[] for _ in range(meta["n_leaves"])]
        for z in handles:
            for key in z.files:
                if key == _STEP_KEY or key.endswith("_idx"):
                    continue
                leaf_i = int(key.split("_s")[0][len("leaf"):])
                spec = tuple(tuple(int(v) for v in row)
                             for row in z[key + "_idx"])
                pieces[leaf_i].append((z, key, spec))

        def assemble(leaf_i, target):
            """Fill one target shard ((start, stop) per dim) from pieces."""
            m = meta["leaves"][leaf_i]
            dtype = np.dtype(m["dtype"])
            tshape = tuple(b - a for a, b in target)
            buf = np.zeros(tshape, dtype)
            mask = np.zeros(tshape, bool)
            for z, key, spec in pieces[leaf_i]:
                inter = []
                for (a, b), (ta, tb) in zip(spec, target):
                    lo, hi = max(a, ta), min(b, tb)
                    if lo >= hi:
                        break
                    inter.append((lo, hi))
                else:
                    pshape = tuple(b - a for a, b in spec)
                    src = z[key].view(dtype).reshape(pshape)
                    src_sl = tuple(slice(lo - a, hi - a)
                                   for (lo, hi), (a, _) in zip(inter, spec))
                    dst_sl = tuple(slice(lo - ta, hi - ta)
                                   for (lo, hi), (ta, _) in zip(inter,
                                                                target))
                    buf[dst_sl] = src[src_sl]
                    mask[dst_sl] = True
            if not mask.all():
                raise ValueError(
                    f"load_sharded: leaf {leaf_i} target slice {target} has "
                    "missing data (checkpoint written by more processes "
                    "than are visible here?)")
            return buf

        out_leaves = []
        for i, (tleaf, m) in enumerate(zip(leaves_t, meta["leaves"])):
            shape = tuple(m["shape"])
            sharding = getattr(tleaf, "sharding", None)
            if sharding is not None and isinstance(tleaf, jax.Array):
                arr = jax.make_array_from_callback(
                    shape, sharding,
                    lambda idx, i=i, shape=shape: assemble(
                        i, _normalize_index(idx, shape)))
            else:
                full = assemble(i, tuple((0, d) for d in shape))
                arr = jax.device_put(full)
            out_leaves.append(arr)

    return jax.tree_util.tree_unflatten(treedef, out_leaves), meta["step"]
