"""Tensor-list flatten/unflatten — parity with apex_C.

Reference: csrc/flatten_unflatten.cpp — ``flatten`` / ``unflatten`` (thin wraps
of torch::utils::flatten_dense_tensors), used by apex DDP to coalesce gradient
buckets into one contiguous buffer per allreduce
(apex/parallel/distributed.py — flat_dist_call).

On TPU a "contiguous comm buffer" is just a concatenated 1-D array; XLA owns
layout. The same helpers double as the superbuffer builder for the fused
multi-tensor optimizer harness (csrc/multi_tensor_apply.cuh equivalent in
apex_tpu.multi_tensor_apply).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:  # native host-side memcpy path (csrc/flatten_unflatten.c, built by
    # setup.py --cpp_ext); absent → numpy fallback, the reference's
    # graceful-degradation contract for missing extensions
    from apex_tpu import _C as _native
except ImportError:
    _native = None


def flatten(tensors: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Concatenate a list of arrays into one 1-D buffer (apex_C.flatten)."""
    if not tensors:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate([jnp.ravel(t) for t in tensors])


def unflatten(flat: jnp.ndarray, like: Sequence[jnp.ndarray]) -> List[jnp.ndarray]:
    """Split a flat buffer back into arrays shaped like ``like``
    (apex_C.unflatten)."""
    outs = []
    offset = 0
    for t in like:
        n = int(np.prod(t.shape)) if t.ndim else 1
        outs.append(jnp.reshape(flat[offset:offset + n], t.shape)
                    .astype(jnp.asarray(t).dtype))
        offset += n
    return outs


def host_flatten(arrays: Sequence[np.ndarray]) -> np.ndarray:
    """Pack host (numpy) arrays into one contiguous byte-homogeneous buffer.

    Native path: one allocation + GIL-released memcpys (apex_C.flatten
    parity for host staging — checkpoint assembly, input batching).
    Returns a 1-D array of the common dtype; mixed dtypes are an error
    (same contract as torch flatten_dense_tensors).
    """
    arrays = [np.ascontiguousarray(a) for a in arrays]
    if not arrays:
        return np.zeros((0,), np.float32)
    dtype = arrays[0].dtype
    for a in arrays:
        if a.dtype != dtype:
            raise ValueError(
                f"host_flatten: mixed dtypes {dtype} vs {a.dtype}")
    if _native is not None:
        buf = _native.flatten(arrays)
        return np.frombuffer(buf, dtype=dtype)
    return np.concatenate([a.ravel() for a in arrays]) \
        if len(arrays) > 1 else arrays[0].ravel().copy()


def host_unflatten_into(flat: np.ndarray,
                        outs: Sequence[np.ndarray]) -> None:
    """Scatter a flat host buffer back into writable arrays in place
    (apex_C.unflatten parity, the direction apex DDP uses to copy allreduced
    flat buckets back into per-param grads)."""
    flat = np.ascontiguousarray(flat)
    for o in outs:
        if not (o.flags.c_contiguous and o.flags.writeable):
            raise ValueError(
                "host_unflatten_into outputs must be writable C-contiguous")
    if _native is not None:
        _native.unflatten_into(flat, list(outs))
        return
    fb = flat.reshape(-1).view(np.uint8)
    offset = 0
    for o in outs:
        nb = o.nbytes
        o.reshape(-1).view(np.uint8)[:] = fb[offset:offset + nb]
        offset += nb


def flatten_tree(tree: Any) -> Tuple[jnp.ndarray, Any]:
    """Flatten a whole pytree into (flat_buffer, spec) — the superbuffer used
    by the fused optimizer harness. ``spec`` round-trips via
    :func:`unflatten_tree`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [jnp.shape(l) for l in leaves]
    dtypes = [jnp.asarray(l).dtype for l in leaves]
    flat = flatten([jnp.asarray(l) for l in leaves]) if leaves else jnp.zeros((0,))
    return flat, (treedef, shapes, dtypes)


def unflatten_tree(flat: jnp.ndarray, spec) -> Any:
    treedef, shapes, dtypes = spec
    outs = []
    offset = 0
    for shape, dtype in zip(shapes, dtypes):
        n = int(np.prod(shape)) if shape else 1
        outs.append(jnp.reshape(flat[offset:offset + n], shape).astype(dtype))
        offset += n
    return jax.tree_util.tree_unflatten(treedef, outs)
