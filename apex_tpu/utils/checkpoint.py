"""Checkpoint / resume for amp train states.

Reference surface being mirrored (SURVEY §6 — checkpoint/resume):

- the documented apex pattern saves ``amp.state_dict()`` (loss scalers)
  alongside model + optimizer state (apex/amp/README.md — "Checkpointing");
- ``examples/imagenet/main_amp.py — --resume`` does torch.save/torch.load of
  {model, optimizer, epoch, best_prec1}.

Here the whole :class:`apex_tpu.amp.AmpState` is one pytree (params, masters,
optimizer state, scaler — including the loss scale and unskipped counter), so
a checkpoint is a single serialized tree plus a small metadata dict. Restore
is shape/dtype-checked against a template state (the equivalent of loading
into an already-constructed model/optimizer, which is how both apex and
torch do it).

Writes are atomic (tmp file + rename) so a preempted save never corrupts the
previous checkpoint — the property orbax's async checkpointing provides on
real pods. For multi-process SHARDED state (each host writing only its own
shards, restore under a different topology), use the sibling
:mod:`apex_tpu.utils.sharded_checkpoint` (``save_sharded``/``load_sharded``);
this module is the single-controller whole-tree path.
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Any, Optional, Tuple

import jax
import numpy as np

from ..log_util import get_logger

__all__ = ["save_checkpoint", "load_checkpoint", "latest_checkpoint",
           "save_train_checkpoint", "resume_train_checkpoint",
           "AsyncCheckpointer"]

_logger = get_logger("utils.checkpoint")

_META_KEY = "__apex_tpu_meta__"

# State fields added after their dataclass first shipped. A checkpoint
# written before the field existed is missing that leaf; restore fills it
# from the template (the freshly-constructed state's default) — the pytree
# analogue of LossScaler.load_state_dict's ``sd.get("hysteresis_left", …)``
# and of apex amp.load_state_dict tolerating older state_dicts
# (apex/amp/frontend.py — state_dict round-trips across versions).
_MIGRATABLE_FIELDS = frozenset({"hysteresis_left"})


def _leaf_paths(state) -> list:
    """Key-path string per flattened leaf, aligned with tree_flatten order."""
    flat_p = jax.tree_util.tree_flatten_with_path(state)[0]
    return [jax.tree_util.keystr(p) for p, _ in flat_p]


_LAST_SEGMENT = re.compile(
    r"(?:\.([A-Za-z_]\w*)"          # .attr        (GetAttrKey)
    r"|\[['\"]([^'\"]+)['\"]\]"     # ['key']      (DictKey)
    r"|\[(\d+)\])$")                # [idx]        (SequenceKey)


def _path_field(path: str) -> str:
    """Final attribute/key name of a keystr path — handles ".attr",
    "['key']", and "[idx]" terminal segments (ADVICE r3: dict-keyed
    leaves like "…['hysteresis_left']" must parse to the bare name, or
    migratable fields under dict nodes are never detected)."""
    m = _LAST_SEGMENT.search(path)
    if m:
        return next(g for g in m.groups() if g is not None)
    return path.rsplit(".", 1)[-1].strip("[]'\"")


def save_train_checkpoint(path: str, state: Any, step: int, rng) -> str:
    """The recipes' ``--save``: :func:`save_checkpoint` plus the rng key
    in the extra dict, so a resumed run continues the exact random
    stream without replaying ``step`` splits."""
    rng = jax.numpy.asarray(rng)
    impl = None
    if jax.numpy.issubdtype(rng.dtype, jax.dtypes.prng_key):
        # typed key array (jax_enable_custom_prng): persist its raw data
        # plus the impl name (rbg keys can't re-wrap as threefry) so
        # restore rebuilds the same key — np.asarray on the key itself
        # would fail (ADVICE r4)
        impl = str(jax.random.key_impl(rng))
        rng = jax.random.key_data(rng)
    out = save_checkpoint(path, state, step=step,
                          extra={"rng": np.asarray(rng).tolist(),
                                 "rng_impl": impl})
    _logger.info("=> saved step %s to %s", step, path)
    return out


def resume_train_checkpoint(path: str, template: Any, rng, *,
                            step_limit: int, limit_flag: str):
    """The recipes' ``--resume``: template-shaped restore (torch
    load_state_dict semantics), rng key recovered from the checkpoint's
    extra dict. Returns ``(state, start_step, rng)``; rejects a
    checkpoint already at/past ``step_limit`` with the recipe's flag
    name in the message."""
    state, start, extra = load_checkpoint(path, template)
    if "rng" in (extra or {}):
        rng = jax.numpy.asarray(extra["rng"], jax.numpy.uint32)
        impl = extra.get("rng_impl")
        if impl:
            rng = jax.random.wrap_key_data(rng, impl=impl)
    _logger.info("=> resumed from %s (step %s)", path, start)
    if start >= step_limit:
        raise SystemExit(
            f"--resume checkpoint is at step {start}; {limit_flag} "
            f"{step_limit} adds nothing (pass a larger {limit_flag} to "
            "continue)")
    return state, start, rng


def save_checkpoint(path: str, state: Any, step: int = 0,
                    extra: Optional[dict] = None) -> str:
    """Serialize ``state`` (any pytree: AmpState, params, opt state) to
    ``path`` (.npz). Returns the path written."""
    flat, _ = jax.tree_util.tree_flatten(state)
    arrays = {}
    dtypes = []
    for i, x in enumerate(flat):
        a = np.asarray(x)
        dtypes.append(a.dtype.name)
        if a.dtype.kind == "V" or a.dtype.name in ("bfloat16",):
            # npz can't represent ml_dtypes (bfloat16 &c); fp32 holds every
            # bf16 exactly and load_checkpoint casts back to the recorded
            # dtype, so the round-trip is bit-faithful
            a = a.astype(np.float32)
        arrays[f"leaf_{i}"] = a
    meta = {"step": int(step), "n_leaves": len(flat), "dtypes": dtypes,
            "paths": _leaf_paths(state), "extra": extra or {}}
    arrays[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)  # atomic on POSIX
    return path


def load_checkpoint(path: str, template: Any) -> Tuple[Any, int, dict]:
    """Restore a pytree saved by :func:`save_checkpoint`.

    ``template`` supplies the treedef and the expected shapes/dtypes (the
    already-built state, as with torch's load_state_dict). Returns
    ``(state, step, extra)``.

    Checkpoints from before a :data:`_MIGRATABLE_FIELDS` field existed (e.g.
    a round-1 AmpState without ``ScalerState.hysteresis_left``) restore
    cleanly: the missing leaves keep the template's freshly-initialized
    values and every other leaf loads normally.
    """
    with np.load(path) as data:
        meta = json.loads(bytes(data[_META_KEY].tolist()).decode("utf-8"))
        flat_t, treedef = jax.tree_util.tree_flatten(template)
        # Template positions filled from the template itself because the
        # (older) checkpoint predates the field. Identified positionally:
        # struct.dataclass flattening is declaration-ordered, so removing
        # the migratable leaves from the template must reproduce the old
        # layout exactly (checked by count, and by name when the checkpoint
        # recorded key paths).
        fill_from_template: set = set()
        old_paths = meta.get("paths")
        if meta["n_leaves"] == len(flat_t) and old_paths is not None:
            # equal-count load: when the checkpoint recorded key paths,
            # a same-shaped but differently-named template is still a
            # configuration mismatch — catch it by name, not just shape
            t_paths = _leaf_paths(template)
            if t_paths != old_paths:
                bad = next((a, b) for a, b in zip(old_paths, t_paths)
                           if a != b)
                raise ValueError(
                    f"checkpoint leaf paths do not match the template "
                    f"(first difference: saved {bad[0]!r} vs template "
                    f"{bad[1]!r}) — wrong model/optimizer configuration")
        if meta["n_leaves"] != len(flat_t):
            t_paths = _leaf_paths(template)
            migratable = [i for i, p in enumerate(t_paths)
                          if _path_field(p) in _MIGRATABLE_FIELDS]
            if meta["n_leaves"] != len(flat_t) - len(migratable) or not migratable:
                raise ValueError(
                    f"checkpoint has {meta['n_leaves']} leaves, template has "
                    f"{len(flat_t)} — wrong model/optimizer configuration")
            fill_from_template = set(migratable)
            if old_paths is not None:
                kept = [p for i, p in enumerate(t_paths)
                        if i not in fill_from_template]
                if kept != old_paths:
                    raise ValueError(
                        "checkpoint leaf paths do not match the template "
                        "even after dropping migratable fields — wrong "
                        "model/optimizer configuration")
        saved_dtypes = meta.get("dtypes")
        flat = []
        ckpt_i = 0
        for i, t in enumerate(flat_t):
            # abstract templates (jax.eval_shape output) are fine for
            # plain restores — only a migratable fill needs real values
            t_shape = tuple(t.shape)
            t_dtype = np.dtype(t.dtype)
            if i in fill_from_template:
                if isinstance(t, jax.ShapeDtypeStruct):
                    raise ValueError(
                        "restoring an old checkpoint that needs field "
                        "migration requires a real-valued template (the "
                        "migrated leaf keeps the template's value); got "
                        "an abstract ShapeDtypeStruct template")
                flat.append(jax.numpy.asarray(np.asarray(t)))
                continue
            arr = data[f"leaf_{ckpt_i}"]
            if arr.shape != t_shape:
                raise ValueError(
                    f"leaf {ckpt_i}: checkpoint shape {arr.shape} != template "
                    f"shape {t_shape}")
            if saved_dtypes is not None and saved_dtypes[ckpt_i] != t_dtype.name:
                raise ValueError(
                    f"leaf {ckpt_i}: checkpoint dtype {saved_dtypes[ckpt_i]} != "
                    f"template dtype {t_dtype.name} — resuming into a "
                    "different precision configuration would silently "
                    "change numerics")
            flat.append(jax.numpy.asarray(arr.astype(t_dtype)))
            ckpt_i += 1
    state = jax.tree_util.tree_unflatten(treedef, flat)
    return state, meta["step"], meta["extra"]


def latest_checkpoint(directory: str, prefix: str = "ckpt_") -> Optional[str]:
    """Newest ``{prefix}{step}.npz`` in ``directory`` (by step), or None."""
    if not os.path.isdir(directory):
        return None
    best, best_step = None, -1
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(".npz"):
            try:
                step = int(name[len(prefix):-4])
            except ValueError:
                continue
            if step > best_step:
                best, best_step = os.path.join(directory, name), step
    return best


def _snapshot(state):
    """Host snapshot with guaranteed-copy semantics.

    ``np.asarray`` on a CPU-backend jax array can return a zero-copy VIEW of
    the XLA buffer; if the next (donating) step then reuses that buffer, a
    lazily-serialized checkpoint would contain torn weights. Packing each
    dtype group through :func:`host_flatten` (csrc memcpy path when built)
    materializes a real copy in one GIL-released pass, and the per-leaf
    arrays handed to the writer are zero-copy views into that snapshot.
    """
    from apex_tpu.utils.pytree import host_flatten

    leaves, treedef = jax.tree_util.tree_flatten(state)
    host = [np.asarray(x) for x in leaves]
    copies: list = [None] * len(host)
    groups: dict = {}
    for i, a in enumerate(host):
        groups.setdefault(a.dtype, []).append(i)
    for dt, idxs in groups.items():
        flat = host_flatten([host[i] for i in idxs])
        off = 0
        for i in idxs:
            n = host[i].size
            copies[i] = flat[off:off + n].reshape(host[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, copies)


class AsyncWriterBase:
    """One-in-flight background writer: ``_submit(fn, *args)`` runs ``fn``
    on a worker thread after waiting out the previous write; ``wait()``
    joins and RE-RAISES any write failure (a swallowed error would report
    phantom checkpoints). Subclasses do their snapshot copies on the
    caller's thread before submitting — the copies must complete before the
    next donating step reuses the buffers."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def _run(self, fn, args):
        try:
            fn(*args)
        except BaseException as e:  # surfaced from wait()/next save()
            self._error = e

    def _submit(self, fn, *args):
        self.wait()
        self._thread = threading.Thread(target=self._run, args=(fn, args),
                                        daemon=True)
        self._thread.start()

    def wait(self):
        """Block until the in-flight write finishes; re-raise its failure."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err


class AsyncCheckpointer(AsyncWriterBase):
    """Background-thread checkpoint writer (orbax-style async save).

    Device→host transfer + snapshot copy happen on the caller's thread
    (required for consistency — the arrays must be copied before the next
    step mutates donated buffers; see :func:`_snapshot`); the file write
    happens on a worker thread so the train loop never blocks on disk.
    """

    def save(self, path: str, state: Any, step: int = 0,
             extra: Optional[dict] = None):
        host_state = _snapshot(state)
        self._submit(save_checkpoint, path, host_state, step, extra)
