"""Compiler-priced memory accounting for the fused-kernel memory contracts.

The emulator backend cannot price the fused kernels' wins in *time*
(BASELINE.md "Honest reading": its clock is dispatch-dominated), but XLA's
buffer assignment prices them in *bytes*, exactly: lower the SAME
computation once with the Pallas kernel and once with the jnp/XLA
composition, compile both, and read the byte counters off
``compiled.memory_analysis()``. Buffer assignment is what the runtime
actually allocates, so this evidence is emulator-independent — the same
counters the 1F1B memory-flatness proof uses
(tests/L0/run_transformer/test_pipeline_parallel.py).

The contracts being priced are the reference's own headline claims:

- xentropy "bprop-in-fprop": backward consumes only
  (losses, max_log_sum_exp); no [N, V] softmax residual is ever saved
  (apex/contrib/csrc/xentropy/xentropy_kernel.cu —
  cunn_SoftMaxXEntropyBackward recomputes softmax from logits + mlse).
- flash attention: no O(s^2) probability materialization in forward or
  residuals (apex/contrib/fmha, apex/contrib/fast_multihead_attn —
  fmhalib keeps only (o, lse) beyond the inputs).
- rematerialisation: ``jax.checkpoint`` trades recompute FLOPs for
  activation memory (the TPU-native analogue of the reference's
  checkpoint-activations training recipes).

Functions here never *execute* anything — ``lower().compile()`` on
abstract ``jax.ShapeDtypeStruct`` avals — so production shapes (1 GB+
residuals) price in seconds with zero device allocation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax

__all__ = ["MemoryStats", "compiled_memory", "price_contract",
           "xentropy_contract", "lm_head_contract", "flash_contract",
           "remat_mlp_contract",
           "causal_softmax_contract", "masked_softmax_contract",
           "lm_step_remat_contract", "ln_memory_efficient_contract",
           "resnet50_o2_ddp_step", "bert_large_lamb_step"]


@dataclasses.dataclass(frozen=True)
class MemoryStats:
    """Byte counters from XLA buffer assignment for one compiled fn."""

    argument_bytes: int
    output_bytes: int
    temp_bytes: int
    peak_bytes: int

    @property
    def live_overhead_bytes(self) -> int:
        """Peak minus the bytes any implementation must hold (args + outs):
        the residual/scratch the chosen implementation keeps live."""
        return self.peak_bytes - self.argument_bytes - self.output_bytes


def compiled_memory(fn: Callable, *avals: Any) -> MemoryStats:
    """Compile ``fn`` at abstract ``avals`` (ShapeDtypeStructs or arrays)
    and return its buffer-assignment byte counters. Nothing executes."""
    c = jax.jit(fn).lower(*avals).compile()
    ma = c.memory_analysis()
    return MemoryStats(
        argument_bytes=int(ma.argument_size_in_bytes),
        output_bytes=int(ma.output_size_in_bytes),
        temp_bytes=int(ma.temp_size_in_bytes),
        peak_bytes=int(ma.peak_memory_in_bytes),
    )


def xentropy_contract(n: int, v: int):
    """Canonical fused-CE pricing setup: (fused_fn, composed_fn, avals,
    theory_bytes). Theory = the [N, V] fp32 log-softmax residual the
    bprop-in-fprop contract says is never saved."""
    import jax.numpy as jnp

    from apex_tpu.kernels.xentropy import (softmax_cross_entropy_loss,
                                           xent_reference)

    avals = [jax.ShapeDtypeStruct((n, v), jnp.bfloat16),
             jax.ShapeDtypeStruct((n,), jnp.int32)]
    fused = jax.value_and_grad(
        lambda lg, lb: jnp.sum(softmax_cross_entropy_loss(lg, lb)))
    composed = jax.value_and_grad(
        lambda lg, lb: jnp.sum(xent_reference(lg, lb)))
    return fused, composed, avals, n * v * 4


def lm_head_contract(n: int, h: int, v: int, chunk: int = 8192):
    """Fused LM-head+CE pricing setup: (fused_fn, composed_fn, avals,
    theory_bytes). Theory = the [N, V] fp32 logits the composed tail
    materializes forward AND saves as the CE residual (the fused op's
    residual is a length-N lse; its chunk working set is O(chunk·N)).
    The saving requires chunk < v — at chunk >= v the single chunk IS
    the full logits and the op prices identical to composed."""
    import jax.numpy as jnp

    from apex_tpu.kernels.lm_head_loss import (lm_head_xent_reference,
                                               lm_head_xentropy)

    avals = [jax.ShapeDtypeStruct((n, h), jnp.float32),
             jax.ShapeDtypeStruct((v, h), jnp.float32),
             jax.ShapeDtypeStruct((n,), jnp.int32)]
    fused = jax.value_and_grad(
        lambda x, w, y: jnp.sum(lm_head_xentropy(
            x, w, y, chunk=chunk, compute_dtype=jnp.bfloat16)),
        argnums=(0, 1))
    composed = jax.value_and_grad(
        lambda x, w, y: jnp.sum(lm_head_xent_reference(
            x, w, y, compute_dtype=jnp.bfloat16)), argnums=(0, 1))
    return fused, composed, avals, n * v * 4


def flash_contract(b: int, h: int, s: int, d: int, with_bwd: bool):
    """Canonical flash-attention pricing setup: (fused_fn, composed_fn,
    avals, theory_bytes). Theory = one [b, h, s, s] fp32 probability
    buffer (forward live peak, or the backward residual)."""
    import jax.numpy as jnp

    from apex_tpu.kernels.flash_attention import (flash_attention,
                                                  mha_reference)

    avals = [jax.ShapeDtypeStruct((b, h, s, d), jnp.bfloat16)] * 3

    def fused_fwd(q, k, v):
        return flash_attention(q, k, v, causal=True)

    def composed_fwd(q, k, v):
        return mha_reference(q, k, v, causal=True, scale=d ** -0.5)

    fused, composed = _fwd_or_grad(fused_fwd, composed_fwd, with_bwd,
                                   argnums=(0, 1, 2))
    return fused, composed, avals, b * h * s * s * 4


def remat_mlp_contract(n_layers: int, n: int, hdim: int):
    """Canonical remat pricing setup for an L-layer residual MLP:
    (plain_fn, remat_fn, avals, theory_bytes). Theory = one [N, 4H] fp32
    hidden activation per layer — the buffer jax.checkpoint drops."""
    import functools

    import jax.numpy as jnp

    def block(x, w1, w2):
        return x + jax.nn.gelu(x @ w1) @ w2

    def net(params, x, remat):
        body = jax.checkpoint(block) if remat else block
        for w1, w2 in params:
            x = body(x, w1, w2)
        return jnp.sum(x)

    avals = [[(jax.ShapeDtypeStruct((hdim, 4 * hdim), jnp.float32),
               jax.ShapeDtypeStruct((4 * hdim, hdim), jnp.float32))
              for _ in range(n_layers)],
             jax.ShapeDtypeStruct((n, hdim), jnp.float32)]
    plain = jax.value_and_grad(functools.partial(net, remat=False))
    remat = jax.value_and_grad(functools.partial(net, remat=True))
    return plain, remat, avals, n_layers * n * 4 * hdim * 4


def lm_step_remat_contract(size: str = "small", vocab: int = 32768,
                           seq: int = 512, batch: int = 8):
    """Integrated pricing of the LM recipe's own ``--remat`` lever: the
    COMPLETE amp-O2 train step (create_lm + fused CE + fused_adam +
    dynamic scaler — exactly what ``examples/lm/main_amp.py`` jits) with
    per-block activation checkpointing vs without. Returns
    (remat_step, plain_step, avals, theory_bytes); theory = one [B, S,
    4H] bf16 MLP hidden per block, the dominant buffer remat drops.

    Unlike the toy-MLP remat row this prices the recipe the user
    actually runs — flash attention, fused LN, fused CE, O2 masters and
    scaler state all inside the measured computation.
    """
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models.transformer_lm import _LM_SIZES, create_lm
    from apex_tpu.optimizers import fused_adam

    policy = amp.resolve_policy("O2", verbose=False)

    def build(remat):
        model = create_lm(size, vocab_size=vocab, max_seq_len=seq,
                          remat=remat, dtype=policy.model_dtype)

        def loss_fn(p, tokens):
            logits = model.apply({"params": p}, tokens[:, :-1],
                                 train=True)
            return softmax_cross_entropy_loss(logits,
                                              tokens[:, 1:]).mean()

        init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-4),
                                               policy)
        params = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jax.numpy.zeros((2, seq), jnp.int32),
                               train=False)["params"])
        return step_fn, jax.eval_shape(init_fn, params)

    remat_step, state = build(True)
    plain_step, _ = build(False)
    avals = [state, jax.ShapeDtypeStruct((batch, seq + 1), jnp.int32)]
    hidden, layers, _ = _LM_SIZES[size]
    theory = layers * batch * seq * 4 * hidden * 2
    return remat_step, plain_step, avals, theory


def ln_memory_efficient_contract(n: int, h: int, n_layers: int = 4):
    """The round-5 LN residency answer (VERDICT r4 weak #4): apex's
    ``memory_efficient=True`` keeps the OUTPUT for backward instead of
    the input. In the pre-LN transformer position — a stack of
    ``x <- LN(x) @ W`` layers — each downstream matmul already saves the
    LN output y for its own wgrad, so the me-LN's residual is SHARED
    with it and the layer input x (the previous matmul's output) dies at
    the forward; the default variant keeps BOTH x and y live into the
    backward. A single isolated LN+matmul prices NOISY (buffer-
    assignment scheduling dominates one residual); the stack is the
    honest shape of the claim. Priced fused-vs-fused:
    (fused_fn=memory_efficient, composed_fn=default save-x), theory =
    the n_layers-1 droppable [n, h] bf16 input residuals (the first x is
    the function argument — alive either way)."""
    import jax.numpy as jnp

    from apex_tpu.kernels.layer_norm import layer_norm

    L = n_layers
    avals = ([jax.ShapeDtypeStruct((n, h), jnp.bfloat16)]
             + [jax.ShapeDtypeStruct((h, h), jnp.bfloat16)] * L
             + [jax.ShapeDtypeStruct((h,), jnp.float32),
                jax.ShapeDtypeStruct((h,), jnp.float32)])

    def make(me):
        def f(a, *rest):
            ws, g, b = rest[:L], rest[L], rest[L + 1]
            x = a
            for w in ws:
                x = layer_norm(x, g, b, memory_efficient=me) @ w
            return jnp.sum(x.astype(jnp.float32) ** 2)

        return jax.value_and_grad(f, argnums=tuple(range(L + 3)))

    return make(True), make(False), avals, (L - 1) * n * h * 2


def _tree_bytes(tree) -> int:
    return sum(l.size * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "shape"))


def resnet50_o2_ddp_step(batch_per_chip: int = 256, n_chips: int = 8,
                         image: int = 224):
    """Driver config 2 at production shape (VERDICT r4 missing #4):
    the FULL ResNet-50 amp-O2 DDP train step — the model, SGD+momentum,
    master weights, scaler, batch-stats mutation, and the grad psum over
    an 8-chip 'data' mesh (AOT topology; compile-only). Returns
    (fn, avals, state_bytes): ``state_bytes`` is the static residency
    floor — every AmpState leaf (fp16 model + fp32 masters + fp32
    momentum + stats) — so peak − floor is the activation/workspace
    overhead the compiler actually schedules."""
    import jax.numpy as jnp
    import optax

    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.models import create_model
    from apex_tpu.utils.schedule_report import topology_mesh

    policy = amp.resolve_policy(opt_level="O2", verbose=False)
    model = create_model("resnet50", num_classes=1000,
                         dtype=policy.model_dtype,
                         param_dtype=jnp.float32)
    sample = jax.ShapeDtypeStruct((2, image, image, 3), jnp.float32)
    variables = jax.eval_shape(
        lambda r, s: model.init(r, s, train=True),
        jax.ShapeDtypeStruct((2,), jnp.uint32), sample)
    params = variables["params"]
    model_state = {k: v for k, v in variables.items() if k != "params"}

    def loss_fn(p, mstate, batch):
        images, labels = batch
        outputs, mutated = model.apply(
            {"params": p, **mstate}, images, train=True,
            mutable=list(mstate.keys()) or False)
        lg = jnp.asarray(outputs, jnp.float32)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            lg, labels).mean()
        return loss, (mutated, outputs)

    optimizer = optax.chain(optax.add_decayed_weights(1e-4),
                            optax.sgd(0.1, momentum=0.9))
    init_fn, step_fn = amp.make_train_step(
        loss_fn, optimizer, policy, has_aux=True, with_model_state=True,
        grad_average_axis="data")
    state = jax.eval_shape(init_fn, params, model_state)
    mesh = topology_mesh({"data": n_chips})
    B = batch_per_chip * n_chips
    batch = (jax.ShapeDtypeStruct((B, image, image, 3), jnp.float32),
             jax.ShapeDtypeStruct((B,), jnp.int32))
    fn = shard_map(step_fn, mesh=mesh,
                   in_specs=(P(), (P("data"), P("data"))),
                   out_specs=P(), check_vma=False)
    return fn, (state, batch), _tree_bytes(state)


def bert_large_lamb_step(batch: int = 8, seq: int = 512,
                         n_pred: int = 80):
    """Driver config 4 at production shape: the FULL BERT-large seq-512
    FusedLAMB amp-O2 pretraining step (the DeepLearningExamples phase-2
    shape), single chip, compile-only. Returns (fn, avals, state_bytes)
    — floor = fp16 model + fp32 masters + LAMB m and v."""
    import jax.numpy as jnp

    from apex_tpu import amp
    from apex_tpu.kernels.xentropy import softmax_cross_entropy_loss
    from apex_tpu.models.bert import BertForPreTraining, create_bert
    from apex_tpu.optimizers import fused_lamb

    policy = amp.resolve_policy(opt_level="O2", verbose=False)
    cfg = create_bert("large", max_position_embeddings=seq)
    model = BertForPreTraining(cfg, dtype=policy.model_dtype)
    ids = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    mask = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    pos = jax.ShapeDtypeStruct((batch, n_pred), jnp.int32)
    pred_ids = jax.ShapeDtypeStruct((batch, n_pred), jnp.int32)
    nsp = jax.ShapeDtypeStruct((batch,), jnp.int32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    params = jax.eval_shape(
        lambda r, a, t, m, p_: model.init(r, a, t, m, p_, train=False),
        key, ids, ids, mask, pos)["params"]

    def loss_fn(p, batch_):
        (input_ids, token_type_ids, attention_mask, mlm_pos, mlm_ids,
         nsp_labels, dropout_rng) = batch_
        mlm_logits, nsp_logits = model.apply(
            {"params": p}, input_ids, token_type_ids, attention_mask,
            mlm_pos, train=True, rngs={"dropout": dropout_rng})
        mlm_losses = softmax_cross_entropy_loss(mlm_logits, mlm_ids)
        valid = (mlm_ids != 0).astype(jnp.float32)
        mlm = jnp.sum(mlm_losses * valid) / jnp.maximum(
            jnp.sum(valid), 1.0)
        return mlm + softmax_cross_entropy_loss(nsp_logits,
                                                nsp_labels).mean()

    init_fn, step_fn = amp.make_train_step(loss_fn, fused_lamb(6e-3),
                                           policy)
    state = jax.eval_shape(init_fn, params)
    avals = (state, (ids, ids, mask, pos, pred_ids, nsp, key))
    return step_fn, avals, _tree_bytes(state)


def _fwd_or_grad(fused_fwd, composed_fwd, with_bwd, argnums=0):
    """Shared with_bwd wrapping for the contract setups: sum-loss
    value_and_grad over both implementations, or the bare forwards."""
    if not with_bwd:
        return fused_fwd, composed_fwd
    import jax.numpy as jnp

    def mk(f):
        return jax.value_and_grad(
            lambda *a: jnp.sum(f(*a).astype(jnp.float32)),
            argnums=argnums)

    return mk(fused_fwd), mk(composed_fwd)


def causal_softmax_contract(b: int, h: int, s: int, with_bwd: bool):
    """Canonical N8 fused-causal-softmax pricing: (fused_fn, composed_fn,
    avals, theory_bytes). The kernel's contract is half I/O with per-tile
    fp32 math (apex/csrc/megatron/scaled_upper_triang_masked_softmax.h
    computes fp32 in registers over half storage); the composed path
    upcasts the whole [b, h, s, s] scores plane. Theory = the fp32-vs-bf16
    difference on one scores buffer (b·h·s·s·2)."""
    import jax.numpy as jnp

    from apex_tpu.kernels.causal_softmax import (causal_softmax,
                                                 causal_softmax_reference)

    avals = [jax.ShapeDtypeStruct((b, h, s, s), jnp.bfloat16)]
    scale = 0.125

    def fused_fwd(x):
        return causal_softmax(x, scale=scale)

    def composed_fwd(x):
        return causal_softmax_reference(x, scale=scale).astype(x.dtype)

    fused, composed = _fwd_or_grad(fused_fwd, composed_fwd, with_bwd)
    return fused, composed, avals, b * h * s * s * 2


def masked_softmax_contract(b: int, h: int, s: int, with_bwd: bool):
    """Canonical N8 arbitrary-mask softmax pricing — like
    :func:`causal_softmax_contract` but with the [b, 1, s, s] int8 mask
    operand (apex/csrc/megatron/scaled_masked_softmax.h)."""
    import jax.numpy as jnp

    from apex_tpu.kernels.masked_softmax import (masked_softmax,
                                                 masked_softmax_reference)

    avals = [jax.ShapeDtypeStruct((b, h, s, s), jnp.bfloat16),
             jax.ShapeDtypeStruct((b, 1, s, s), jnp.int8)]
    scale = 0.125

    def fused_fwd(x, m):
        return masked_softmax(x, m, scale=scale)

    def composed_fwd(x, m):
        return masked_softmax_reference(x, m, scale=scale).astype(x.dtype)

    fused, composed = _fwd_or_grad(fused_fwd, composed_fwd, with_bwd)
    return fused, composed, avals, b * h * s * s * 2


def price_contract(name: str, fused_fn: Callable, composed_fn: Callable,
                   avals: Sequence[Any],
                   theory_bytes: Optional[int] = None) -> dict:
    """Price one memory contract: same computation, fused (Pallas) vs
    composed (jnp/XLA). Returns a JSON-ready row; ``saved_peak_bytes`` is
    the compiler-certified win, ``vs_theory`` its fraction of the
    analytic contract (e.g. N*V*4 for the xentropy residual)."""
    fused = compiled_memory(fused_fn, *avals)
    composed = compiled_memory(composed_fn, *avals)
    row = {
        "contract": name,
        "backend": jax.default_backend(),
        "fused_peak_bytes": fused.peak_bytes,
        "composed_peak_bytes": composed.peak_bytes,
        "saved_peak_bytes": composed.peak_bytes - fused.peak_bytes,
        "fused_overhead_bytes": fused.live_overhead_bytes,
        "composed_overhead_bytes": composed.live_overhead_bytes,
    }
    if theory_bytes is not None:
        row["theory_bytes"] = int(theory_bytes)
        row["vs_theory"] = round(row["saved_peak_bytes"] / theory_bytes, 3)
    return row
