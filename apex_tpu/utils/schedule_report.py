"""Compile-time overlap evidence from scheduled HLO (VERDICT round-4
missing #3).

The build's perf thesis — "XLA's latency-hiding scheduler overlaps the
collectives with the remaining compute the way apex overlaps NCCL with
backward" (amp/__init__.py, parallel/distributed.py docstrings) — was
asserted in docstrings and verified nowhere. This module makes it
compiler-certified the same way utils/memory_report.py priced the memory
contracts: AOT-compile the REAL library programs for a multi-chip TPU
topology (``jax.experimental.topologies`` — no chips needed, nothing
executes) and read the evidence out of the scheduled HLO text
(``is_scheduled=true``, so textual order IS the execution schedule):

- ``collective-permute-start``/``-done`` pairs with compute ops scheduled
  strictly BETWEEN them — the 1F1B schedule's microbatch transport riding
  under stage compute (apex's ``batch_isend_irecv`` overlap);
- per-leaf grad psums COMBINED into one ``all-reduce`` op over the whole
  tuple — the reference DDP's ``allreduce_bucket`` flat-bucket coalescing
  (apex/parallel/distributed.py), done by XLA's combiner pass;
- an honest negative where the toolchain declines: this TPU compiler
  keeps ``all-reduce`` synchronous in the scheduled HLO (no -start/-done
  split; recorded, not hidden — see BASELINE.md's overlap table).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["topology_mesh", "scheduled_text", "collective_async_pairs",
           "all_reduce_bucketing", "ddp_step_program",
           "ddp_accum_step_program", "pipeline_1f1b_program",
           "ring_attention_program", "ulysses_attention_program",
           "zero_update_program"]

# one compute op between a start/done pair = the transport is riding under
# real work. On TPU every lowered compute op is one of these HLO forms.
_COMPUTE_RE = re.compile(
    r"\b(fusion|convolution|dot|custom-call|while)\(")
# result types may be tuples with spaces — key on the assigned variable
# only; the op is matched by literal substring at the call site. A
# computation root carries a "ROOT " prefix before the variable.
_ASSIGN_RE = re.compile(r"^\s*(?:ROOT )?%(\S+) = ")


def topology_mesh(axes: Dict[str, int], topology: str = "v5e:2x4"):
    """A Mesh over an AOT TPU topology (8 chips by default) — compile-only
    devices, the supported way to schedule a multi-chip program on a
    single-chip (or chipless) host."""
    from jax.experimental import topologies
    from jax.sharding import Mesh

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology)
    sizes = tuple(axes.values())
    need = int(np.prod(sizes))
    devs = topo.devices
    if need > len(devs):
        raise ValueError(f"mesh {axes} needs {need} of {len(devs)} devices")
    return Mesh(np.asarray(devs[:need]).reshape(sizes), tuple(axes))


def scheduled_text(fn, *avals, compiler_options: Optional[dict] = None
                   ) -> str:
    """Lower + compile ``fn`` at the given avals and return the scheduled
    HLO text. Nothing executes; buffers are never allocated."""
    lowered = jax.jit(fn).lower(*avals)
    compiled = (lowered.compile(compiler_options=compiler_options)
                if compiler_options else lowered.compile())
    txt = compiled.as_text()
    assert "is_scheduled=true" in txt, \
        "compiler returned unscheduled HLO; textual order is meaningless"
    return txt


def collective_async_pairs(txt: str, op: str = "collective-permute"
                           ) -> List[Dict[str, Any]]:
    """Every ``<op>-start``/``<op>-done`` pair in the scheduled module,
    with the number of compute ops (fusions/convolutions/dots/
    custom-calls) scheduled strictly between start and done — the
    latency-hiding window. Pairs are matched within their computation
    (the schedule orders ops per computation)."""
    pairs = []
    lines = txt.splitlines()
    open_starts: Dict[str, int] = {}
    for i, line in enumerate(lines):
        if line.lstrip().startswith("ENTRY") or line.strip() == "}":
            # computation boundary: any unmatched start cannot legally
            # remain open across it
            open_starts.clear()
        if f"{op}-start(" in line:
            m = _ASSIGN_RE.match(line)
            if m:
                open_starts[m.group(1)] = i
            continue
        if f"{op}-done(" in line:
            ref = re.search(rf"{op}-done\(%(\S+?)\)", line)
            if not ref or ref.group(1) not in open_starts:
                continue
            s = open_starts.pop(ref.group(1))
            n_compute = sum(1 for ln in lines[s + 1:i]
                            if _COMPUTE_RE.search(ln))
            pairs.append({"start_line": s, "done_line": i,
                          "ops_between": i - s - 1,
                          "compute_between": n_compute})
    return pairs


def all_reduce_bucketing(txt: str) -> Dict[str, Any]:
    """The DDP coalescing evidence: how many ``all-reduce`` ops the
    module schedules and how many tensors ride in each (tuple operands).
    One op carrying every grad leaf = the flat-bucket allreduce apex
    builds by hand with flatten/unflatten."""
    ops = []
    for line in txt.splitlines():
        stripped = line.strip()
        m = re.match(r"(?:ROOT )?%\S+ = .*\ball-reduce(?:-start)?\((.*?)\)",
                     stripped)
        if m:
            ops.append(m.group(1).count("%"))
    return {"n_all_reduce_ops": len(ops), "tensors_per_op": ops,
            "async_split": txt.count("all-reduce-start")}


# ---------------------------------------------------------------- programs
# The REAL library tiers, built small enough to compile fast but with the
# structure the claims are about.

def ddp_step_program(n_layers: int = 6, width: int = 512,
                     batch: int = 64, accum_steps: int = 1):
    """The actual amp O2 DDP train step (make_train_step +
    grad_average_axis='data' + fused_adam), shard_mapped over an 8-chip
    'data' mesh. Returns (fn, avals, n_grad_leaves) — the leaf count is
    what the bucketing evidence is checked against (unlike the 2-tuple
    sibling builders).

    ``accum_steps=N > 1`` builds the SAME model/mesh/global batch under
    in-jit microbatch accumulation: the batch carries a leading
    microbatch axis of size N (per-microbatch rows sharded over 'data')
    and the grads accumulate through a lax.scan BEFORE the psum — one
    parameter, so the N=1 baseline and the accumulation program can
    never drift apart while their all-reduce counts are being compared
    (bench_schedule.py ddp_accum, tests/tpu)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.optimizers import fused_adam

    mesh = topology_mesh({"data": 8})

    def loss_fn(params, batch_):
        x, y = batch_
        h = x
        for w in params:
            h = jnp.tanh(h @ jnp.asarray(w, h.dtype))
        return jnp.mean((jnp.asarray(h, jnp.float32) - y) ** 2)

    policy = amp.resolve_policy(opt_level="O2", verbose=False)
    init_fn, step_fn = amp.make_train_step(loss_fn, fused_adam(1e-3),
                                           policy,
                                           grad_average_axis="data",
                                           accum_steps=accum_steps)
    params = [jax.ShapeDtypeStruct((width, width), jnp.float32)
              for _ in range(n_layers)]
    state = jax.eval_shape(init_fn, params)
    if accum_steps == 1:
        shape, bspec = (batch, width), P("data")
    else:
        shape = (accum_steps, batch // accum_steps, width)
        bspec = P(None, "data")
    bat = (jax.ShapeDtypeStruct(shape, jnp.bfloat16),
           jax.ShapeDtypeStruct(shape, jnp.float32))
    fn = shard_map(step_fn, mesh=mesh,
                   in_specs=(P(), (bspec, bspec)),
                   out_specs=(P(), P()), check_vma=False)
    return fn, (state, bat), n_layers


def ddp_accum_step_program(n_layers: int = 6, width: int = 512,
                           batch: int = 64, accum_steps: int = 4):
    """:func:`ddp_step_program` at ``accum_steps=N`` — the scheduled HLO
    must show the same ONE bucketed grad all-reduce per optimizer window
    as the plain step, not N of them (the acceptance certificate for the
    accumulation tentpole: allreduce traffic per optimizer step cut N×).
    Returns (fn, avals, n_grad_leaves, accum_steps)."""
    fn, avals, n_leaves = ddp_step_program(n_layers, width, batch,
                                           accum_steps)
    return fn, avals, n_leaves, accum_steps


def pipeline_1f1b_program(pp: int = 8, microbatches: int = 16,
                          width: int = 256, mb_rows: int = 8):
    """The actual hand-scheduled 1F1B (pipeline_parallel.schedules.
    forward_backward_1f1b) over an 8-stage 'pipe' mesh. Returns
    (fn, avals)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer import pipeline_parallel as pp_mod

    mesh = topology_mesh({"pipe": pp})

    def stage_fn(sp, x):
        return jnp.tanh(x @ sp["w"])

    def loss_fn(y, tgt):
        return jnp.mean((y - tgt) ** 2)

    def run(sp, xs, tgt):
        return pp_mod.forward_backward_1f1b(
            stage_fn, loss_fn, sp, xs, tgt, num_stages=pp)

    sp = {"w": jax.ShapeDtypeStruct((width, width), jnp.float32)}
    xs = jax.ShapeDtypeStruct((microbatches, mb_rows, width), jnp.float32)
    tgt = jax.ShapeDtypeStruct((microbatches, mb_rows, width), jnp.float32)
    fn = shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                   out_specs=(P(), P()), check_vma=False)
    return fn, (sp, xs, tgt)


def ring_attention_program(context: int = 8, b: int = 1, h: int = 4,
                           local_seq: int = 256, d: int = 128):
    """The actual ring-attention forward+backward
    (transformer.context_parallel.ring_attention) over an 8-chip
    'context' mesh — the long-context tier's KV rotation. Returns
    (fn, avals)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.context_parallel import ring_attention

    mesh = topology_mesh({"context": context})

    def run(q, k, v):
        def loss(q, k, v):
            o = ring_attention(q, k, v, axis_name="context", causal=True)
            return jnp.sum(jnp.asarray(o, jnp.float32) ** 2)

        l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, grads

    aval = jax.ShapeDtypeStruct((b, h, local_seq, d), jnp.bfloat16)
    fn = shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                   out_specs=(P(), P()), check_vma=False)
    return fn, (aval, aval, aval)


def ulysses_attention_program(context: int = 8, b: int = 1, h: int = 8,
                              local_seq: int = 256, d: int = 128):
    """The actual Ulysses (all-to-all) sequence-parallel attention
    fwd+bwd (transformer.context_parallel.ulysses_attention) over an
    8-chip 'context' mesh. Returns (fn, avals)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.transformer.context_parallel import ulysses_attention

    mesh = topology_mesh({"context": context})

    def run(q, k, v):
        def loss(q, k, v):
            o = ulysses_attention(q, k, v, axis_name="context",
                                  causal=True)
            return jnp.sum(jnp.asarray(o, jnp.float32) ** 2)

        return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

    aval = jax.ShapeDtypeStruct((b, h, local_seq, d), jnp.bfloat16)
    fn = shard_map(run, mesh=mesh, in_specs=(P(), P(), P()),
                   out_specs=(P(), P()), check_vma=False)
    return fn, (aval, aval, aval)


def zero_update_program(width: int = 1024, n_layers: int = 4):
    """The contrib ZeRO update's collective skeleton (psum_scatter the
    grads, shard-local math, all_gather the params) over an 8-way 'data'
    mesh. Returns (fn, avals)."""
    from apex_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = topology_mesh({"data": 8})

    def update(params, grads):
        out = []
        for p, g in zip(params, grads):
            gs = jax.lax.psum_scatter(g, "data", scatter_dimension=0,
                                      tiled=True)
            ps = jax.lax.dynamic_slice_in_dim(
                p, jax.lax.axis_index("data") * (p.shape[0] // 8),
                p.shape[0] // 8, 0)
            new = ps - 1e-3 * gs
            out.append(jax.lax.all_gather(new, "data", axis=0, tiled=True))
        return out

    params = [jax.ShapeDtypeStruct((width, width), jnp.float32)
              for _ in range(n_layers)]
    grads = [jax.ShapeDtypeStruct((width, width), jnp.float32)
             for _ in range(n_layers)]
    fn = shard_map(update, mesh=mesh, in_specs=(P(), P()),
                   out_specs=P(), check_vma=False)
    return fn, (params, grads)
