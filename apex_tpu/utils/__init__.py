from .checkpoint import (AsyncCheckpointer, latest_checkpoint,  # noqa: F401
                         load_checkpoint, save_checkpoint)
from .pytree import flatten, unflatten, flatten_tree, unflatten_tree  # noqa: F401
from .sharded_checkpoint import (AsyncShardedCheckpointer,  # noqa: F401
                                 load_sharded, save_sharded)
