from .pytree import flatten, unflatten, flatten_tree, unflatten_tree  # noqa: F401
