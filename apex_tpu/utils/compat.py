"""jax version compatibility shims.

The axon TPU toolchain ships a jax with top-level :func:`jax.shard_map`
whose keyword for disabling the varying-manual-axes check is
``check_vma``; older hermetic jax builds (e.g. 0.4.x CPU containers)
only have ``jax.experimental.shard_map.shard_map`` and spell the same
switch ``check_rep``. Library, bench and test call sites all use the
axon idiom (``check_vma=False``); this module resolves ONE callable at
import time that accepts it everywhere:

- ``jax.shard_map`` exists → returned untouched (the axon fast path).
- only the experimental fallback exists → wrapped so ``check_vma=`` is
  translated to ``check_rep=`` when the signature has it, or silently
  dropped when it has neither.

Route module-level imports through here instead of ``from jax import
shard_map`` — on a jax without the top-level symbol that import is an
ImportError at *collection* time, which is how 13 test files used to
error out before running a single test.

Usage::

    from apex_tpu.utils.compat import shard_map
"""

from __future__ import annotations

import functools
import inspect

__all__ = ["shard_map"]


def _resolve_shard_map():
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):    # C-accelerated / unsignaturable
        return fn
    if "check_vma" in params:
        return fn
    translate = "check_rep" in params

    @functools.wraps(fn)
    def _shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            vma = kwargs.pop("check_vma")
            if translate and "check_rep" not in kwargs:
                kwargs["check_rep"] = vma
        return fn(*args, **kwargs)

    return _shard_map


shard_map = _resolve_shard_map()
