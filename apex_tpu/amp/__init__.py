"""apex_tpu.amp — mixed precision with apex's API shape on a functional core.

Reference surface (apex/amp/frontend.py, handle.py, _process_optimizer.py):

- ``amp.initialize(model, optimizer, opt_level=..., ...)``
- ``with amp.scale_loss(loss, optimizer) as scaled: scaled.backward()``
- ``amp.state_dict()`` / ``amp.load_state_dict()``
- ``amp.master_params(optimizer)``

TPU mapping: the imperative pieces survive as thin facades; the real engine is
:func:`make_train_step`, which builds ONE jitted step implementing apex's
observable order of operations (apex/amp/_process_optimizer.py —
post_backward_with_master_weights + wrapped step):

    scaled loss → grads → unscale into fp32 master grads (+found_inf)
    → lax.cond(found_inf): skip (scale halves, optimizer state does NOT
      advance) / apply update to master weights
    → master→model half copy → scaler schedule update

bf16 is the default half dtype (BASELINE.json), fp16 selectable.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..log_util import get_logger
from . import lists  # noqa: F401
from .autocast import (active_policy, autocast, cast_op_inputs,
                       op_compute_dtype, resolve_dtype, trace_token)
from .policy import Policy, default_is_norm_param, opt_levels, resolve_policy
from .scaler import (LossScaler, ScalerState, init_scaler, scale_loss as
                     _scale_loss_fn, scaler_metrics, unscale,
                     unscale_with_stashed, update_scale)

__all__ = [
    "Policy", "LossScaler", "ScalerState", "opt_levels", "resolve_policy",
    "initialize", "scale_loss", "master_params", "state_dict",
    "load_state_dict", "init_scaler", "scaler_metrics", "unscale",
    "unscale_with_stashed", "update_scale", "make_train_step",
    "to_microbatches", "AmpState",
    "half_function", "float_function", "promote_function",
    "register_half_function", "register_float_function",
    "register_promote_function",
    "autocast", "active_policy", "op_compute_dtype", "resolve_dtype",
    "cast_op_inputs", "trace_token",
]

# Global registry mirroring apex/amp/_amp_state.py — class AmpState: frontends
# register scalers here so module-level state_dict()/scale_loss() work.
class _AmpState:
    def __init__(self):
        self.loss_scalers = []
        self.opt_properties = None
        self.verbosity = 1


_amp_state = _AmpState()


_logger = get_logger("amp")


def maybe_print(msg, verbosity_level=1):
    """apex/amp/_amp_state.py — maybe_print, routed through the package
    logger (apex_tpu.get_logger) rather than stdout."""
    if _amp_state.verbosity >= verbosity_level:
        _logger.info(msg)


# ------------------------------------------------------------------ imperative
class _InitializedModel(NamedTuple):
    """Return bundle of :func:`initialize` — the policy-applied model pieces."""

    apply_fn: Callable
    params: Any
    policy: Policy

    def __call__(self, *args, **kwargs):
        return self.apply_fn(*args, **kwargs)


def initialize(model, optimizers=None, opt_level="O1", enabled=True,
               num_losses=1, verbosity=1, min_loss_scale=None,
               max_loss_scale=2.0 ** 24, **overrides):
    """apex/amp/frontend.py — initialize, reshaped for functional models.

    ``model`` is ``(apply_fn, params)`` (or a flax Module bound later by the
    caller); ``optimizers`` an optax GradientTransformation (or list). Returns
    ``(initialized_model, optimizers)`` where the model bundle carries the
    resolved Policy and policy-cast params, and per-loss LossScalers are
    registered for :func:`scale_loss` / :func:`state_dict`.
    """
    _amp_state.verbosity = verbosity
    policy = resolve_policy(opt_level=opt_level, enabled=enabled, **overrides)
    _amp_state.opt_properties = policy
    _amp_state.loss_scalers = [
        LossScaler(policy.loss_scale, min_loss_scale=min_loss_scale,
                   max_loss_scale=max_loss_scale)
        for _ in range(num_losses)
    ]

    def bundle_one(m):
        if isinstance(m, tuple) and len(m) == 2:
            apply_fn, params = m
        else:
            apply_fn, params = m, None
        if params is not None:
            params = policy.cast_params(params)

        def policy_apply(p, *args, **kwargs):
            args = policy.cast_to_compute(args)
            # O1 engine: policy-aware ops inside apply_fn consult the
            # ambient policy's tables (apex applies its patches here too —
            # _initialize.py installs them during initialize)
            with autocast(policy):
                return apply_fn(p, *args, **kwargs)

        return _InitializedModel(
            policy_apply if apply_fn is not None else None, params, policy)

    # apex accepts a single model/optimizer or lists of either and returns
    # the same shape (frontend.py — initialize handles both)
    models_in_list = isinstance(model, list)
    bundle = [bundle_one(m) for m in model] if models_in_list \
        else bundle_one(model)
    if optimizers is None:
        return bundle
    return bundle, optimizers


# --- legacy registry API (apex/amp/amp.py — half_function, float_function,
# promote_function, register_*). Apex monkey-patches call sites; the
# functional analogue wraps the callable so its floating array args are cast
# on the way in — same observable op-level dtype policy, no patching.
def _current_half_dtype():
    """Active half dtype, or None when amp is inactive (uninitialized,
    enabled=False, or O0) — apex's wrappers no-op when amp isn't on."""
    pol = _amp_state.opt_properties
    if pol is None or not pol.enabled or pol.compute_dtype == jnp.float32:
        return None
    return pol.compute_dtype


def _is_float_array(a):
    # only real arrays are cast (apex casts only torch Tensors): Python
    # scalars/lists pass through untouched, preserving jax weak typing.
    import numpy as np

    return isinstance(a, (jax.Array, np.ndarray)) and \
        jnp.issubdtype(a.dtype, jnp.floating)


def _cast_call(fn, args, kwargs, dtype):
    if dtype is None:
        return fn(*args, **kwargs)

    def one(a):
        return jnp.asarray(a, dtype) if _is_float_array(a) else a

    return fn(*(one(a) for a in args),
              **{k: one(v) for k, v in kwargs.items()})


def half_function(fn):
    """Wrap ``fn`` to run in the policy's half dtype (amp.py — half_function
    / FP16_FUNCS entry semantics). No-op while amp is inactive."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        return _cast_call(fn, args, kwargs, _current_half_dtype())

    return wrapped


def float_function(fn):
    """Wrap ``fn`` to run in fp32 (amp.py — float_function / FP32_FUNCS).
    No-op while amp is inactive."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        dtype = jnp.float32 if _current_half_dtype() is not None else None
        return _cast_call(fn, args, kwargs, dtype)

    return wrapped


def promote_function(fn):
    """Wrap ``fn`` to promote floating ARRAY args (positional and keyword)
    to the widest floating dtype among them (amp.py — promote_function /
    CASTS). Non-array args never participate, so Python scalars keep their
    weak typing."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        floats = [a for a in list(args) + list(kwargs.values())
                  if _is_float_array(a)]
        if _current_half_dtype() is None or len(floats) < 2:
            return fn(*args, **kwargs)
        target = jnp.result_type(*[a.dtype for a in floats])
        return _cast_call(fn, args, kwargs, target)

    return wrapped


def _register(module, name, wrapper):
    setattr(module, name, wrapper(getattr(module, name)))


def register_half_function(module, name):
    """amp.py — register_half_function(module, function_name)."""
    _register(module, name, half_function)


def register_float_function(module, name):
    _register(module, name, float_function)


def register_promote_function(module, name):
    _register(module, name, promote_function)


@contextlib.contextmanager
def scale_loss(loss, optimizer=None, loss_id=0, model=None,
               delay_unscale=False):
    """apex/amp/handle.py — scale_loss context manager (imperative facade).

    Yields the scaled loss; user differentiates it however they like and later
    calls ``scaler.unscale``/``update_scale`` — or, preferably, uses
    :func:`make_train_step` which does all of this inside jit
    (``accum_steps=N`` for the compiled equivalent of the pattern below).

    ``delay_unscale=True`` is apex's gradient-accumulation handshake: the
    scaler schedule does NOT advance on exit, and the caller defers
    unscaling by stashing grads across iterations —
    ``stash = scaler.unscale(grads)`` on the first microbatch, then
    ``stash = scaler.unscale_with_stashed(grads, stash)`` (the
    ``amp_C.multi_tensor_axpby`` fusion; flat 1-D buffers route through
    ``kernels.multi_tensor.fused_axpby``) on the rest. Overflow flags
    OR-accumulate across the window, so ``update_scale()`` on the final
    (``delay_unscale=False``) iteration skips/backs off once per window —
    stashed-grad parity with apex's delayed path.
    """
    if not _amp_state.loss_scalers:
        _amp_state.loss_scalers = [LossScaler("dynamic")]
    scaler = _amp_state.loss_scalers[loss_id]
    yield scaler.scale_loss(jnp.asarray(loss))
    if not delay_unscale:
        scaler.update_scale()


def master_params(optimizer_or_state):
    """apex/amp/frontend.py — master_params: the fp32 master pytree."""
    if isinstance(optimizer_or_state, AmpState):
        return (optimizer_or_state.master_params
                if optimizer_or_state.master_params is not None
                else optimizer_or_state.params)
    if hasattr(optimizer_or_state, "init") and hasattr(optimizer_or_state,
                                                       "update"):
        raise TypeError(
            "master_params expects the AmpState train state (or a params "
            "pytree), not an optax GradientTransformation — unlike apex, the "
            "optimizer object holds no parameters here.")
    return optimizer_or_state


def state_dict():
    """Serialize all registered loss scalers (frontend.py — state_dict)."""
    return {f"loss_scaler{i}": s.state_dict()
            for i, s in enumerate(_amp_state.loss_scalers)}


def load_state_dict(sd):
    for i, s in enumerate(_amp_state.loss_scalers):
        key = f"loss_scaler{i}"
        if key in sd:
            s.load_state_dict(sd[key])


# ------------------------------------------------------------------ functional
@jax.tree_util.register_pytree_node_class
class AmpState:
    """Train-state pytree: model params (+ optional fp32 masters), optimizer
    state, the loss-scaler state, and any mutable model state (flax
    collections like BatchNorm's batch_stats) — everything one jitted step
    touches."""

    def __init__(self, params, master_params, opt_state, scaler,
                 model_state=None):
        self.params = params
        self.master_params = master_params
        self.opt_state = opt_state
        self.scaler = scaler
        self.model_state = model_state

    def tree_flatten(self):
        return (self.params, self.master_params, self.opt_state,
                self.scaler, self.model_state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    def replace(self, **kw):
        vals = dict(params=self.params, master_params=self.master_params,
                    opt_state=self.opt_state, scaler=self.scaler,
                    model_state=self.model_state)
        vals.update(kw)
        return AmpState(**vals)


def to_microbatches(batch, accum_steps: int):
    """Reshape every array leaf ``[B, ...]`` → ``[N, B/N, ...]`` — the
    leading microbatch scan axis :func:`make_train_step`'s
    ``accum_steps=N`` expects. Works on jax and numpy leaves alike (host
    pipelines can reshape before ``device_put``); identity at ``N=1`` so
    data paths stay shape-stable. Leaves whose leading dim doesn't
    divide raise. PRNG keys are leaves too: exclude them and split
    per-microbatch instead (``jax.random.split(key, N)``) — a reshape
    would duplicate, not fork, the randomness."""
    accum_steps = int(accum_steps)
    if accum_steps == 1:
        return batch

    def one(a):
        if not getattr(a, "ndim", 0):
            raise ValueError(
                "to_microbatches needs a leading batch dim on every "
                f"leaf; got a scalar leaf {a!r} — reshape only the "
                "batched leaves (and split PRNG keys) yourself")
        b = a.shape[0]
        if b % accum_steps:
            raise ValueError(
                f"leading batch dim {b} does not divide by "
                f"accum_steps {accum_steps}")
        return a.reshape((accum_steps, b // accum_steps) + a.shape[1:])

    return jax.tree_util.tree_map(one, batch)


def make_train_step(loss_fn: Callable, optimizer, policy: Policy,
                    has_aux: bool = False,
                    is_norm_param: Optional[Callable] = None,
                    with_model_state: bool = False,
                    grad_average_axis=None,  # str | tuple[str, ...] | None
                    gradient_predivide_factor: float = 1.0,
                    grad_average_mask=None,
                    overflow_sync_axes=None,
                    grad_fn: Optional[Callable] = None,
                    telemetry=False,
                    accum_steps: int = 1,
                    accum_dtype=jnp.float32):
    """Build ``(init_fn, step_fn)`` implementing the apex iteration (§4.2 of
    the survey) as one jitted function.

    ``loss_fn(params, batch) -> loss`` (params arrive in the policy's model
    dtype). ``optimizer`` is an optax GradientTransformation whose update runs
    on fp32 master weights when the policy asks for them.

    With ``with_model_state=True`` the loss_fn signature becomes
    ``loss_fn(params, model_state, batch) -> (loss, new_model_state)`` (or
    ``(loss, (new_model_state, aux))`` under has_aux) — the functional home
    for flax mutable collections such as BatchNorm batch_stats, and
    ``init_fn(params, model_state)`` stores it on the AmpState.

    ``grad_average_axis`` names a mesh axis — or a TUPLE of axes (the
    lax collectives accept either; e.g. ``("data", "context")`` for DDP
    composed outside a context-parallel ring) — to mean-reduce gradients
    over: the apex DDP composition point (apex/parallel/distributed.py
    averages grads over the world inside its allreduce hooks; here it is
    one psum under shard_map/pmap). ``gradient_predivide_factor`` mirrors apex DDP's
    option of the same name: grads are divided by the factor BEFORE the
    sum and by world/factor after, trading overflow headroom in half-precision
    sums. Overflow detection runs on the *reduced* grads, so any rank's inf
    skips the step on all ranks, same as NCCL allreduce propagating infs.

    ``grad_average_mask``: optional pytree of bools matching the grads
    structure. True (default) → allreduce-mean; False → the param is
    sharded over ``grad_average_axis`` (expert-parallel weights, ZeRO
    shards): its grad is scaled by 1/world but never psummed.

    ``overflow_sync_axes``: mesh axes to pmax ``found_inf`` over. Whenever
    ANY param is shard-local to an axis (pipe-stage chunks, TP kernel
    shards, masked expert leaves), its infs don't ride a grad psum to the
    other ranks the way apex's NCCL allreduce propagates them — name every
    such axis here or ranks can disagree on skip-vs-step and the scaler
    state desynchronizes. Defaults to ``(grad_average_axis,)`` when a
    ``grad_average_mask`` is given.

    Skip-on-overflow matches apex: the optimizer state does NOT advance on a
    skipped step (apex/amp/_process_optimizer.py skips ``optimizer.step``
    entirely), and the loss scale halves via the scaler schedule.

    Skip-on-overflow is implemented as a scalar-predicate select, so the
    INVARIANT a swapped-in optimizer must honor is: ``optimizer.update``
    must be TOTAL on non-finite grads — it is evaluated unconditionally
    (both sides of the select exist in the traced program), and an update
    that raised, asserted, or produced side effects on inf/NaN inputs
    would fire on every overflow step even though its result is
    discarded. Every optax/apex_tpu optimizer satisfies this (pure
    arithmetic: garbage in, discarded garbage out); a custom
    transformation with host callbacks or value-dependent python control
    flow would not.

    ``grad_fn``: custom loss+gradient producer replacing the internal
    ``jax.grad`` — the composition point for hand-scheduled backward passes
    (pipeline 1F1B). Signature
    ``grad_fn(params, batch, loss_scale) -> (loss, grads)`` where ``loss``
    is the UNSCALED scalar and ``grads`` are SCALED by ``loss_scale``
    (exactly what ``forward_backward_1f1b(..., loss_scale=...)`` returns) —
    everything downstream (grad averaging, unscale, found_inf skip-step,
    master-weight copy, scaler schedule) applies unchanged. When given,
    ``loss_fn`` is ignored and may be None; incompatible with ``has_aux``,
    ``with_model_state``, and ``accum_steps`` (see below).

    ``accum_steps``: microbatch gradient accumulation INSIDE the jitted
    step — apex's large-batch recipe (``amp.scale_loss(...,
    delay_unscale=True)`` + ``amp_C.multi_tensor_axpby``), compiled. With
    ``accum_steps=N > 1`` the step takes a batch whose every leaf carries
    a leading microbatch axis of size N (``[N, B/N, ...]``) and runs a
    ``lax.scan`` over the N microbatches, accumulating the SCALED grads
    into an ``accum_dtype`` accumulator (fp32 by default; pass the model
    dtype to halve accumulator HBM at apex-O3-style risk). Grad
    averaging (the ``grad_average_axis`` psum), unscale + ``found_inf``,
    the overflow-skip select, the optimizer update, and the scaler
    schedule then run ONCE per window — cutting DDP allreduce traffic
    and scaler/unscale arithmetic N× per optimizer step (certified by
    the ``comm.ddp.allreduce.*`` trace-time counters and the
    ``bench_schedule.py ddp_accum`` scheduled-HLO leg). Semantics:

    - the reported/optimized ``loss`` is the MEAN over the window's
      microbatches (grads are averaged by N before unscale), so a window
      equals one step on the concatenated batch up to reduction order;
    - a non-finite grad in ANY microbatch poisons the accumulator
      (inf/NaN survive summation), so the WHOLE window is skipped with
      optimizer state frozen and the scale backed off once —
      ``delay_unscale=True``'s deferred overflow check;
    - the scaler schedule advances once per WINDOW (``scale_window``
      counts optimizer steps, not microbatches), identical to apex
      skipping ``update_scale`` on delayed iterations;
    - ``model_state`` threads through the scan carry (microbatch i+1
      sees microbatch i's BatchNorm stats); under ``has_aux`` the aux is
      stacked over the window (leading axis N);
    - telemetry emits ONE callback per window, with ``accum_steps`` in
      the record;
    - incompatible with ``grad_fn``: hand-scheduled producers (1F1B)
      stream their own microbatches — compose accumulation OUTSIDE such
      a producer by summing its scaled grads across windows yourself.

    ``telemetry``: truthy bakes structured in-jit telemetry into the
    step — ONE ``jax.debug.callback`` per executed step streams the
    metrics dict plus the fp32 grad norm and the scaler trajectory
    (``apex_tpu.telemetry.scaler_metrics``) to the telemetry registry
    under tag ``"amp"`` (no extra device syncs; the host sink also
    stamps ``step_time_s``). Pass ``True`` to use the process-default
    registry — resolved at CALLBACK time, so sinks can be reconfigured
    without retracing — or a ``telemetry.MetricsRegistry`` to pin one.
    Read at TRACE time (docs/telemetry.md): flip before the first call
    of the jitted step.
    """
    if grad_fn is not None and (has_aux or with_model_state):
        raise ValueError("grad_fn is incompatible with has_aux/"
                         "with_model_state — the custom producer returns "
                         "only (loss, grads)")
    accum_steps = int(accum_steps)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if grad_fn is not None and accum_steps > 1:
        raise ValueError(
            "accum_steps is incompatible with grad_fn — hand-scheduled "
            "producers (pipeline 1F1B) already stream their own "
            "microbatches; to accumulate across windows, sum the SCALED "
            "grads your grad_fn returns outside this step instead")

    def init_fn(params, model_state=None):
        params32 = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, jnp.float32)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            params)
        model_params = policy.cast_params(params32, is_norm_param)
        masters = params32 if policy.wants_master_weights else None
        if masters is not None:
            # fp32-passthrough leaves (keep_batchnorm_fp32 norm params) come
            # out of cast_params as the *same* jax.Array as the master leaf;
            # a donated AmpState would then hand one buffer to the runtime
            # twice (PJRT rejects double donation). Copy to break aliasing.
            model_params = jax.tree_util.tree_map(
                lambda m, p: jnp.array(p, copy=True) if p is m else p,
                masters, model_params)
        opt_params = masters if masters is not None else model_params
        opt_state = optimizer.init(opt_params)
        scaler = init_scaler(policy.loss_scale)
        return AmpState(model_params, masters, opt_state, scaler, model_state)

    def step_fn(state: AmpState, batch):
        scaler = state.scaler
        if policy.compute_dtype != jnp.float32:
            # O1's patched-call-site input casts / O2-O3's patched forward
            # (apex/amp/_initialize.py — patch_forward): floating inputs enter
            # the model in the compute dtype; int leaves untouched.
            batch = policy.cast_to_compute(batch)

        def scaled_loss_fn(p, mstate, mb):
            if with_model_state:
                out = loss_fn(p, mstate, mb)
                if has_aux:
                    loss, (ms, aux) = out
                else:
                    loss, ms = out
                    aux = None
            else:
                out = loss_fn(p, mb)
                if has_aux:
                    loss, aux = out
                else:
                    loss, aux = out, None
                ms = None
            return _scale_loss_fn(loss, scaler), (loss, aux, ms)

        def mb_grads(mstate, mb):
            """SCALED grads + (unscaled loss, aux, new model_state) of one
            microbatch — the per-iteration backward of apex's recipe."""
            return jax.grad(lambda p: scaled_loss_fn(p, mstate, mb),
                            has_aux=True)(state.params)

        # O1 engine active for the whole traced forward+backward: FP32_FUNCS
        # ops (softmax/norms/losses) lift themselves to fp32, FP16_FUNCS
        # (matmul/conv) drop to half — the trace-time equivalent of apex's
        # table-driven call-site patches (amp/lists/, SURVEY P6).
        with autocast(policy):
            if grad_fn is not None:
                loss, grads = grad_fn(state.params, batch,
                                      scaler.loss_scale)
                aux, new_model_state = None, None
            elif accum_steps == 1:
                grads, (loss, aux, new_model_state) = mb_grads(
                    state.model_state, batch)
            else:
                # apex delay_unscale=True, compiled: SCALED grads
                # accumulate across the window (axpby with a=b=1 here;
                # the single 1/scale pass comes after the loop), losses
                # average, and every per-step reduction below this scan
                # — psum, unscale, found_inf, optimizer, scaler — runs
                # once per WINDOW. A non-finite microbatch grad survives
                # the summation (inf+x=inf, inf-inf=nan), so the
                # deferred overflow check still catches it.
                def _zero(p):
                    p = jnp.asarray(p)
                    dt = accum_dtype if jnp.issubdtype(p.dtype,
                                                       jnp.floating) \
                        else p.dtype
                    return jnp.zeros(p.shape, dt)

                def _add(a, g):
                    if jnp.issubdtype(jnp.asarray(a).dtype, jnp.floating):
                        return a + jnp.asarray(g, a.dtype)
                    return jnp.asarray(g)

                def body(carry, mb):
                    acc, mstate, loss_sum = carry
                    g, (loss, aux, ms) = mb_grads(mstate, mb)
                    acc = jax.tree_util.tree_map(_add, acc, g)
                    return (acc, ms,
                            loss_sum + jnp.asarray(loss, jnp.float32)), aux

                init = (jax.tree_util.tree_map(_zero, state.params),
                        state.model_state, jnp.float32(0.0))
                (grads, new_model_state, loss_sum), aux = jax.lax.scan(
                    body, init, batch, length=accum_steps)
                loss = loss_sum / accum_steps
                # grads hold the SUM of scaled microbatch grads; average
                # so the window optimizes the mean microbatch loss (one
                # elementwise pass — kept separate from unscale's 1/scale
                # so accum_steps=N stays bitwise-comparable to a manual
                # sum-then-divide accumulation)
                grads = jax.tree_util.tree_map(
                    lambda g: g / accum_steps
                    if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
                    else g, grads)
                if not has_aux:
                    aux = None
        if grad_average_axis is not None:
            # comm health: this inlined DDP reduction is the step's bucket
            # allreduce — account bytes/leaves at trace time. With a
            # grad_average_mask, mask=False leaves never ride a
            # collective (scaled locally below), so only the True leaves
            # count toward the allreduce payload.
            from apex_tpu import telemetry as _tele_acct

            if grad_average_mask is None:
                _tele_acct.account_collective("ddp.allreduce", grads)
            else:
                _tele_acct.account_collective("ddp.allreduce", [
                    g for g, m in zip(
                        jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(grad_average_mask)) if m])
            # the reported loss is the global-batch mean, not one shard's
            # local value (the reference recipe all-reduces its metrics:
            # examples/imagenet/main_amp.py — reduce_tensor)
            loss = jax.lax.pmean(loss, grad_average_axis)
            # apex DDP's flat-bucket allreduce-mean, as one psum over the
            # named axis. Compiler-certified (bench_schedule.py, BASELINE
            # overlap table): XLA's combiner buckets every per-leaf psum
            # into ONE all-reduce — apex's flatten/allreduce_bucket — and
            # schedules it after the last grad producer; on this
            # toolchain the op itself stays sync in HLO (honest negative,
            # pinned by tests/tpu/test_schedule_overlap.py).
            world = jax.lax.psum(1, grad_average_axis)
            pre = gradient_predivide_factor

            def avg(g):
                return jax.lax.psum(g / pre, grad_average_axis) \
                    * (pre / world)

            if grad_average_mask is None:
                grads = jax.tree_util.tree_map(avg, grads)
            else:
                # per-leaf reduction rule (apex analogue: per-param process
                # groups in contrib DistributedFusedAdam). mask True →
                # allreduce-mean (replicated params); False → the leaf is
                # SHARDED over the axis (e.g. expert-parallel weights whose
                # complete grad already arrived via the all_to_all
                # transpose): scale by 1/world only, never psum — a psum
                # would sum unrelated shards' parameters together.
                grads = jax.tree_util.tree_map(
                    lambda g, m: avg(g) if m else g / world,
                    grads, grad_average_mask)
        use_masters = state.master_params is not None
        cur = state.master_params if use_masters else state.params
        # Master-weight runs unscale into fp32 master grads; without masters
        # (O0/O1/O3) grads stay in each param's own dtype so the optimizer
        # state dtypes match what optimizer.init saw (apex O3 is pure-half).
        unscaled, found_inf = unscale(grads, scaler, jnp.float32)
        sync_axes = overflow_sync_axes
        if isinstance(sync_axes, str):
            sync_axes = (sync_axes,)
        if sync_axes is None and grad_average_axis is not None \
                and grad_average_mask is not None:
            # grad_average_axis may itself be a tuple of axes — flatten,
            # never nest (pmax would read a nested tuple as one axis name)
            sync_axes = (tuple(grad_average_axis)
                         if isinstance(grad_average_axis, tuple)
                         else (grad_average_axis,))
        if sync_axes:
            # shard-local leaves never pass through a grad psum, so their
            # infs don't propagate to other ranks the way apex's NCCL
            # allreduce propagates them — sync the flag explicitly or ranks
            # would disagree on skip-vs-step and the scaler state diverges.
            found_inf = jax.lax.pmax(
                jnp.asarray(found_inf, jnp.float32),
                tuple(sync_axes)).astype(jnp.bool_)
        if use_masters:
            master_grads = unscaled
        else:
            master_grads = jax.tree_util.tree_map(
                lambda g, p: jnp.asarray(g, jnp.asarray(p).dtype),
                unscaled, cur)

        # Overflow skip as a scalar-predicate SELECT, not lax.cond: the
        # update math runs unconditionally and every state leaf keeps its
        # old value when found_inf (where with a scalar pred is bitwise
        # pass-through of the untaken side, so skip semantics — optimizer
        # state frozen, count not incremented — are unchanged). A cond
        # forces XLA to materialize the whole (masters, opt_state) tuple
        # as conditional outputs, which priced at ~25% over the update's
        # own traffic roofline on v5e (profiled: 4.7 ms vs 3.5 ms ideal
        # on the 111M-param LM step); the select fuses into the update's
        # producers instead. The wasted update compute on an actual
        # overflow step is noise at scale_window frequencies.
        updates, new_opt = optimizer.update(master_grads, state.opt_state,
                                            cur)
        import optax
        stepped = optax.apply_updates(cur, updates)
        keep = jnp.logical_not(found_inf)

        def _sel(new, old):
            new = jnp.asarray(new)
            return jnp.where(keep, new, jnp.asarray(old, new.dtype))

        new_cur = jax.tree_util.tree_map(_sel, stepped, cur)
        new_opt_state = jax.tree_util.tree_map(_sel, new_opt,
                                               state.opt_state)

        # master→model half copy (apex _master_params_to_model_params /
        # multi_tensor_scale after step). Norm params may be fp32 in the
        # model pytree; tree_map preserves each leaf's dtype. For fp32
        # passthrough leaves this traces to the same value as the master
        # leaf, but as two *outputs* of the jitted step XLA materializes
        # them into distinct buffers — so re-donating the returned state is
        # safe (unlike init_fn's eager case, which must copy explicitly).
        new_params = jax.tree_util.tree_map(
            lambda m, p: jnp.asarray(m, jnp.asarray(p).dtype),
            new_cur, state.params)
        new_masters = new_cur if use_masters else None

        new_scaler = update_scale(scaler, found_inf)
        new_state = AmpState(new_params, new_masters, new_opt_state,
                             new_scaler, new_model_state)
        metrics = {"loss": loss, "found_inf": found_inf,
                   "loss_scale": scaler.loss_scale}
        if telemetry:
            from apex_tpu import telemetry as _telemetry

            reg = telemetry \
                if isinstance(telemetry, _telemetry.MetricsRegistry) \
                else None
            record = dict(metrics)
            # fp32 grad norm off the already-unscaled master grads (one
            # fused reduction, no extra transfers) + the scale trajectory
            record["grad_norm"] = _telemetry.global_norm(master_grads)
            record.update(scaler_metrics(scaler))
            # one callback per OPTIMIZER step: under accumulation that is
            # one per window, with the window size in the record
            record["accum_steps"] = accum_steps
            _telemetry.emit_metrics(record, tag="amp", registry=reg)
        if has_aux:
            metrics["aux"] = aux
        return new_state, metrics

    return init_fn, step_fn
