"""The O1 per-op cast engine — trace-time analogue of apex's monkey-patching.

Reference: apex/amp/_initialize.py installs wrappers over every op named in
apex/amp/lists/ (torch_overrides.py — FP16_FUNCS, FP32_FUNCS, CASTS) so that,
under O1, tensor-core ops run half, reductions/losses/norms run fp32, and
binary CASTS ops promote operands. JAX traces instead of patching, so the
engine is ambient-context + consultation: :func:`make_train_step` (and
``amp.initialize``'s policy_apply) install the active policy for the duration
of the traced forward, and policy-aware modules ask :func:`op_compute_dtype`
what dtype the table assigns their op.

The context is thread-local Python state consulted at *trace* time only —
nothing here appears in the jaxpr except the casts it decides on. Because
jit caches by jaxpr inputs, the active policy is ALSO salted into jax's
jit cache key (``include_in_jit_key`` config state): a user-jitted
policy-aware function traced under one ambient policy re-traces — instead
of silently reusing stale cast decisions — when called under another
(ADVICE r2 #1; apex re-applies its patches on every ``amp.initialize``,
so stale wrappers cannot survive a policy change there either).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax.numpy as jnp

from . import lists

__all__ = ["autocast", "active_policy", "op_compute_dtype", "resolve_dtype",
           "cast_op_inputs", "trace_token"]

_tls = threading.local()

# jit-cache salt: a jax user context carrying the active policy — part of
# the tracing/lowering/compilation cache key, so jit distinguishes traces
# made under different ambient policies. Older jax has no
# make_user_context; there the salt rides the XLA-metadata context
# instead (``xla_metadata_context_manager`` sits in ``trace_context()``
# on every jax this repo supports), carrying a content fingerprint of
# the policy as a frontend attribute — semantics-free HLO metadata whose
# only load-bearing property is membership in the jit cache key. Last
# resort (neither API): thread-local state only, with trace_token() for
# manual static-arg salting.
try:
    import jax as _jax

    _policy_state = _jax.make_user_context(default_value=None)
except AttributeError:
    try:
        from jax.experimental.xla_metadata import \
            set_xla_metadata as _set_xla_metadata

        def _policy_state(policy):
            # repr of the frozen Policy dataclass: a stable CONTENT
            # fingerprint (two equal policies share one trace; id()
            # would retrace per object and could alias after gc)
            return _set_xla_metadata(apex_tpu_amp_policy=repr(policy))
    except ImportError:  # pragma: no cover - jax without either API
        import warnings

        warnings.warn(
            "this jax has neither make_user_context nor xla_metadata: "
            "the ambient amp policy cannot be salted into the jit cache "
            "key, so a function YOU jit and call under different "
            "autocast policies will silently reuse its first trace's "
            "cast decisions. Re-jit per policy, or upgrade jax.",
            stacklevel=2)
        _policy_state = None


def active_policy():
    """The Policy installed by the innermost :func:`autocast`, or None."""
    return getattr(_tls, "policy", None)


def trace_token():
    """A hashable fingerprint of the active policy (None outside
    :func:`autocast`). jit already re-traces on policy changes via the
    cache salt; pass this as an extra static argument for caches jax does
    not manage (e.g. functools.lru_cache over traced helpers)."""
    return active_policy()


@contextlib.contextmanager
def autocast(policy):
    """Install ``policy`` as the ambient op-cast policy (the O1 engine's
    analogue of apex applying its patches at ``amp.initialize`` time —
    scoped, because trace-time globals must not leak across steps).

    Entering also salts jax's jit cache with the policy, so re-entering a
    previously-jitted function under a different policy re-traces it with
    the new cast decisions rather than reusing the old executable."""
    prev = getattr(_tls, "policy", None)
    _tls.policy = policy
    try:
        if _policy_state is not None:
            with _policy_state(policy):
                yield policy
        else:
            yield policy
    finally:
        _tls.policy = prev


def op_compute_dtype(op_name: str, *operand_dtypes):
    """Dtype the active policy assigns to ``op_name``, or None for "no
    opinion" (run in operand dtype). Delegates to
    :meth:`Policy.op_dtype`; returns None when no policy is active."""
    pol = active_policy()
    if pol is None:
        return None
    return pol.op_dtype(op_name, *operand_dtypes)


def resolve_dtype(explicit, op_name: str, default=None):
    """Module-side dtype resolution: an explicit user dtype always wins;
    otherwise the active policy's table opinion; otherwise ``default``.

    The pattern for policy-aware flax modules: declare ``dtype: Optional[Any]
    = None`` and resolve with the op name the apex tables classify
    (``conv2d``, ``linear``, ``layer_norm``, ``batch_norm``, ...).
    """
    if explicit is not None:
        return explicit
    d = op_compute_dtype(op_name)
    return d if d is not None else default


def cast_op_inputs(op_name: str, *arrays):
    """Cast floating arrays to the table dtype for ``op_name`` (no-op when
    the policy has no opinion). Returns the arrays in order.

    For CASTS entries the target is the widest floating operand dtype —
    apex's promote wrapper (lists/torch_overrides.py — CASTS).
    """
    dtypes = []
    for a in arrays:
        try:
            dtypes.append(jnp.asarray(a).dtype)
        except (TypeError, ValueError):
            dtypes.append(None)
    target = op_compute_dtype(op_name,
                              *[d for d in dtypes if d is not None])
    if target is None:
        return arrays if len(arrays) != 1 else arrays[0]
    out = []
    for a, d in zip(arrays, dtypes):
        if d is not None and jnp.issubdtype(d, jnp.floating):
            out.append(jnp.asarray(a, target))
        else:
            out.append(a)
    return tuple(out) if len(out) != 1 else out[0]
