"""The O1 per-op cast engine — trace-time analogue of apex's monkey-patching.

Reference: apex/amp/_initialize.py installs wrappers over every op named in
apex/amp/lists/ (torch_overrides.py — FP16_FUNCS, FP32_FUNCS, CASTS) so that,
under O1, tensor-core ops run half, reductions/losses/norms run fp32, and
binary CASTS ops promote operands. JAX traces instead of patching, so the
engine is ambient-context + consultation: :func:`make_train_step` (and
``amp.initialize``'s policy_apply) install the active policy for the duration
of the traced forward, and policy-aware modules ask :func:`op_compute_dtype`
what dtype the table assigns their op.

The context is thread-local Python state consulted at *trace* time only —
nothing here appears in the jaxpr except the casts it decides on.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax.numpy as jnp

from . import lists

__all__ = ["autocast", "active_policy", "op_compute_dtype", "resolve_dtype",
           "cast_op_inputs"]

_tls = threading.local()


def active_policy():
    """The Policy installed by the innermost :func:`autocast`, or None."""
    return getattr(_tls, "policy", None)


@contextlib.contextmanager
def autocast(policy):
    """Install ``policy`` as the ambient op-cast policy (the O1 engine's
    analogue of apex applying its patches at ``amp.initialize`` time —
    scoped, because trace-time globals must not leak across steps)."""
    prev = getattr(_tls, "policy", None)
    _tls.policy = policy
    try:
        yield policy
    finally:
        _tls.policy = prev


def op_compute_dtype(op_name: str, *operand_dtypes):
    """Dtype the active policy assigns to ``op_name``, or None for "no
    opinion" (run in operand dtype). Delegates to
    :meth:`Policy.op_dtype`; returns None when no policy is active."""
    pol = active_policy()
    if pol is None:
        return None
    return pol.op_dtype(op_name, *operand_dtypes)


def resolve_dtype(explicit, op_name: str, default=None):
    """Module-side dtype resolution: an explicit user dtype always wins;
    otherwise the active policy's table opinion; otherwise ``default``.

    The pattern for policy-aware flax modules: declare ``dtype: Optional[Any]
    = None`` and resolve with the op name the apex tables classify
    (``conv2d``, ``linear``, ``layer_norm``, ``batch_norm``, ...).
    """
    if explicit is not None:
        return explicit
    d = op_compute_dtype(op_name)
    return d if d is not None else default


def cast_op_inputs(op_name: str, *arrays):
    """Cast floating arrays to the table dtype for ``op_name`` (no-op when
    the policy has no opinion). Returns the arrays in order.

    For CASTS entries the target is the widest floating operand dtype —
    apex's promote wrapper (lists/torch_overrides.py — CASTS).
    """
    dtypes = []
    for a in arrays:
        try:
            dtypes.append(jnp.asarray(a).dtype)
        except (TypeError, ValueError):
            dtypes.append(None)
    target = op_compute_dtype(op_name,
                              *[d for d in dtypes if d is not None])
    if target is None:
        return arrays if len(arrays) != 1 else arrays[0]
    out = []
    for a, d in zip(arrays, dtypes):
        if d is not None and jnp.issubdtype(d, jnp.floating):
            out.append(jnp.asarray(a, target))
        else:
            out.append(a)
    return tuple(out) if len(out) != 1 else out[0]
