"""Opt-level cast policies — the TPU equivalent of apex's amp frontend.

Reference semantics: apex/amp/frontend.py — ``initialize``, ``class
Properties``, ``class O0/O1/O2/O3``, ``opt_levels`` dict. Apex resolves an
opt-level string into a ``Properties`` bundle (cast_model_type,
patch_torch_functions, keep_batchnorm_fp32, master_weights, loss_scale), lets
explicit kwargs override table entries, and prints a banner with the resolved
options.

The TPU design keeps the *table and resolution rules* bit-identical but swaps
the mechanism: instead of monkey-patching torch call sites (O1) or rewriting
module dtypes in place (O2/O3), a frozen :class:`Policy` drives dtype decisions
at trace time — ``cast_to_compute`` for inputs, ``cast_params`` for parameter
pytrees (honouring keep_batchnorm_fp32 via path predicates), and the op
classification tables in :mod:`apex_tpu.amp.lists` for O1-style per-op policy.

The TPU-native half dtype is bfloat16 (see BASELINE.json: "O1/O2 cast policies
… target XLA bf16"); float16 remains selectable so the dynamic loss scaler's
overflow path stays exercised by tests.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Optional, Union

import jax.numpy as jnp

logger = logging.getLogger("apex_tpu.amp")

# Sentinel mirroring apex's use of None for "leave to defaults".
_MISSING = object()

DTypeLike = Any


def _canon_dtype(d):
    if d is None:
        return None
    return jnp.dtype(d)


@dataclasses.dataclass(frozen=True)
class Policy:
    """Resolved amp properties. Mirrors apex/amp/frontend.py — class Properties.

    Fields keep apex's names and meanings:

    - ``enabled``: master switch (apex ``amp.initialize(enabled=False)`` makes
      everything a no-op).
    - ``opt_level``: "O0" | "O1" | "O2" | "O3".
    - ``cast_model_type``: dtype params/inputs are cast to (O2/O3), or None.
    - ``patch_torch_functions``: O1-style per-op cast policy. On TPU this
      selects the op-table-driven compute dtype rules in
      :mod:`apex_tpu.amp.lists` instead of runtime monkey-patching.
    - ``keep_batchnorm_fp32``: keep norm-layer params/stats in fp32 when the
      model itself is cast (O2).
    - ``master_weights``: maintain an fp32 master copy of params; optimizer
      steps read/write the master copy and mirror back to the model dtype.
    - ``loss_scale``: float for static scaling, or the string "dynamic".
    """

    enabled: bool = True
    opt_level: str = "O1"
    cast_model_type: Optional[DTypeLike] = None
    patch_torch_functions: bool = False
    keep_batchnorm_fp32: Optional[bool] = None
    master_weights: Optional[bool] = None
    loss_scale: Union[float, str] = 1.0
    # TPU extension: which dtype "half" means. bf16 is the TPU default; fp16
    # keeps scaler-overflow semantics testable.
    half_dtype: DTypeLike = jnp.bfloat16

    # ------------------------------------------------------------------ dtypes
    @property
    def compute_dtype(self):
        """Dtype matmul/conv compute should run in under this policy."""
        if not self.enabled:
            return jnp.float32
        if self.cast_model_type is not None:
            return _canon_dtype(self.cast_model_type)
        if self.patch_torch_functions:  # O1: half compute for FP16_FUNCS ops
            return _canon_dtype(self.half_dtype)
        return jnp.float32

    def op_dtype(self, op_name: str, *operand_dtypes):
        """Per-op compute dtype under this policy — the O1 engine's core
        (reference: apex/amp/lists/torch_overrides.py tables, applied by
        _initialize.py's patching; SURVEY P6).

        Only O1 (``patch_torch_functions``) has per-op opinions: FP16_FUNCS
        run in ``half_dtype``, FP32_FUNCS in fp32, CASTS promote to the
        widest floating operand. O0/O2/O3 return None — apex patches no
        functions there (the model dtype governs).
        """
        if not self.enabled or not self.patch_torch_functions:
            return None
        from . import lists

        d = lists.compute_dtype_for(op_name, self.half_dtype)
        if d is not None:
            return d
        if op_name in lists.CASTS or op_name in lists.SEQUENCE_CASTS:
            return lists.promote_dtype(*operand_dtypes)
        return None

    @property
    def param_dtype(self):
        """Dtype model ("working") parameters are stored in."""
        if self.enabled and self.cast_model_type is not None:
            return _canon_dtype(self.cast_model_type)
        return jnp.float32

    @property
    def model_dtype(self):
        """What recipes should pass as a flax module's ``dtype``: None
        under O1 (modules resolve per op class through the autocast
        engine — convs/GEMMs half, norms/losses fp32), the blanket compute
        dtype otherwise (O0 fp32; O2/O3 the cast type)."""
        if self.enabled and self.patch_torch_functions:
            return None
        return self.compute_dtype

    @property
    def wants_master_weights(self) -> bool:
        if not self.enabled:
            return False
        if self.master_weights is None:
            return False
        return bool(self.master_weights)

    @property
    def keep_bn_fp32(self) -> bool:
        if self.keep_batchnorm_fp32 is None:
            # apex default: True whenever the model is cast to half (O2);
            # irrelevant otherwise.
            return self.param_dtype != jnp.float32
        return bool(self.keep_batchnorm_fp32)

    # ------------------------------------------------------------- tree casts
    def cast_to_compute(self, tree):
        """Cast floating leaves of ``tree`` to the compute dtype.

        Equivalent of apex's patched-forward input cast
        (apex/amp/_initialize.py — patch_forward closure).
        """
        return _cast_floating(tree, self.compute_dtype)

    def cast_params(self, params, is_norm_param: Optional[Callable] = None):
        """Cast a parameter pytree to ``param_dtype``, keeping norm params fp32
        when ``keep_batchnorm_fp32`` applies.

        ``is_norm_param(path_tuple) -> bool`` identifies batch/layer-norm
        parameters; defaults to name matching on the path (flax convention:
        modules named ``bn*`` / ``*norm*`` / params ``scale``/``bias`` owned by
        them).
        """
        import jax

        target = self.param_dtype
        if target == jnp.float32:
            return _cast_floating(params, jnp.float32)
        pred = is_norm_param if is_norm_param is not None else default_is_norm_param
        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        treedef = jax.tree_util.tree_structure(params)
        leaves = []
        for path, leaf in flat:
            if not _is_float(leaf):
                leaves.append(leaf)
            elif self.keep_bn_fp32 and pred(_path_names(path)):
                leaves.append(jnp.asarray(leaf, jnp.float32))
            else:
                leaves.append(jnp.asarray(leaf, target))
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # ------------------------------------------------------------------ repr
    def banner(self) -> str:
        """The resolved-options banner apex prints from frontend.initialize."""
        lines = [
            "Selected optimization level {}".format(self.opt_level),
            "Defaults for this optimization level are:",
        ]
        for k in ("enabled", "cast_model_type", "patch_torch_functions",
                  "keep_batchnorm_fp32", "master_weights", "loss_scale"):
            lines.append("{:28} : {}".format(k, getattr(self, k)))
        return "\n".join(lines)


def _is_float(x):
    try:
        return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
    except (TypeError, ValueError):
        return False


def _cast_floating(tree, dtype):
    import jax

    def cast(x):
        if _is_float(x):
            return jnp.asarray(x, dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


def _path_names(path):
    names = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key is None:
            key = str(p)
        names.append(str(key))
    return tuple(names)


_NORM_TOKENS = ("bn", "batchnorm", "batch_norm", "batch_stats", "norm", "ln")


def default_is_norm_param(path_names) -> bool:
    """Heuristic path predicate for keep_batchnorm_fp32.

    Apex identifies BN modules by class (_initialize.py / fp16util.py —
    BN_convert_float checks ``isinstance(module, _BatchNorm)``); in a pytree
    world we go by path segment names. Users with exotic naming pass their own
    predicate to :meth:`Policy.cast_params`.
    """
    return any(
        tok in seg.lower() for seg in path_names for tok in _NORM_TOKENS
    )


# --------------------------------------------------------------------- tables
# Mirrors apex/amp/frontend.py — opt_levels = {"O0": O0(), ...}. Values are the
# per-level Properties defaults; None means "no opinion" exactly as in apex.

_O0 = dict(cast_model_type=jnp.float32, patch_torch_functions=False,
           keep_batchnorm_fp32=None, master_weights=False, loss_scale=1.0)
_O1 = dict(cast_model_type=None, patch_torch_functions=True,
           keep_batchnorm_fp32=None, master_weights=None, loss_scale="dynamic")
_O2 = dict(cast_model_type="half", patch_torch_functions=False,
           keep_batchnorm_fp32=True, master_weights=True, loss_scale="dynamic")
_O3 = dict(cast_model_type="half", patch_torch_functions=False,
           keep_batchnorm_fp32=False, master_weights=False, loss_scale=1.0)

opt_levels = {"O0": _O0, "O1": _O1, "O2": _O2, "O3": _O3}

_LEVEL_DOC = {
    "O0": "Pure FP32 training.",
    "O1": "Insert automatic casts around ops (op-table policy).",
    "O2": "Half training with FP32 batchnorm and FP32 master weights.",
    "O3": "Pure half training.",
}


def resolve_policy(
    opt_level: str = "O1",
    enabled: bool = True,
    cast_model_type=_MISSING,
    patch_torch_functions=_MISSING,
    keep_batchnorm_fp32=_MISSING,
    master_weights=_MISSING,
    loss_scale=_MISSING,
    half_dtype=jnp.bfloat16,
    verbose: bool = True,
) -> Policy:
    """Resolve an opt level + kwarg overrides into a frozen Policy.

    Mirrors apex/amp/frontend.py — initialize's validation + override merge:
    unknown opt levels raise, explicit kwargs beat table defaults, and the
    resolved options are logged as a banner.
    """
    if opt_level not in opt_levels:
        raise ValueError(
            "Unexpected optimization level {}. Options are 'O0', 'O1', 'O2', "
            "'O3'. Note that in `O0`, `O1`, etc., the prefix O is the letter "
            "O, not the number zero.".format(opt_level)
        )
    opts = dict(opt_levels[opt_level])

    # keep_batchnorm_fp32 may arrive as the strings "True"/"False" (apex
    # accepts those from argparse: frontend.py — check_option_consistency).
    if isinstance(keep_batchnorm_fp32, str) and keep_batchnorm_fp32 is not _MISSING:
        if keep_batchnorm_fp32 not in ("True", "False"):
            raise ValueError(
                "keep_batchnorm_fp32 must be True, False, 'True' or 'False', "
                "got {}".format(keep_batchnorm_fp32)
            )
        keep_batchnorm_fp32 = keep_batchnorm_fp32 == "True"

    overrides = dict(
        cast_model_type=cast_model_type,
        patch_torch_functions=patch_torch_functions,
        keep_batchnorm_fp32=keep_batchnorm_fp32,
        master_weights=master_weights,
        loss_scale=loss_scale,
    )
    for k, v in overrides.items():
        if v is not _MISSING:
            opts[k] = v

    cmt = opts["cast_model_type"]
    if isinstance(cmt, str) and cmt == "half":
        cmt = half_dtype
    cmt = _canon_dtype(cmt)
    # apex stores float32 for O0 but treats it as "no cast"; we normalise to
    # None for no-op casting while keeping param_dtype fp32 either way.
    cmt_field = None if (cmt is not None and cmt == jnp.float32) else cmt

    ls = opts["loss_scale"]
    if isinstance(ls, str) and ls != "dynamic":
        ls = float(ls)

    policy = Policy(
        enabled=enabled,
        opt_level=opt_level,
        cast_model_type=cmt_field,
        patch_torch_functions=bool(opts["patch_torch_functions"]),
        keep_batchnorm_fp32=opts["keep_batchnorm_fp32"],
        master_weights=opts["master_weights"],
        loss_scale=ls,
        half_dtype=_canon_dtype(half_dtype),
    )
    if verbose:
        logger.info("%s\n%s", _LEVEL_DOC[opt_level], policy.banner())
    return policy
