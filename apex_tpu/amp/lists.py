"""O1 cast-policy op tables — parity with apex/amp/lists/.

Reference: apex/amp/lists/torch_overrides.py — FP16_FUNCS, FP32_FUNCS, CASTS,
SEQUENCE_CASTS (plus tensor_overrides.py / functional_overrides.py which repeat
the classification for Tensor methods and torch.nn.functional).

Apex uses these tables to decide, per patched call site, whether an op runs in
half (tensor-core ops), fp32 (reductions / loss / numerically touchy ops), or
with promoted operand dtypes. On TPU there is no call-site patching — modules
consult :func:`compute_dtype_for` at trace time — but the *classification* is
the behavioral spec and is preserved verbatim where the op exists in JAX.
"""

from __future__ import annotations

import jax.numpy as jnp

# Ops that benefit from half (MXU) math — apex FP16_FUNCS.
FP16_FUNCS = frozenset({
    "conv1d", "conv2d", "conv3d", "conv_transpose1d", "conv_transpose2d",
    "conv_transpose3d", "conv_tbc", "prelu",
    "addmm", "addmv", "addr", "matmul", "mm", "mv", "bmm", "baddbmm",
    "addbmm", "chain_matmul", "linear", "dot", "einsum",
    "dot_general", "conv_general_dilated",  # jax-native spellings
})

# Ops kept in fp32 for range/precision — apex FP32_FUNCS (+ functional/loss
# entries from functional_overrides.py).
FP32_FUNCS = frozenset({
    "acos", "asin", "cosh", "erfinv", "exp", "expm1", "log", "log10", "log2",
    "log1p", "reciprocal", "rsqrt", "sinh", "tan", "pow",
    "softmax", "log_softmax", "cumprod", "cumsum", "dist", "mean", "norm",
    "prod", "std", "sum", "var", "renorm", "logsumexp",
    "cross_entropy", "nll_loss", "l1_loss", "mse_loss", "smooth_l1_loss",
    "kl_div", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "poisson_nll_loss", "cosine_embedding_loss", "hinge_embedding_loss",
    "margin_ranking_loss", "multilabel_margin_loss", "soft_margin_loss",
    "triplet_margin_loss", "ctc_loss",
    "layer_norm", "group_norm", "instance_norm", "batch_norm",
    "gelu",  # kept half in some vintages; fp32 is the safe classification
})

# Ops whose operands are promoted to the widest input dtype — apex CASTS.
CASTS = frozenset({
    "addcdiv", "addcmul", "atan2", "cross", "bilinear",
    "add", "div", "mul", "sub", "eq", "ne", "lt", "le", "gt", "ge",
    "equal", "cat", "stack", "index_put",
})

# Sequence-of-tensors variants promoted elementwise — apex SEQUENCE_CASTS.
SEQUENCE_CASTS = frozenset({"cat", "stack", "concatenate"})


def compute_dtype_for(op_name: str, half_dtype=jnp.bfloat16):
    """Return the compute dtype O1 policy assigns to ``op_name``.

    None means "no opinion" (run in operand dtype / promote per CASTS).
    """
    if op_name in FP16_FUNCS:
        return jnp.dtype(half_dtype)
    if op_name in FP32_FUNCS:
        return jnp.dtype(jnp.float32)
    return None


def promote_dtype(*dtypes):
    """Widest-input promotion used for CASTS entries (apex utils.type_string
    ordering: fp16 < fp32 < fp64)."""
    result = None
    for d in dtypes:
        d = jnp.dtype(d)
        if not jnp.issubdtype(d, jnp.floating):
            continue
        result = d if result is None else jnp.promote_types(result, d)
    return result
