"""Dynamic loss scaling — TPU equivalent of apex/amp/scaler.py — class LossScaler.

Reference semantics (apex/amp/scaler.py):

- ``LossScaler("dynamic")`` starts at ``min(max_loss_scale, 2**16)``, doubles
  after ``scale_window`` (2000) consecutive overflow-free steps, halves on
  overflow (clamped to ``min_loss_scale``), and resets the clean-step counter
  in both cases (``update_scale``).
- ``unscale`` multiplies grads by ``1/scale`` into master grads while checking
  for inf/nan (csrc/multi_tensor_scale_kernel.cu writes a ``noop``/found_inf
  flag); on overflow the step is skipped AND optimizer state must not advance.
- ``unscale_with_stashed`` fuses unscale with accumulation onto stashed master
  grads (csrc/multi_tensor_axpby_kernel.cu).

TPU design: the scaler is a pytree (:class:`ScalerState`) carried in the train
state so the whole update lives inside one jitted step; ``found_inf`` is a
scalar bool computed alongside the unscale (XLA fuses the reduction into the
scale elementwise pass — the multi_tensor launch-batching the CUDA harness
exists for is free here). A stateful :class:`LossScaler` facade preserves the
apex object API (``loss_scale()``, ``update_scale()``, ``unscale``) for
imperative use and tests.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class ScalerState:
    """Pytree loss-scaler state. Static config lives in pytree_node=False fields."""

    loss_scale: jnp.ndarray          # f32 scalar, current scale
    unskipped: jnp.ndarray           # i32 scalar, consecutive clean steps
    steps: jnp.ndarray               # i32 scalar, total update_scale calls
    overflows: jnp.ndarray           # i32 scalar, total overflows seen
    # overflows still tolerated before the next backoff (hysteresis support,
    # Megatron DynamicGradScaler / csrc/update_scale_hysteresis.cu)
    hysteresis_left: jnp.ndarray
    dynamic: bool = struct.field(pytree_node=False, default=True)
    scale_factor: float = struct.field(pytree_node=False, default=2.0)
    scale_window: int = struct.field(pytree_node=False, default=2000)
    min_loss_scale: float = struct.field(pytree_node=False, default=0.0)
    max_loss_scale: float = struct.field(pytree_node=False, default=2.0 ** 24)
    hysteresis: int = struct.field(pytree_node=False, default=1)


def init_scaler(
    loss_scale: Union[float, str] = "dynamic",
    init_scale: float = 2.0 ** 16,
    scale_factor: float = 2.0,
    scale_window: int = 2000,
    min_loss_scale: float = None,
    max_loss_scale: float = 2.0 ** 24,
    hysteresis: int = 1,
) -> ScalerState:
    """Build a ScalerState. Mirrors LossScaler.__init__ defaults.

    ``hysteresis`` — the Megatron DynamicGradScaler schedule (the same
    mechanism as csrc/update_scale_hysteresis.cu): every overflow step
    spends one tolerance point and the scale backs off only once the
    tolerance is exhausted — and KEEPS backing off on each further overflow
    while exhausted; the tolerance refills only when the scale grows (after
    ``scale_window`` clean steps). The default 1 is apex amp's classic
    immediate-backoff behavior."""
    dynamic = isinstance(loss_scale, str) and loss_scale == "dynamic"
    if dynamic:
        scale = min(max_loss_scale, init_scale)
    else:
        scale = float(loss_scale)
    if hysteresis < 1:
        raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
    return ScalerState(
        loss_scale=jnp.float32(scale),
        unskipped=jnp.int32(0),
        steps=jnp.int32(0),
        overflows=jnp.int32(0),
        hysteresis_left=jnp.int32(hysteresis),
        dynamic=dynamic,
        scale_factor=float(scale_factor),
        scale_window=int(scale_window),
        min_loss_scale=0.0 if min_loss_scale is None else float(min_loss_scale),
        max_loss_scale=float(max_loss_scale),
        hysteresis=int(hysteresis),
    )


def scale_loss(loss, state: ScalerState):
    """loss * scale, in the loss's dtype. Mirrors handle.py — scale_loss entry."""
    return loss * jnp.asarray(state.loss_scale, loss.dtype)


def scaler_metrics(state: ScalerState):
    """Telemetry view of the scale trajectory (SURVEY §6's loss-scaler
    health signals): current scale, schedule position, cumulative
    overflow count. Safe inside jit — plain reads of the pytree state,
    consumed by ``amp.make_train_step(telemetry=...)``'s per-step
    emission."""
    return {
        "loss_scale": state.loss_scale,
        "scale_unskipped": state.unskipped,
        "scale_steps": state.steps,
        "overflows": state.overflows,
    }


def _tree_found_inf(tree):
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)]
    if not leaves:
        return jnp.bool_(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l))) for l in leaves]
    return jnp.any(jnp.stack(flags))


def unscale(grads, state: ScalerState, out_dtype=jnp.float32):
    """grads * (1/scale) cast to ``out_dtype`` master grads, plus found_inf.

    Equivalent of scaler.py — unscale → amp_C.multi_tensor_scale with the
    overflow flag (``noop`` tensor) folded into the same pass.
    """
    inv = (1.0 / state.loss_scale).astype(jnp.float32)

    def one(g):
        if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
            return (jnp.asarray(g, jnp.float32) * inv).astype(out_dtype)
        return g

    found = _tree_found_inf(grads)
    return jax.tree_util.tree_map(one, grads), found


def _is_flat_buffer(x):
    """One 1-D floating array — the multi_tensor superbuffer layout."""
    import numpy as np

    return isinstance(x, (jax.Array, np.ndarray)) and x.ndim == 1 \
        and jnp.issubdtype(x.dtype, jnp.floating)


def unscale_with_stashed(new_grads, stashed, state: ScalerState,
                         out_dtype=jnp.float32):
    """out = new/scale + stashed — grad accumulation across iterations
    (the ``delay_unscale=True`` window's per-iteration fusion).

    Equivalent of scaler.py — unscale_with_stashed →
    amp_C.multi_tensor_axpby(a=1/scale, b=1). When both operands are flat
    1-D buffers (the multi_tensor superbuffer layout) the call routes
    through :func:`kernels.multi_tensor.fused_axpby` — the ported axpby
    kernel doing accumulate-with-unscale and the overflow check in ONE
    pass; pytrees take the per-leaf path (same math, XLA-fused).
    """
    inv = (1.0 / state.loss_scale).astype(jnp.float32)

    if _is_flat_buffer(new_grads) and _is_flat_buffer(stashed):
        from apex_tpu.kernels.multi_tensor import fused_axpby

        out, found = fused_axpby(jnp.asarray(new_grads, jnp.float32),
                                 jnp.asarray(stashed, jnp.float32),
                                 inv, 1.0)
        return jnp.asarray(out, out_dtype), found

    def one(g, s):
        g32 = jnp.asarray(g, jnp.float32)
        return (g32 * inv + jnp.asarray(s, jnp.float32)).astype(out_dtype)

    found = jnp.logical_or(_tree_found_inf(new_grads), _tree_found_inf(stashed))
    return jax.tree_util.tree_map(one, new_grads, stashed), found


def update_scale(state: ScalerState, found_inf) -> ScalerState:
    """Post-step schedule. Mirrors scaler.py — update_scale exactly:

    overflow: scale = max(scale/factor, min_scale); unskipped = 0
    clean:    unskipped += 1
    then:     if unskipped == window: scale = min(scale*factor, max_scale);
              unskipped = 0
    (static scalers never change scale but still count.)
    """
    found_inf = jnp.asarray(found_inf, jnp.bool_)
    hyst = state.hysteresis_left
    if state.dynamic:
        # Megatron DynamicGradScaler.update, vectorized: each overflow
        # spends one tolerance point (floored at 0); while exhausted, EVERY
        # overflow backs the scale off; the tolerance refills only on
        # growth. hysteresis=1 degenerates to apex amp's immediate backoff.
        hyst = jnp.where(found_inf, jnp.maximum(hyst - 1, 0), hyst)
        do_backoff = found_inf & (hyst <= 0)
        dropped = jnp.maximum(
            state.loss_scale / state.scale_factor,
            jnp.float32(state.min_loss_scale) if state.min_loss_scale
            else jnp.float32(jnp.finfo(jnp.float32).tiny),
        )
        scale = jnp.where(do_backoff, dropped, state.loss_scale)
        unskipped = jnp.where(found_inf, 0, state.unskipped + 1)
        grow = unskipped >= state.scale_window
        scale = jnp.where(
            grow,
            jnp.minimum(scale * state.scale_factor,
                        jnp.float32(state.max_loss_scale)),
            scale,
        )
        unskipped = jnp.where(grow, 0, unskipped)
        hyst = jnp.where(grow, state.hysteresis, hyst)
    else:
        scale = state.loss_scale
        unskipped = jnp.where(found_inf, 0, state.unskipped + 1)
    return state.replace(
        loss_scale=scale,
        unskipped=jnp.asarray(unskipped, jnp.int32),
        steps=state.steps + 1,
        overflows=state.overflows + jnp.asarray(found_inf, jnp.int32),
        hysteresis_left=jnp.asarray(hyst, jnp.int32),
    )


class LossScaler:
    """Stateful facade with apex's object API (apex/amp/scaler.py — LossScaler).

    Tests and user code read ``loss_scale()``; ``update_scale()`` consumes the
    overflow flag recorded by the last ``unscale``/``unscale_with_stashed``.
    """

    def __init__(self, loss_scale="dynamic", init_scale=2.0 ** 16,
                 scale_factor=2.0, scale_window=2000,
                 min_loss_scale=None, max_loss_scale=2.0 ** 24,
                 hysteresis=1):
        self._state = init_scaler(loss_scale, init_scale, scale_factor,
                                  scale_window, min_loss_scale,
                                  max_loss_scale, hysteresis)
        self._has_overflow = False
        self.dynamic = self._state.dynamic

    def loss_scale(self):
        return float(self._state.loss_scale)

    def scale_loss(self, loss):
        return scale_loss(loss, self._state)

    def unscale(self, grads, out_dtype=jnp.float32):
        # OR-accumulate, don't overwrite: across a delay_unscale window
        # (N unscale/unscale_with_stashed calls before one update_scale)
        # an overflow in ANY iteration must skip the whole window —
        # apex's _overflow_buf accumulating across multi_tensor launches.
        out, found = unscale(grads, self._state, out_dtype)
        self._has_overflow = self._has_overflow or bool(found)
        return out

    def unscale_with_stashed(self, new_grads, stashed, out_dtype=jnp.float32):
        out, found = unscale_with_stashed(new_grads, stashed, self._state,
                                          out_dtype)
        self._has_overflow = self._has_overflow or bool(found)
        return out

    def update_scale(self):
        self._state = update_scale(self._state, jnp.bool_(self._has_overflow))
        had = self._has_overflow
        self._has_overflow = False
        if had:
            # host-side overflow-event counter for the imperative path
            # (the jitted path counts via emit_metrics' found_inf)
            from apex_tpu import telemetry

            if telemetry.enabled():
                telemetry.get_registry().counter_inc(
                    "amp.scaler.overflow_events")
        return had

    # -- checkpointing (apex/amp/frontend.py — state_dict serializes scalers)
    def state_dict(self):
        return {
            "loss_scale": float(self._state.loss_scale),
            "unskipped": int(self._state.unskipped),
            "steps": int(self._state.steps),
            "overflows": int(self._state.overflows),
            "hysteresis_left": int(self._state.hysteresis_left),
        }

    def load_state_dict(self, sd):
        self._state = self._state.replace(
            loss_scale=jnp.float32(sd["loss_scale"]),
            unskipped=jnp.int32(sd["unskipped"]),
            steps=jnp.int32(sd.get("steps", 0)),
            overflows=jnp.int32(sd.get("overflows", 0)),
            hysteresis_left=jnp.int32(
                sd.get("hysteresis_left", self._state.hysteresis)),
        )
