"""Fused LM-head projection + softmax cross-entropy, chunked over vocab.

The unfused LM tail materializes ``logits[N, V]`` in HBM four times per
step (head-GEMM write, loss read, dlogits write, two grad-GEMM reads) —
at GPT-2 shape (N=8184, V=32768) that is ~0.5 GB per pass, and the whole
tail priced at ~12 ms/step on v5e against a ~5-6 ms fused roofline. This
op never materializes logits: the forward streams vocab chunks through a
``lax.scan``, carrying the online logsumexp (running max + rebased sum,
the flash-attention trick applied over the vocab axis instead of keys);
the backward recomputes each chunk's logits from the saved ``lse`` (one
extra head-GEMM of FLOPs, bought back several times over in HBM traffic)
and feeds the chunk's ``dlogits`` straight into the ``dW``/``dx`` GEMMs
while still in registers/VMEM-resident fusions.

This is a TPU-first addition with no direct reference counterpart: apex's
xentropy (apex/contrib/xentropy/softmax_xentropy.py) fuses only the loss,
taking pre-computed logits — that op lives in :mod:`kernels.xentropy` and
stays the default recipe path. Loss semantics (label smoothing included)
match ``xent_reference`` exactly; only the GEMM compute dtype is the
caller's choice (``compute_dtype``), with fp32 accumulation either way
(``preferred_element_type``).

Implemented with XLA scan + dot_general rather than Pallas: the work is
three large GEMMs plus elementwise — exactly what XLA already schedules
optimally on the MXU — and the win is purely structural (what never
touches HBM), which the scan expresses directly. The scans are
``unroll=True``: rolled, the while-loop boundary forces every chunk's
intermediates through HBM and serializes the GEMMs (measured 20.6 ms at
the GPT-2 tail shape on v5e — WORSE than unfused); unrolled, XLA
schedules the chunks as straight-line code and the same op runs 8.75 ms
vs 12.2 ms composed, with the bwd residual shrunk from the [N, V]
logits to a length-N ``lse``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from apex_tpu.kernels.xentropy import xent_reference
from apex_tpu.log_util import get_logger

__all__ = ["lm_head_xentropy", "lm_head_xent_reference"]

_logger = get_logger("kernels.lm_head_loss")


def lm_head_xent_reference(x, kernel, labels, smoothing: float = 0.0,
                           compute_dtype=None):
    """Unfused fp32-accum composition (the oracle the fused op is tested
    against): logits = x @ kernel.T in ``compute_dtype`` inputs, then
    :func:`xent_reference`."""
    cd = compute_dtype or x.dtype
    logits = jax.lax.dot_general(
        jnp.asarray(x, cd), jnp.asarray(kernel, cd),
        (((x.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    return xent_reference(logits, labels, smoothing)


# Both scans are unroll=True (module docstring), so the chunk COUNT is
# straight-line GEMM count: hundreds of iterations are a compile-time
# blowup AND slower than unfused (the while-loop pathology the unroll
# avoids comes back as schedule bloat). 64 tiles keeps GPT-2's padded
# 50304 vocab at a >= 786-wide chunk — comfortably MXU-efficient.
_MAX_UNROLLED_CHUNKS = 64
# ... but widening is itself a memory lever: the per-iteration [N, C]
# fp32 logits block (plus the backward's recompute) grows linearly with
# the chunk, so the auto-widening never exceeds this width beyond what
# the caller already asked for. 8192 is the op's default chunk — the
# known-memory-sane tile at standard vocabs.
_MAX_WIDENED_CHUNK = 8192


def _pick_chunk(v: int, chunk: int) -> int:
    """The requested chunk, lane-aligned (floor to a multiple of 128,
    min 128) and clamped to the padded vocab. Vocabs that don't divide
    are handled by padding the weight to ``ceil(v/c)*c`` rows and
    masking the pad columns out of the logsumexp — NOT by shrinking the
    chunk to a divisor: GPT-2's padded 50304 = 128*3*131 has no
    lane-aligned divisor above 384, and 131 unrolled 384-wide tiles is
    both a compile blowup and slower than unfused (review round-5).

    The unrolled chunk COUNT is additionally clamped to
    ``_MAX_UNROLLED_CHUNKS``: a small ``chunk`` at large vocab (e.g. 128
    at 50k = 393 straight-line GEMM iterations) silently compiles
    forever and runs slower than unfused, so the chunk is raised (with a
    warning) to the smallest lane-aligned width keeping the count sane
    (ADVICE r5 #2). The widening respects the caller's memory intent: it
    never exceeds ``max(chunk, _MAX_WIDENED_CHUNK)`` — the per-iteration
    [N, C] logits block is the op's memory knob, and an extreme vocab
    (e.g. 10M-row retrieval head) where no sane width keeps the count
    under the cap gets the capped width and a louder warning instead of
    a silent HBM blowup."""
    c = max(128, min(chunk, v + (-v) % 128))
    c -= c % 128
    nc = -(-v // c)
    if nc > _MAX_UNROLLED_CHUNKS:
        c_min = -(-v // _MAX_UNROLLED_CHUNKS)
        widened = c_min + (-c_min) % 128
        ceiling = max(c, _MAX_WIDENED_CHUNK)
        if widened <= ceiling:
            _logger.warning(
                "lm_head_xentropy chunk=%d at vocab %d would unroll %d "
                "GEMM scan iterations (unroll=True: straight-line code); "
                "raising the chunk to %d (%d iterations). Pass chunk>=%d "
                "explicitly to silence.", c, v, nc, widened,
                -(-v // widened), widened)
            c = widened
        else:
            # vocab so large that bounding the unroll would need a chunk
            # beyond the memory-sane ceiling: take the ceiling, keep the
            # count honest, and say so — vocab-parallel (axis_name) is
            # the real answer at this scale
            _logger.warning(
                "lm_head_xentropy vocab %d cannot keep the unrolled GEMM "
                "count <= %d at any memory-sane chunk (would need %d-wide "
                "tiles); using chunk=%d (%d iterations). Expect long "
                "compiles — shard the head with axis_name= "
                "(vocab-parallel) instead.", v, _MAX_UNROLLED_CHUNKS,
                widened, ceiling, -(-v // ceiling))
            c = ceiling
    return c


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused(x, kernel, labels, smoothing, chunk, compute_dtype, axis_name):
    loss, _ = _fused_fwd(x, kernel, labels, smoothing, chunk, compute_dtype,
                         axis_name)
    return loss


def _chunk_logits(xc, wc):
    # [N, H] x [C, H] -> [N, C], fp32 accumulation regardless of input dtype
    return jax.lax.dot_general(xc, wc, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _pad_rows(kernel, chunk, compute_dtype):
    """[V, H] weight in compute dtype, zero-padded to a chunk multiple,
    reshaped to [nc, chunk, H] for the scans."""
    v, h = kernel.shape
    nc = -(-v // chunk)
    wc = jnp.asarray(kernel, compute_dtype)
    pad = nc * chunk - v
    if pad:
        wc = jnp.pad(wc, ((0, pad), (0, 0)))
    return wc.reshape(nc, chunk, h), nc


def _shard_offset(v_local, axis_name):
    """(global col offset of this rank's vocab shard, global vocab)."""
    if axis_name is None:
        return 0, v_local
    idx = jax.lax.axis_index(axis_name)
    # psum of a literal 1 is static under shard_map and exists on every
    # jax this library targets (jax.lax.axis_size does not)
    size = jax.lax.psum(1, axis_name)
    return idx * v_local, v_local * size


def _fused_fwd(x, kernel, labels, smoothing, chunk, compute_dtype,
               axis_name):
    n, h = x.shape
    v = kernel.shape[0]                       # LOCAL shard rows
    off0, v_glob = _shard_offset(v, axis_name)
    xc = jnp.asarray(x, compute_dtype)
    wr, nc = _pad_rows(kernel, chunk, compute_dtype)
    padded = nc * chunk != v
    offsets = jnp.arange(nc, dtype=jnp.int32) * chunk

    def body(carry, inp):
        m, s, zy, slg = carry
        wc, off = inp
        lg = _chunk_logits(xc, wc)                        # [N, C] fp32
        lcols = off + jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
        if padded:
            # pad columns are x @ 0 = 0, which would pollute the
            # logsumexp — mask them to -inf (exp -> 0) before any reduce
            lg = jnp.where(lcols < v, lg, -jnp.inf)
        cols = off0 + lcols                               # GLOBAL ids
        m2 = jnp.maximum(m, jnp.max(lg, axis=-1))
        s = s * jnp.exp(m - m2) + jnp.sum(
            jnp.exp(lg - m2[:, None]), axis=-1)
        hit = cols == labels[:, None]
        if padded:
            # pad columns carry global ids that ALIAS the next shard's
            # real vocab rows (off0 + lcols, lcols >= v) — without this
            # gate a label owned by the next shard matches the -inf pad
            # logit here and zy psums to -inf (loss = +inf)
            hit = hit & (lcols < v)
        zy = zy + jnp.sum(jnp.where(hit, lg, 0.0), axis=-1)
        slg = slg + jnp.sum(jnp.where(lcols < v, lg, 0.0), axis=-1) \
            if padded else slg + jnp.sum(lg, axis=-1)
        return (m2, s, zy, slg), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, zy, slg), _ = jax.lax.scan(body, init, (wr, offsets), unroll=True)
    if axis_name is not None:
        # cross-shard online-softmax combine: global max, sums rebased to
        # it; zy/slg are exact psums (each label/col owned by one shard).
        # Identical on every rank afterwards — the loss is replicated.
        m_g = jax.lax.pmax(m, axis_name)
        s = jax.lax.psum(s * jnp.exp(m - m_g), axis_name)
        zy = jax.lax.psum(zy, axis_name)
        slg = jax.lax.psum(slg, axis_name)
        m = m_g
    lse = m + jnp.log(s)
    nll = lse - zy
    if smoothing > 0.0:
        mean_logp = slg / v_glob - lse
        loss = (1.0 - smoothing) * nll - smoothing * mean_logp
    else:
        loss = nll
    # out-of-range labels (ignore-index -100, vocab overshoot): no column
    # matches, so zy stays 0 and the loss would silently read as lse —
    # finite but WRONG. xent_reference masks such rows to NaN explicitly
    # (a raw gather would numpy-wrap -100 onto token V-100); match it
    # exactly so the fused op stays a drop-in and bad labels are loud
    # (ADVICE r5 #1).
    valid = (labels >= 0) & (labels < v_glob)
    loss = jnp.where(valid, loss, jnp.float32(jnp.nan))
    return loss, (x, kernel, labels, lse)


def _fused_bwd(smoothing, chunk, compute_dtype, axis_name, res, g):
    x, kernel, labels, lse = res
    n, h = x.shape
    v = kernel.shape[0]                       # LOCAL shard rows
    off0, v_glob = _shard_offset(v, axis_name)
    xc = jnp.asarray(x, compute_dtype)
    wr, nc = _pad_rows(kernel, chunk, compute_dtype)
    padded = nc * chunk != v
    offsets = jnp.arange(nc, dtype=jnp.int32) * chunk
    g32 = jnp.asarray(g, jnp.float32)
    # out-of-range labels: the reference drops the onehot cotangent (its
    # NaN-masked nll contributes nothing) but keeps the smoothing
    # mean-logp path flowing — d/dlogits of -s*mean_logp is
    # s*(p - 1/V). Match exactly.
    valid = (labels >= 0) & (labels < v_glob)

    def body(dx, inp):
        wc, off = inp
        lg = _chunk_logits(xc, wc)                        # recompute [N, C]
        lcols = off + jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
        if padded:
            lg = jnp.where(lcols < v, lg, -jnp.inf)       # p -> 0 at pads
        cols = off0 + lcols
        p = jnp.exp(lg - lse[:, None])                    # lse is GLOBAL
        hit = cols == labels[:, None]
        if padded:
            # same pad-alias gate as the forward: without it, a
            # next-shard label would put -g into this shard's pad dl
            # column (harmless for dW only because pad rows are sliced
            # off, but it corrupts the dx psum)
            hit = hit & (lcols < v)
        onehot = hit.astype(jnp.float32)
        if smoothing > 0.0:
            target = (1.0 - smoothing) * onehot + smoothing / v_glob
            if padded:
                # the smoothing floor must not leak into pad columns
                target = jnp.where(lcols < v, target, 0.0)
            inv_dl = smoothing * (p - 1.0 / v_glob)
            if padded:
                inv_dl = jnp.where(lcols < v, inv_dl, 0.0)
        else:
            target = onehot
            inv_dl = jnp.float32(0.0)
        dl = jnp.where(valid[:, None], p - target, inv_dl) \
            * g32[:, None]                                # [N, C] fp32
        dlc = jnp.asarray(dl, compute_dtype)
        # dW chunk written once (no cross-chunk accumulation): [C, H]
        dwc = jax.lax.dot_general(dlc, xc, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        # dx accumulated across chunks in fp32
        dx = dx + jax.lax.dot_general(dlc, wc, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dx, dwc

    dx, dws = jax.lax.scan(body, jnp.zeros((n, h), jnp.float32),
                           (wr, offsets), unroll=True)
    if axis_name is not None:
        # every shard's chunks contribute to the full dL/dx — the
        # Megatron parallel-head rule (copy_to's psum-bwd), emitted
        # directly here so callers never double-reduce
        dx = jax.lax.psum(dx, axis_name)
    dw = dws.reshape(nc * chunk, h)[:v]
    return (jnp.asarray(dx, x.dtype), jnp.asarray(dw, kernel.dtype), None)


_fused.defvjp(_fused_fwd, _fused_bwd)


def lm_head_xentropy(x, kernel, labels, *, smoothing: float = 0.0,
                     chunk: int = 8192, compute_dtype=None,
                     axis_name=None):
    """Per-example CE of ``softmax(x @ kernel.T)`` without materializing
    logits. ``x: [..., H]`` hidden states, ``kernel: [V, H]`` vocab-major
    head weight (the embedding table itself for tied-weight GPT models),
    ``labels: [...]`` int targets. Returns fp32 losses shaped like
    ``labels``. Differentiable in ``x`` and ``kernel``.

    ``smoothing`` matches :func:`kernels.xentropy.xent_reference` (apex
    SoftmaxCrossEntropyLoss semantics). ``chunk`` is the vocab tile the
    scan streams (lane-aligned; vocabs that don't divide — GPT-2's
    50257 included — are zero-padded to a chunk multiple with the pad
    columns masked to -inf out of the logsumexp and sliced off dW, so
    every vocab gets full-width tiles). The unrolled chunk COUNT is
    clamped: a small ``chunk`` at large vocab that would unroll more
    than 64 straight-line GEMM iterations is widened with a warning
    (compile blowup + slower than unfused otherwise). ``compute_dtype``
    sets the GEMM input dtype (default: ``x.dtype``; pass the amp half
    dtype for MXU-rate GEMMs) — accumulation and all loss math stay
    fp32 on every path.

    Out-of-range labels (the ignore-index convention's ``-100``, or ids
    ``>= V``) follow ``xent_reference`` exactly: the loss is NaN (the
    reference masks such rows explicitly — a raw gather would wrap
    ``-100`` onto a real token) and the backward drops
    the onehot cotangent for those rows (zero grad at ``smoothing=0``;
    only the smoothing mean-logp term flows otherwise). To IGNORE such
    positions, mask the returned per-example losses before reducing —
    ``jnp.where(labels != -100, losses, 0.0)`` — which also zeroes
    their cotangents; this op never silently trains on a clamped token.

    ``axis_name`` makes the op VOCAB-PARALLEL inside ``shard_map``: each
    rank passes its row shard of the head (global vocab = shard rows ×
    axis size, rank ``i`` owning rows ``[i·V_loc, (i+1)·V_loc)``) and
    the GLOBAL labels. The forward combines the per-shard online
    logsumexp with one pmax + three psums (the Megatron
    vocab_parallel_cross_entropy reductions, fused with the head GEMM);
    the backward psums dx itself — callers must NOT wrap the head input
    in ``copy_to_tensor_model_parallel_region`` or dL/dx double-counts.
    The returned loss is replicated across the axis. Take grads INSIDE
    the shard_map (the recipes' pattern); differentiating THROUGH a
    shard_map whose out_spec replicates the loss hands each rank a
    cotangent pre-divided by the axis size (shard_map's transpose
    convention), scaling the shard-local dW by 1/size.
    """
    if not 0.0 <= smoothing < 1.0:
        raise ValueError(f"smoothing must be in [0, 1), got {smoothing}")
    h = x.shape[-1]
    v, hk = kernel.shape
    if hk != h:
        raise ValueError(f"kernel must be [V, H={h}] vocab-major, got "
                         f"{kernel.shape}")
    shape = x.shape[:-1]
    if labels.shape != shape:
        raise ValueError(f"labels shape {labels.shape} != x leading dims "
                         f"{shape}")
    cd = compute_dtype or x.dtype
    c = _pick_chunk(v, chunk)
    n = 1
    for s_ in shape:
        n *= s_
    if n == 0:
        if axis_name is not None:
            raise ValueError("axis_name with an empty batch is ambiguous")
        return lm_head_xent_reference(x, kernel, labels, smoothing, cd)
    loss = _fused(x.reshape(n, h), kernel, labels.reshape(n).astype(jnp.int32),
                  smoothing, c, jnp.dtype(cd), axis_name)
    return loss.reshape(shape)
