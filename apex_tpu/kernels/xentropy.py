"""Fused softmax cross-entropy Pallas kernel with label smoothing.

TPU-native equivalent of the reference's ``xentropy_cuda`` extension
(apex/contrib/csrc/xentropy/xentropy_kernel.cu —
cunn_SoftMaxXEntropyForward/Backward). Semantics preserved:

- forward computes per-row loss and saves only (losses, max_log_sum_exp)
  for backward ("bprop-in-fprop" memory shape: no softmax tensor saved);
- label smoothing folded into both passes (in-place smoothing in the
  reference);
- half I/O with fp32 math.

Rows are blocked over a 1-D grid with the full vocab row in VMEM per block
(same layout choice as the LN kernel); unaligned vocab falls back to the jnp
path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.kernels import vmem

__all__ = ["softmax_cross_entropy_loss", "xent_reference"]


def xent_reference(logits, labels, smoothing: float = 0.0):
    """fp32 composed reference (the reference tests compare against
    F.log_softmax + nll with manual smoothing).

    Out-of-range labels (ignore-index ``-100``, ids ``>= V``) produce a
    NaN loss and drop the onehot cotangent — explicitly, for EVERY
    out-of-range id: a raw ``take_along_axis`` would numpy-wrap
    negatives in ``[-V, -1]`` onto real vocab rows (``-100`` at
    ``V > 100`` silently trains on token ``V-100``), which torch's
    ``nll_loss`` would never do (it raises). NaN is the loud jax-side
    equivalent; mask the returned losses to ignore such positions."""
    lg = jnp.asarray(logits, jnp.float32)
    logp = jax.nn.log_softmax(lg, axis=-1)
    valid = (labels >= 0) & (labels < lg.shape[-1])
    safe = jnp.where(valid, labels, 0)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, jnp.float32(jnp.nan))
    if smoothing > 0.0:
        mean_logp = jnp.mean(logp, axis=-1)
        return (1.0 - smoothing) * nll - smoothing * mean_logp
    return nll


def _fwd_kernel(lg_ref, lb_ref, loss_ref, mlse_ref, *, smoothing):
    # per-row tensors ride the SUBLANE dim as [br, 1] blocks — lane-dim
    # dynamic stores at non-128-aligned offsets don't lower on Mosaic
    lg = lg_ref[:].astype(jnp.float32)              # [br, V]
    labels = lb_ref[:, 0]                           # [br]
    m = jnp.max(lg, axis=-1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(lg - m), axis=-1, keepdims=True)) + m
    # gather-by-label as a masked reduction (Mosaic has no 1-slice gather)
    cols = jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    onehot_logit = jnp.sum(
        jnp.where(cols == labels[:, None], lg, 0.0), axis=-1, keepdims=True)
    # out-of-range labels (ignore-index -100, ids >= V): the masked
    # reduction matches no column, so nll would silently read as lse —
    # finite but WRONG. Match xent_reference: NaN, loudly.
    valid = (labels >= 0) & (labels < lg.shape[-1])
    nll = jnp.where(valid[:, None], lse - onehot_logit,
                    jnp.float32(jnp.nan))           # [br, 1]
    if smoothing > 0.0:
        mean_logp = jnp.mean(lg - lse, axis=-1, keepdims=True)
        loss = (1.0 - smoothing) * nll - smoothing * mean_logp
    else:
        loss = nll
    loss_ref[:] = loss
    mlse_ref[:] = lse


def _bwd_kernel(lg_ref, lb_ref, mlse_ref, g_ref, out_ref, *, smoothing):
    lg = lg_ref[:].astype(jnp.float32)              # [br, V]
    labels = lb_ref[:, 0]
    lse = mlse_ref[:]                               # [br, 1]
    g = g_ref[:]                                    # [br, 1]
    V = lg.shape[-1]
    softmax = jnp.exp(lg - lse)
    cols = jax.lax.broadcasted_iota(jnp.int32, softmax.shape, 1)
    onehot = (cols == labels[:, None]).astype(jnp.float32)
    # out-of-range labels: the reference drops the onehot cotangent (its
    # NaN-masked nll contributes nothing) but keeps the smoothing
    # mean-logp path flowing — d/dlogits of -s*mean_logp is
    # s*(softmax - 1/V). Same algebra as lm_head_loss._fused_bwd.
    valid = (labels >= 0) & (labels < V)
    if smoothing > 0.0:
        target = (1.0 - smoothing) * onehot + smoothing / V
        inv_dl = smoothing * (softmax - 1.0 / V)
    else:
        target = onehot
        inv_dl = jnp.float32(0.0)
    dl = jnp.where(valid[:, None], softmax - target, inv_dl)
    out_ref[:] = (dl * g).astype(out_ref.dtype)


def _col(x, n):
    return x.reshape(n, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent(logits, labels, smoothing, interpret):
    loss, _ = _xent_fwd(logits, labels, smoothing, interpret)
    return loss


def _block_rows(n, v, n_bufs=4):
    # fp32 logits block + ~3 same-size temporaries (exp, iota/onehot,
    # output); shared scoped-VMEM budget lives in kernels/vmem.py.
    # The BACKWARD passes n_bufs=8 ONLY for fp32 residuals: its logits
    # residual arrives in the caller's dtype, and at fp32 the 4*v-byte
    # rows plus the same-width dlogits block overflowed Mosaic's 16MB
    # scoped-VMEM stack (21MB at the tuned 32-row block, [8192, 32768]
    # fp32 — caught by the round-5 LM run). Half-precision callers keep
    # the fwd accounting: their 2*v-byte residual fits the full tuned
    # block (bench-verified at 32 rows bf16).
    return vmem.block_rows(n, row_bytes=4 * v, n_bufs=n_bufs, max_rows=128,
                           divisor_of=n, key="xentropy.block_rows")


def _xent_fwd(logits, labels, smoothing, interpret):
    n, v = logits.shape
    br = _block_rows(n, v)
    kernel = functools.partial(_fwd_kernel, smoothing=smoothing)
    loss, mlse = pl.pallas_call(
        kernel,
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, v), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, _col(labels, n))
    return loss.reshape(n), (logits, labels, mlse)


def _xent_bwd(smoothing, interpret, res, g):
    logits, labels, mlse = res
    n, v = logits.shape
    # 8 buffers only when the residual actually IS fp32 (4*v-byte rows);
    # half-precision callers keep the full tuned block — their 2*v-byte
    # residual fits the fwd accounting (bench-verified at 32 rows bf16)
    br = _block_rows(n, v,
                     n_bufs=8 if logits.dtype == jnp.float32 else 4)
    kernel = functools.partial(_bwd_kernel, smoothing=smoothing)
    dlogits = pl.pallas_call(
        kernel,
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, v), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, v), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, v), logits.dtype),
        interpret=interpret,
    )(logits, _col(labels, n), _col(mlse, n),
      _col(g.astype(jnp.float32), n))
    return dlogits, None


_xent.defvjp(_xent_fwd, _xent_bwd)


def softmax_cross_entropy_loss(logits, labels, smoothing: float = 0.0,
                               interpret: bool = False):
    """Per-example fused CE. logits: [..., V] (half ok), labels: [...] int.

    Reference: apex/contrib/xentropy/softmax_xentropy.py —
    SoftmaxCrossEntropyLoss(logits, labels, smoothing).

    Out-of-range labels (ignore-index ``-100``, ids ``>= V``) follow
    :func:`xent_reference` on EVERY dispatch path (Pallas kernel and jnp
    fallback alike): NaN loss, onehot cotangent dropped. To ignore such
    positions, mask the returned per-example losses before reducing —
    ``jnp.where(labels != -100, losses, 0.0)``.
    """
    shape = logits.shape[:-1]
    v = logits.shape[-1]
    n = 1
    for s in shape:
        n *= s
    lg2 = logits.reshape(n, v)
    lb = labels.reshape(n)
    aligned = v % 128 == 0 and (n % 128 == 0 or n % 8 == 0)
    if not aligned:
        return xent_reference(logits, labels, smoothing)
    if jax.default_backend() == "cpu":
        interpret = True
    from . import mosaic_dtype_ok

    if not interpret and not mosaic_dtype_ok(lg2):
        return xent_reference(logits, labels, smoothing)
    return _xent(lg2, lb, smoothing, interpret).reshape(shape)
