"""Multi-tensor fused update kernels over a flat superbuffer.

TPU-native equivalent of the reference's ``amp_C`` extension — the
multi_tensor_apply harness (csrc/multi_tensor_apply.cuh) plus its functors:
ScaleFunctor (multi_tensor_scale_kernel.cu), AxpbyFunctor
(multi_tensor_axpby_kernel.cu), L2NormFunctor (multi_tensor_l2norm_kernel.cu),
AdamFunctor (multi_tensor_adam.cu), SGDFunctor (multi_tensor_sgd_kernel.cu).

The CUDA harness exists to update hundreds of small tensors in O(1) kernel
launches. The TPU translation keeps the *semantics* — one whole-model update
pass per step with an overflow (``noop``) flag — via a single Pallas kernel
over the model flattened into one fp32 superbuffer (see
apex_tpu.multi_tensor_apply for the tensor-list plumbing, and
apex_tpu.utils.pytree for flatten/unflatten). Chunking happens through the
Pallas grid instead of the CUDA TensorListMetadata chunk tables.

All kernels run on (rows, 128) lane-aligned views of the zero-padded flat
buffer; zero padding is a fixed point of every functor here, so padded tails
never perturb real entries. Off-TPU they fall back to jnp (one fused jaxpr).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import vmem as _vmem

_LANES = 128
# the adam kernel touches 7 blocked buffers (+pipelining double-buffers and
# fp32 temporaries); the shared scoped-VMEM heuristic (kernels/vmem.py) gives
# 1024 rows of 128 lanes — 2048 overflowed Mosaic's 16MB stack at LM scale
_BLOCK_ROWS = _vmem.block_rows(1 << 30, row_bytes=4 * _LANES, n_bufs=8,
                               max_rows=2048)


def _as_rows(flat):
    n = flat.shape[0]
    rows = max(1, -(-n // _LANES))
    rows_p = -(-rows // 8) * 8
    padded = jnp.pad(flat, (0, rows_p * _LANES - n))
    return padded.reshape(rows_p, _LANES), n


def _grid_rows(rows):
    bm = min(_vmem.get_override("multi_tensor.block_rows", _BLOCK_ROWS,
                                multiple=8), rows)
    rows_p = -(-rows // bm) * bm
    return bm, rows_p, rows_p // bm


def _use_pallas(interpret, *xs):
    from . import mosaic_dtype_ok, on_tpu

    return interpret or (on_tpu() and mosaic_dtype_ok(*xs))


def _row_spec(bm):
    return pl.BlockSpec((bm, _LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)


def _acc_spec():
    return pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)


# ------------------------------------------------------------------- scale
def _scale_kernel(scale_ref, x_ref, out_ref, flag_ref):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    out_ref[:] = (x * scale_ref[0, 0]).astype(out_ref.dtype)

    @pl.when(i == 0)
    def _():
        flag_ref[0, 0] = 0

    bad = jnp.logical_not(jnp.all(jnp.isfinite(x)))
    flag_ref[0, 0] = jnp.maximum(flag_ref[0, 0], bad.astype(jnp.int32))


def fused_scale(flat, scale, interpret: bool = False):
    """out = flat * scale, plus found_inf — amp_C.multi_tensor_scale."""
    scale = jnp.asarray(scale, jnp.float32).reshape(1, 1)
    if not _use_pallas(interpret, flat):
        x32 = flat.astype(jnp.float32)
        out = (x32 * scale[0, 0]).astype(flat.dtype)
        return out, jnp.logical_not(jnp.all(jnp.isfinite(x32)))
    x2, n = _as_rows(flat)
    bm, rows_p, g = _grid_rows(x2.shape[0])
    x2 = jnp.pad(x2, ((0, rows_p - x2.shape[0]), (0, 0)))
    out, flag = pl.pallas_call(
        _scale_kernel,
        grid=(g,),
        in_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  _row_spec(bm)],
        out_specs=[_row_spec(bm), _acc_spec()],
        out_shape=[jax.ShapeDtypeStruct(x2.shape, flat.dtype),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32)],
        interpret=interpret,
    )(scale, x2)
    return out.reshape(-1)[:n], flag[0, 0] > 0


# ------------------------------------------------------------------- axpby
def _axpby_kernel(ab_ref, x_ref, y_ref, out_ref, flag_ref):
    i = pl.program_id(0)
    x = x_ref[:].astype(jnp.float32)
    y = y_ref[:].astype(jnp.float32)
    out_ref[:] = (ab_ref[0, 0] * x + ab_ref[0, 1] * y).astype(out_ref.dtype)

    @pl.when(i == 0)
    def _():
        flag_ref[0, 0] = 0

    bad = jnp.logical_not(jnp.logical_and(jnp.all(jnp.isfinite(x)),
                                          jnp.all(jnp.isfinite(y))))
    flag_ref[0, 0] = jnp.maximum(flag_ref[0, 0], bad.astype(jnp.int32))


def fused_axpby(flat_x, flat_y, a, b, interpret: bool = False):
    """out = a*x + b*y with overflow check — amp_C.multi_tensor_axpby
    (grad accumulation fused with unscale).

    Hot-path wiring: ``amp.scaler.unscale_with_stashed`` routes flat 1-D
    buffer pairs here with ``a=1/scale, b=1`` — the delayed-unscale
    accumulate-with-unscale primitive for the superbuffer layout (the
    in-jit ``make_train_step(accum_steps=N)`` path accumulates per-leaf
    trees instead and lets XLA fuse the equivalent axpby)."""
    ab = jnp.stack([jnp.asarray(a, jnp.float32),
                    jnp.asarray(b, jnp.float32)]).reshape(1, 2)
    if not _use_pallas(interpret, flat_x, flat_y):
        x32, y32 = flat_x.astype(jnp.float32), flat_y.astype(jnp.float32)
        out = (ab[0, 0] * x32 + ab[0, 1] * y32).astype(flat_x.dtype)
        found = jnp.logical_not(jnp.logical_and(
            jnp.all(jnp.isfinite(x32)), jnp.all(jnp.isfinite(y32))))
        return out, found
    x2, n = _as_rows(flat_x)
    y2, _ = _as_rows(flat_y)
    bm, rows_p, g = _grid_rows(x2.shape[0])
    x2 = jnp.pad(x2, ((0, rows_p - x2.shape[0]), (0, 0)))
    y2 = jnp.pad(y2, ((0, rows_p - y2.shape[0]), (0, 0)))
    out, flag = pl.pallas_call(
        _axpby_kernel,
        grid=(g,),
        in_specs=[pl.BlockSpec((1, 2), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
                  _row_spec(bm), _row_spec(bm)],
        out_specs=[_row_spec(bm), _acc_spec()],
        out_shape=[jax.ShapeDtypeStruct(x2.shape, flat_x.dtype),
                   jax.ShapeDtypeStruct((1, 1), jnp.int32)],
        interpret=interpret,
    )(ab, x2, y2)
    return out.reshape(-1)[:n], flag[0, 0] > 0


# ------------------------------------------------------------------- l2norm
def _l2norm_kernel(x_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0, 0] = 0.0

    x = x_ref[:].astype(jnp.float32)
    acc_ref[0, 0] += jnp.sum(x * x)


def fused_l2norm(flat, interpret: bool = False):
    """||flat||_2 in fp32 — amp_C.multi_tensor_l2norm (used by FusedLAMB's
    global-norm stage and contrib clip_grad)."""
    if not _use_pallas(interpret, flat):
        x32 = flat.astype(jnp.float32)
        return jnp.sqrt(jnp.sum(x32 * x32))
    x2, _ = _as_rows(flat)
    bm, rows_p, g = _grid_rows(x2.shape[0])
    x2 = jnp.pad(x2, ((0, rows_p - x2.shape[0]), (0, 0)))
    acc = pl.pallas_call(
        _l2norm_kernel,
        grid=(g,),
        in_specs=[_row_spec(bm)],
        out_specs=_acc_spec(),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
        interpret=interpret,
    )(x2)
    return jnp.sqrt(acc[0, 0])


# --------------------------------------------------------------------- adam
def _adam_kernel(sc_ref, p_ref, m_ref, v_ref, g_ref,
                 p_out, m_out, v_out, *, adam_w):
    lr = sc_ref[0, 0]
    b1 = sc_ref[0, 1]
    b2 = sc_ref[0, 2]
    eps = sc_ref[0, 3]
    wd = sc_ref[0, 4]
    bc1 = sc_ref[0, 5]   # 1 - b1**t
    bc2 = sc_ref[0, 6]   # 1 - b2**t
    inv_scale = sc_ref[0, 7]

    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32) * inv_scale
    if not adam_w:
        g = g + wd * p  # ADAM_MODE_0: L2 regularization folded into grad
    m = b1 * m_ref[:] + (1.0 - b1) * g
    v = b2 * v_ref[:] + (1.0 - b2) * g * g
    update = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w:
        update = update + wd * p  # ADAM_MODE_1: decoupled weight decay
    p_out[:] = (p - lr * update).astype(p_out.dtype)
    m_out[:] = m
    v_out[:] = v


def fused_adam_step(flat_p, flat_m, flat_v, flat_g, *, lr, beta1, beta2, eps,
                    weight_decay, step, adam_w_mode=True, inv_scale=1.0,
                    bias_correction=True, interpret: bool = False):
    """One whole-model Adam/AdamW step — amp_C.multi_tensor_adam
    (csrc/multi_tensor_adam.cu — AdamFunctor; bias correction via step count,
    adam_w selects decoupled decay).

    Buffers are flat fp32 (m, v always fp32, matching apex's fp32 optimizer
    state). ``step`` is the 1-based step count (traced ok).
    """
    step = jnp.asarray(step, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    if bias_correction:
        bc1, bc2 = 1.0 - b1 ** step, 1.0 - b2 ** step
    else:  # apex FusedAdam(bias_correction=False)
        bc1 = bc2 = jnp.float32(1.0)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32), b1, b2,
        jnp.asarray(eps, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
        bc1, bc2,
        jnp.asarray(inv_scale, jnp.float32),
    ]).reshape(1, 8)
    if not _use_pallas(interpret, flat_p, flat_g):
        lr_, b1_, b2_, eps_, wd_, bc1, bc2, inv = [scalars[0, i]
                                                   for i in range(8)]
        p = flat_p.astype(jnp.float32)
        g = flat_g.astype(jnp.float32) * inv
        if not adam_w_mode:
            g = g + wd_ * p
        m = b1_ * flat_m + (1 - b1_) * g
        v = b2_ * flat_v + (1 - b2_) * g * g
        upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps_)
        if adam_w_mode:
            upd = upd + wd_ * p
        return (p - lr_ * upd).astype(flat_p.dtype), m, v

    p2, n = _as_rows(flat_p)
    m2, _ = _as_rows(flat_m)
    v2, _ = _as_rows(flat_v)
    g2, _ = _as_rows(flat_g)
    bm, rows_p, grid = _grid_rows(p2.shape[0])
    pad = ((0, rows_p - p2.shape[0]), (0, 0))
    p2, m2, v2, g2 = (jnp.pad(a, pad) for a in (p2, m2, v2, g2))
    kernel = functools.partial(_adam_kernel, adam_w=adam_w_mode)
    p_new, m_new, v_new = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, 8), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)] + [_row_spec(bm)] * 4,
        out_specs=[_row_spec(bm)] * 3,
        out_shape=[jax.ShapeDtypeStruct((rows_p, _LANES), flat_p.dtype),
                   jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32)],
        interpret=interpret,
    )(scalars, p2, m2, v2, g2)
    return (p_new.reshape(-1)[:n], m_new.reshape(-1)[:n],
            v_new.reshape(-1)[:n])


def adam_tree_step(tree_p, tree_m, tree_v, tree_g, *, lr, beta1, beta2, eps,
                   weight_decay, step, adam_w_mode=True, inv_scale=1.0,
                   bias_correction=True):
    """AdamFunctor applied PER LEAF under one jit — the TPU-native layout.

    Same per-element math as :func:`fused_adam_step`'s superbuffer kernel
    (asserted bitwise-identical in tests/L0/test_multi_tensor.py), but over
    the parameter pytree directly. The CUDA multi_tensor harness exists to
    amortize kernel LAUNCHES, which jit does not pay; the superbuffer
    translation instead pays two whole-model flatten/unflatten copies per
    step. Measured on v5e at 125M params (BASELINE.md round-5 kernel tier):
    flat+Pallas 18.7 ms, flat+jnp 15.1 ms, this path 5.2 ms — XLA fuses the
    per-leaf updates to the HBM roofline. The flat kernels remain for
    callers whose SHARDING is buffer-level (contrib ZeRO optimizers
    psum_scatter the superbuffer).

    Returns (new_p tree in param dtype, new_m tree fp32, new_v tree fp32).
    """
    step = jnp.asarray(step, jnp.float32)
    b1 = jnp.asarray(beta1, jnp.float32)
    b2 = jnp.asarray(beta2, jnp.float32)
    if bias_correction:
        bc1, bc2 = 1.0 - b1 ** step, 1.0 - b2 ** step
    else:
        bc1 = bc2 = jnp.float32(1.0)
    lr = jnp.asarray(lr, jnp.float32)
    eps = jnp.asarray(eps, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    inv = jnp.asarray(inv_scale, jnp.float32)

    def leaf(p, m, v, g):
        p32 = p.astype(jnp.float32)
        g32 = g.astype(jnp.float32) * inv
        if not adam_w_mode:
            g32 = g32 + wd * p32       # ADAM_MODE_0: L2 folded into grad
        m2 = b1 * m + (1.0 - b1) * g32
        v2 = b2 * v + (1.0 - b2) * g32 * g32
        upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + eps)
        if adam_w_mode:
            upd = upd + wd * p32       # ADAM_MODE_1: decoupled decay
        return (p32 - lr * upd).astype(p.dtype), m2, v2

    lp, td = jax.tree_util.tree_flatten(tree_p)
    lm = jax.tree_util.tree_leaves(tree_m)
    lv = jax.tree_util.tree_leaves(tree_v)
    lg = jax.tree_util.tree_leaves(tree_g)
    outs = [leaf(p, m, v, g) for p, m, v, g in zip(lp, lm, lv, lg)]

    def unf(i):
        return jax.tree_util.tree_unflatten(td, [o[i] for o in outs])

    return unf(0), unf(1), unf(2)


def sgd_tree_step(tree_p, tree_buf, tree_g, *, lr, momentum=0.0,
                  dampening=0.0, weight_decay=0.0, nesterov=False,
                  wd_after_momentum=False):
    """SGDFunctor applied PER LEAF under one jit — the TPU-native layout
    (same rationale and bitwise contract as :func:`adam_tree_step`; the
    superbuffer's flatten/unflatten copies are the dominant cost of
    :func:`fused_sgd_step` under jit).

    Returns (new_p tree in param dtype, new_buf tree fp32)."""
    lr = jnp.asarray(lr, jnp.float32)
    mom = jnp.asarray(momentum, jnp.float32)
    damp = jnp.asarray(dampening, jnp.float32)
    wd = jnp.asarray(weight_decay, jnp.float32)
    momentum_on = True if hasattr(momentum, "dtype") \
        else float(momentum) != 0.0

    def leaf(p, buf, g):
        p32 = p.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        if not wd_after_momentum:
            g32 = g32 + wd * p32
        if momentum_on:
            buf2 = mom * buf + (1 - damp) * g32
            upd = g32 + mom * buf2 if nesterov else buf2
        else:
            buf2 = buf
            upd = g32
        if wd_after_momentum:
            upd = upd + wd * p32
        return (p32 - lr * upd).astype(p.dtype), buf2

    lp, td = jax.tree_util.tree_flatten(tree_p)
    lb = jax.tree_util.tree_leaves(tree_buf)
    lg = jax.tree_util.tree_leaves(tree_g)
    outs = [leaf(p, b, g) for p, b, g in zip(lp, lb, lg)]

    def unf(i):
        return jax.tree_util.tree_unflatten(td, [o[i] for o in outs])

    return unf(0), unf(1)


# ---------------------------------------------------------------------- sgd
def _sgd_kernel(sc_ref, p_ref, buf_ref, g_ref, p_out, buf_out, *,
                momentum_on, nesterov, wd_after_momentum):
    lr = sc_ref[0, 0]
    momentum = sc_ref[0, 1]
    dampening = sc_ref[0, 2]
    wd = sc_ref[0, 3]

    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    if not wd_after_momentum:
        g = g + wd * p
    if momentum_on:
        buf = momentum * buf_ref[:] + (1.0 - dampening) * g
        upd = g + momentum * buf if nesterov else buf
        buf_out[:] = buf
    else:
        upd = g
        buf_out[:] = buf_ref[:]
    if wd_after_momentum:
        upd = upd + wd * p
    p_out[:] = (p - lr * upd).astype(p_out.dtype)


def fused_sgd_step(flat_p, flat_buf, flat_g, *, lr, momentum=0.0,
                   dampening=0.0, weight_decay=0.0, nesterov=False,
                   wd_after_momentum=False, interpret: bool = False):
    """One whole-model SGD step — amp_C.multi_tensor_sgd
    (csrc/multi_tensor_sgd_kernel.cu — SGDFunctor, incl. the
    wd_after_momentum variant apex exposes on FusedSGD).

    Note: with zero-initialized momentum buffers and dampening==0 the first
    step equals torch/apex's buf=grad initialization.
    """
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(momentum, jnp.float32),
        jnp.asarray(dampening, jnp.float32),
        jnp.asarray(weight_decay, jnp.float32),
    ]).reshape(1, 4)
    momentum_on = float(momentum) != 0.0 if not hasattr(momentum, "dtype") \
        else True
    if not _use_pallas(interpret, flat_p, flat_g):
        lr_, mom, damp, wd_ = [scalars[0, i] for i in range(4)]
        p = flat_p.astype(jnp.float32)
        g = flat_g.astype(jnp.float32)
        if not wd_after_momentum:
            g = g + wd_ * p
        if momentum_on:
            buf = mom * flat_buf + (1 - damp) * g
            upd = g + mom * buf if nesterov else buf
        else:
            buf = flat_buf
            upd = g
        if wd_after_momentum:
            upd = upd + wd_ * p
        return (p - lr_ * upd).astype(flat_p.dtype), buf

    p2, n = _as_rows(flat_p)
    b2, _ = _as_rows(flat_buf)
    g2, _ = _as_rows(flat_g)
    bm, rows_p, grid = _grid_rows(p2.shape[0])
    pad = ((0, rows_p - p2.shape[0]), (0, 0))
    p2, b2, g2 = (jnp.pad(a, pad) for a in (p2, b2, g2))
    kernel = functools.partial(_sgd_kernel, momentum_on=momentum_on,
                               nesterov=nesterov,
                               wd_after_momentum=wd_after_momentum)
    p_new, buf_new = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((1, 4), lambda i: (0, 0),
                               memory_space=pltpu.SMEM)] + [_row_spec(bm)] * 3,
        out_specs=[_row_spec(bm)] * 2,
        out_shape=[jax.ShapeDtypeStruct((rows_p, _LANES), flat_p.dtype),
                   jax.ShapeDtypeStruct((rows_p, _LANES), jnp.float32)],
        interpret=interpret,
    )(scalars, p2, b2, g2)
    return p_new.reshape(-1)[:n], buf_new.reshape(-1)[:n]
