"""Shared scoped-VMEM row-blocking heuristic for the Pallas kernel tier.

Mosaic's scoped-VMEM stack on this generation is 16MB; a kernel's working
set is roughly (rows_per_block × row_bytes × live_buffers), and pipelining
double-buffers it. Every row-blocked kernel (layer_norm, xentropy,
multi_tensor) sizes its block from the same ~4MB budget via this helper so
a future limit change lands in one place.

Tuned-block overrides (VERDICT round-2 item 4): the heuristic numbers are
emulator-era defaults; real silicon wants measured blocks. A per-kernel
override registry maps knob keys (``"layer_norm.block_rows"``,
``"flash.block_q"``, ...) to values discovered by
``bench_kernels.py --sweep``; ``load_overrides(path)`` reads that sweep's
JSON, and the ``APEX_TPU_TUNED`` env var auto-loads one at import so a
tuned file applies to every entry point without code changes. Overrides
still pass through the same alignment/divisibility clamps as the
heuristic, so a stale file can slow kernels down but never break them.
"""

from __future__ import annotations

import json
import math
import os

VMEM_BUDGET_BYTES = 4 * 1024 * 1024

_OVERRIDES: dict = {}


def set_override(key: str, value: int) -> None:
    """Set a tuned block knob (see module docstring for keys)."""
    _OVERRIDES[key] = int(value)


def get_override(key, default: int, multiple: int = 1,
                 cap: int = 0) -> int:
    """The tuned value for ``key``, or ``default``. key=None → default.

    ``multiple`` rounds a tuned value down to the call site's alignment
    (sublane tiles etc.) and ``cap`` bounds it — a hand-edited or stale
    file must only ever cost speed, never a Mosaic lowering error."""
    if key is None:
        return default
    _auto_load_packaged()
    v = _OVERRIDES.get(key)
    if v is None:
        return default
    v = max(multiple, (int(v) // multiple) * multiple)
    if cap:
        v = min(v, cap)
    return v


def clear_overrides() -> None:
    _OVERRIDES.clear()


def remove_override(key: str) -> None:
    _OVERRIDES.pop(key, None)


def overrides() -> dict:
    return dict(_OVERRIDES)


def _validated_file(path: str) -> dict:
    """Parse + validate a tuned JSON whole-file-first: a bad value (bool,
    digit string, non-integral float) raises BEFORE anything is
    committed, so no caller can leave the registry partially overwritten
    (ADVICE r3)."""
    with open(path) as f:
        data = json.load(f)
    validated = {}
    for k, v in data.items():
        ok = (isinstance(v, int) and not isinstance(v, bool)) or (
            isinstance(v, float) and math.isfinite(v) and int(v) == v)
        if not ok:
            raise ValueError(
                f"tuned override {k!r}={v!r} is not an integer")
        validated[str(k)] = int(v)
    return validated


def load_overrides(path: str) -> dict:
    """Load a ``bench_kernels.py --sweep`` JSON ({key: value}) into the
    registry; returns the loaded mapping. Validates the whole file before
    committing any entry."""
    validated = _validated_file(path)
    _OVERRIDES.update(validated)
    return validated


if os.environ.get("APEX_TPU_TUNED"):
    # a missing/corrupt tuned file must never brick `import apex_tpu`
    try:
        load_overrides(os.environ["APEX_TPU_TUNED"])
    except Exception as _e:  # noqa: BLE001 — any file/parse failure
        import warnings

        warnings.warn(
            f"APEX_TPU_TUNED={os.environ['APEX_TPU_TUNED']!r} could not "
            f"be loaded ({_e}); running with heuristic block sizes")


# Packaged per-device-kind tuned files (round 5): tuned/<kind>.json,
# discovered from the sweep on that silicon and checked in, so tuned
# blocks apply by default — no env var, no user action. Loaded lazily at
# the first get_override() call (kernels resolve blocks at trace time,
# when the backend is already up; probing jax.devices() at import would
# initialize the backend as a side effect of `import apex_tpu`). An
# explicit APEX_TPU_TUNED file or set_override() call wins: packaged
# values never clobber keys that are already set.
_TUNED_DIR = os.path.join(os.path.dirname(__file__), "tuned")
_auto_load_done = False


def _auto_load_packaged() -> None:
    global _auto_load_done
    if _auto_load_done:
        return
    _auto_load_done = True
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return
    path = os.path.join(_TUNED_DIR,
                        kind.lower().replace(" ", "_") + ".json")
    if not os.path.isfile(path):
        return
    try:
        validated = _validated_file(path)  # whole-file-first (ADVICE r3)
    except Exception as e:  # noqa: BLE001
        import warnings

        warnings.warn(f"packaged tuned file {path!r} could not be "
                      f"loaded ({e}); running with heuristic block sizes")
        return
    for k, v in validated.items():
        _OVERRIDES.setdefault(k, v)


def block_rows(n_rows: int, row_bytes: int, n_bufs: int,
               max_rows: int = 512, divisor_of: int = 0,
               key: str = None) -> int:
    """Rows per block such that ``rows*row_bytes*n_bufs`` ≲ the VMEM budget.

    Result is a multiple of 8 (sublane tile), ≥ 8, ≤ ``max_rows``, and never
    exceeds ``n_rows`` rounded up to the sublane tile. With ``divisor_of``
    set, the result is halved until it divides that total (kernels whose
    grid must tile exactly); ``divisor_of`` must itself be a multiple of 8
    or no multiple-of-8 block can divide it.

    ``key`` names this call site's tuned-override knob: a registered
    override (see module docstring) replaces the budget heuristic, but
    still passes through the alignment/divisibility clamps.
    """
    if divisor_of and divisor_of % 8:
        raise ValueError(
            f"divisor_of={divisor_of} must be a multiple of 8: no sublane-"
            "tiled block can divide it")
    budget = VMEM_BUDGET_BYTES // max(1, row_bytes * n_bufs)
    # a tuned value may exceed the heuristic's max_rows preference but
    # not the physical scoped-VMEM stack (~4x the conservative budget):
    # past that the override would trade a slowdown for a Mosaic
    # compile error at a larger shape than it was swept at
    tuned = get_override(key, 0, multiple=8, cap=max(8, 4 * budget))
    if tuned:
        b = max(8, tuned)
    else:
        b = max(8, min(max_rows, budget))
    b = (b // 8) * 8
    b = min(b, max(8, ((n_rows + 7) // 8) * 8))
    if divisor_of:
        while b > 8 and divisor_of % b:
            b //= 2
        b = max(8, (b // 8) * 8)
    return b
