"""Shared scoped-VMEM row-blocking heuristic for the Pallas kernel tier.

Mosaic's scoped-VMEM stack on this generation is 16MB; a kernel's working
set is roughly (rows_per_block × row_bytes × live_buffers), and pipelining
double-buffers it. Every row-blocked kernel (layer_norm, xentropy,
multi_tensor) sizes its block from the same ~4MB budget via this helper so
a future limit change lands in one place.
"""

from __future__ import annotations

VMEM_BUDGET_BYTES = 4 * 1024 * 1024


def block_rows(n_rows: int, row_bytes: int, n_bufs: int,
               max_rows: int = 512, divisor_of: int = 0) -> int:
    """Rows per block such that ``rows*row_bytes*n_bufs`` ≲ the VMEM budget.

    Result is a multiple of 8 (sublane tile), ≥ 8, ≤ ``max_rows``, and never
    exceeds ``n_rows`` rounded up to the sublane tile. With ``divisor_of``
    set, the result is halved until it divides that total (kernels whose
    grid must tile exactly); ``divisor_of`` must itself be a multiple of 8
    or no multiple-of-8 block can divide it.
    """
    if divisor_of and divisor_of % 8:
        raise ValueError(
            f"divisor_of={divisor_of} must be a multiple of 8: no sublane-"
            "tiled block can divide it")
    budget = VMEM_BUDGET_BYTES // max(1, row_bytes * n_bufs)
    b = max(8, min(max_rows, budget))
    b = (b // 8) * 8
    b = min(b, max(8, ((n_rows + 7) // 8) * 8))
    if divisor_of:
        while b > 8 and divisor_of % b:
            b //= 2
        b = max(8, (b // 8) * 8)
    return b
