"""Blockwise (flash) attention Pallas kernels with custom VJP.

TPU-native equivalent of the reference's fused attention extensions:
- ``fast_multihead_attn`` (apex/contrib/csrc/multihead_attn/*.cu —
  self_multihead_attn_forward/backward: strided-batched QKV GEMMs + fused
  softmax) and
- ``fmhalib`` (apex/contrib/csrc/fmha/fmha_api.cpp — varlen packed
  flash-MHA for seqlen ≤ 512).

Design (SURVEY §6 long-context note): the kernel is blockwise over KV with
an online-softmax running (m, l) state, so a later ring-attention/context-
parallel extension only has to rotate KV blocks between chips (ppermute)
around the same inner kernel. Numerics follow the reference kernels: bf16/
half I/O in bf16 (fp16 operands take the jnp fallback on hardware —
Mosaic has no fp16), all accumulation in fp32, logsumexp saved for backward.

Layout: [batch, heads, seq, head_dim] (q, k, v). ``segment_ids`` gives the
varlen/packed-sequence masking of fmhalib (tokens attend only within their
segment). Unaligned shapes fall back to the jnp reference path, which XLA
fuses acceptably — the Pallas path is the transformer hot path
(seq % block == 0).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import vmem
from apex_tpu.kernels import mosaic_dtype_ok

__all__ = ["flash_attention", "mha_reference", "attn_chunk_fwd",
           "attn_chunk_bwd"]

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


# --------------------------------------------------------------- jnp reference
def mha_reference(q, k, v, *, causal: bool = False, scale: float = 1.0,
                  segment_ids: Optional[jnp.ndarray] = None,
                  mask: Optional[jnp.ndarray] = None,
                  bias: Optional[jnp.ndarray] = None,
                  dropout_rate: float = 0.0,
                  dropout_seed=None):
    """fp32-math reference (the oracle the reference's tests use a torch
    softmax composition for). ``bias`` is ADDITIVE on the scaled logits
    (apex's additive-mask MHA variants), broadcastable to [b, h, sq, sk].
    ``dropout_rate``/``dropout_seed``: inverted dropout on the softmax
    probabilities (the reference's fused softmax+dropout, N11) — the
    fallback stream (jax.random) differs from the Pallas kernel's hardware
    PRNG, like the reference's python vs fused impls differ."""
    out_dtype = q.dtype
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k32) * scale
    if bias is not None:
        s = s + jnp.asarray(bias, jnp.float32)
    sq, sk = s.shape[-2], s.shape[-1]
    if causal:
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), s, _NEG_INF)
    if segment_ids is not None:
        seg_mask = segment_ids[:, None, :, None] == \
            segment_ids[:, None, None, :]
        s = jnp.where(seg_mask, s, _NEG_INF)
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        if dropout_seed is None:
            raise ValueError("dropout_rate > 0 requires dropout_seed")
        key = jax.random.PRNGKey(jnp.asarray(dropout_seed, jnp.int32))
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.asarray(jnp.einsum("bhqk,bhkd->bhqd", p, v32), out_dtype)


def _mix_seed(seed, b, qi, ki):
    """Murmur-style avalanche of (user seed, bh index, q-block, k-block)
    into one PRNG seed. A linear combination would collide systematically —
    seed=step with step+1 at block index i-1 reuses step's block-i mask, and
    nearby seeds shift rather than change the mask field; the wrap-multiply
    + xorshift mixing decorrelates all four inputs."""
    x = jnp.asarray(seed, jnp.uint32)
    for v, c in ((b, 0x9E3779B1), (qi, 0x85EBCA77), (ki, 0xC2B2AE3D)):
        x = (x ^ jnp.asarray(v, jnp.uint32)) * jnp.uint32(c)
        x = x ^ (x >> 16)
    return x.astype(jnp.int32)


def _keep_mask(seed_ref, b, qi, ki, block_q, block_k, rate):
    """Deterministic per-(bh, q-block, k-block) dropout keep-mask from the
    hardware PRNG. The seed formula is shared by the forward and BOTH
    backward kernels, so backward replays the exact forward mask (the
    reference kernels replay their philox state the same way, N11)."""
    pltpu.prng_seed(_mix_seed(seed_ref[0], b, qi, ki))
    bits = pltpu.bitcast(
        pltpu.prng_random_bits((block_q, block_k)), jnp.uint32)
    thresh = min(int(rate * 4294967296.0), 4294967295)
    return bits >= jnp.uint32(thresh)


# -------------------------------------------------------------- forward kernel
def _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, bias_ref, seed_ref,
                o_ref, lse_ref, acc_ref, m_ref, l_ref, *, scale, causal,
                block_q, block_k, have_segs, have_bias, dropout_rate):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: whole block above the diagonal → skip
    run = True
    if causal:
        run = (qi * block_q + block_q - 1) >= (ki * block_k)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if have_bias:
            s = s + bias_ref[0].astype(jnp.float32)

        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if have_segs:
            segq = segq_ref[0, 0, pl.ds(qi * block_q, block_q)]   # [bq]
            segk = segk_ref[0, 0, pl.ds(ki * block_k, block_k)]   # [bk]
            s = jnp.where(segq[:, None] == segk[None, :], s, _NEG_INF)

        m_prev = m_ref[:, :1]                     # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                    # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)           # [bq, 1]
        # l accumulates UNDROPPED p (the softmax normalizer is exact);
        # dropout zeroes entries only in the PV accumulation
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        p_acc = p
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref, pl.program_id(0), qi, ki,
                              block_q, block_k, dropout_rate)
            p_acc = jnp.where(keep, p, 0.0)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p_acc, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        denom = l_safe * (1.0 - dropout_rate)   # inverted-dropout scaling
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(l_safe)
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = lse[:, 0]


# ------------------------------------------------------------- backward kernels
def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     segq_ref, segk_ref, bias_ref, seed_ref, dk_ref, dv_ref,
                     dk_acc, dv_acc, *, scale, causal, block_q, block_k,
                     have_segs, have_bias, dropout_rate):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (qi * block_q + block_q - 1) >= (ki * block_k)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if have_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if have_segs:
            segq = segq_ref[0, 0, pl.ds(qi * block_q, block_q)]
            segk = segk_ref[0, 0, pl.ds(ki * block_k, block_k)]
            s = jnp.where(segq[:, None] == segk[None, :], s, _NEG_INF)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
        p = jnp.exp(s - lse[:, None])                 # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # replay the forward's mask: same seed formula, (qi, ki) order
            keep = _keep_mask(seed_ref, pl.program_id(0), qi, ki,
                              block_q, block_k, dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_d = jnp.where(keep, p * inv, 0.0)
            dp = jnp.where(keep, dp * inv, 0.0)
        else:
            p_d = p
        dv_acc[:] += jax.lax.dot_general(
            p_d, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   segq_ref, segk_ref, bias_ref, seed_ref, dq_ref, *rest,
                   scale, causal, block_q, block_k, have_segs, have_bias,
                   emit_dlog, dropout_rate):
    # rest = (dlog_ref, dq_acc) when emit_dlog else (dq_acc,)
    if emit_dlog:
        dlog_ref, dq_acc = rest
    else:
        (dq_acc,) = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    run = True
    if causal:
        run = (qi * block_q + block_q - 1) >= (ki * block_k)

    if emit_dlog and causal:
        # each (qi, ki) grid step owns its dlog block; skipped blocks must
        # still be defined
        @pl.when(jnp.logical_not(run))
        def _zero_dlog():
            dlog_ref[0] = jnp.zeros_like(dlog_ref[0])

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if have_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if have_segs:
            segq = segq_ref[0, 0, pl.ds(qi * block_q, block_q)]
            segk = segk_ref[0, 0, pl.ds(ki * block_k, block_k)]
            s = jnp.where(segq[:, None] == segk[None, :], s, _NEG_INF)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref, pl.program_id(0), qi, ki,
                              block_q, block_k, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        dlogits = p * (dp - delta[:, None])       # d loss / d (scaled+bias)
        if emit_dlog:
            dlog_ref[0] = dlogits.astype(dlog_ref.dtype)
        ds = dlogits * scale
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dbias_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  segq_ref, segk_ref, bias_ref, seed_ref, dbias_ref, *,
                  scale, causal, block_q, block_k, have_segs, n_inner,
                  dropout_rate, bh_of):
    """Reduced bias cotangent for BROADCAST bias classes: grid is
    (B*, nq, nk, R) with the broadcast-reduced dim R innermost, so the
    (class, i, j) output block stays resident in VMEM across the R steps
    and dlogits accumulates in place — HBM only ever sees the final
    [B*, sq, sk], never the [b*h, sq, sk] intermediate."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    r = pl.program_id(3)

    @pl.when(r == 0)
    def _init():
        dbias_ref[0] = jnp.zeros_like(dbias_ref[0])

    run = True
    if causal:
        run = (qi * block_q + block_q - 1) >= (ki * block_k)

    @pl.when(run)
    def _body():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if have_segs:
            segq = segq_ref[0, 0, pl.ds(qi * block_q, block_q)]
            segk = segk_ref[0, 0, pl.ds(ki * block_k, block_k)]
            s = jnp.where(segq[:, None] == segk[None, :], s, _NEG_INF)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            bh_idx = bh_of(pl.program_id(0), pl.program_id(3))
            keep = _keep_mask(seed_ref, bh_idx, qi, ki,
                              block_q, block_k, dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        dbias_ref[0] += (p * (dp - delta[:, None])).astype(dbias_ref.dtype)


# ------------------------------------------------------------------- dispatch
def _flatten(q):
    b, h, s, d = q.shape
    return q.reshape(b * h, s, d)


def _seg_flat(segment_ids, h):
    # [b, s] -> [b*h, s]
    return jnp.repeat(segment_ids, h, axis=0)


def _has_vma(x):
    """True when ``x`` is varying over shard_map manual axes. Pallas
    interpret mode (the CPU test path) cannot lower such inputs — its
    internal dynamic_slice grid indexing mixes unvaried loop constants with
    varying operands and trips check_vma — so dispatch falls back to the
    jnp reference there. Real-TPU Mosaic lowering is unaffected."""
    try:
        return bool(jax.typeof(x).vma)
    except (AttributeError, TypeError):
        return False


def _match_vma(x, like):
    """Cast a freshly-created constant to the varying-manual-axes of ``like``
    so it can mix with per-shard data inside shard_map(check_vma=True)."""
    try:
        vma = jax.typeof(like).vma
        cur = jax.typeof(x).vma
        missing = tuple(sorted(set(vma) - set(cur)))
        if missing:
            if hasattr(jax.lax, "pcast"):
                return jax.lax.pcast(x, missing, to="varying")
            return jax.lax.pvary(x, missing)
    except (AttributeError, TypeError):
        pass
    return x


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying the vma (varying-manual-axes) of ``like``,
    so pallas_call outputs type-check inside shard_map(check_vma=True) —
    the ring/Ulysses context-parallel wrappers call these kernels there."""
    try:
        vma = jax.typeof(like).vma
        if vma:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        pass
    return jax.ShapeDtypeStruct(shape, dtype)


def _resolve_blocks(block_q, block_k):
    """Default/tuned block sizes, clamped to the Pallas tile alignments
    (_pallas_ok: bq sublane-multiple 8, bk lane-multiple 128 — a tuned
    file must never drop the kernel to the quadratic-memory fallback)."""
    if block_q is None:
        block_q = vmem.get_override("flash.block_q", DEFAULT_BLOCK_Q,
                                    multiple=8)
    if block_k is None:
        block_k = vmem.get_override("flash.block_k", DEFAULT_BLOCK_K,
                                    multiple=128)
    return block_q, block_k


def _resolve_bwd_blocks(bq, bk, sq, sk, dropout_rate):
    """Backward-specific tuned blocks (``flash.bwd_block_q``/``_k``),
    defaulting to the forward's resolved values.

    Only consulted when dropout is OFF: the dropout keep-mask is seeded
    per (bh, q-block, k-block) FORWARD block, so a backward running on a
    different geometry could not replay it. The bwd kernels' working set
    differs from the forward's (dk/dv accumulators + dlog tiles), so its
    optimum need not match — measured on v5e the bwd prefers a smaller
    k-block than the forward's 1024 (BASELINE.md round-5 kernel tier)."""
    if dropout_rate > 0.0:
        return bq, bk
    bq2 = vmem.get_override("flash.bwd_block_q", bq, multiple=8)
    bk2 = vmem.get_override("flash.bwd_block_k", bk, multiple=128)
    return _fit_block(bq2, sq, 8), _fit_block(bk2, sk, 128)


def _fit_block(b, s, multiple):
    """Shrink a (possibly tuned) block to the LARGEST aligned divisor of
    the sequence that is <= b. A big tuned block (e.g. block_q=1024 from
    the v5e sweep) must degrade to a smaller Pallas block at shapes it
    doesn't divide — never drop the call to the quadratic-memory
    fallback, which is what _pallas_ok would otherwise do.

    Divisor scan, not repeated halving: halving a non-divisor like 768
    at s=1024 bottoms out at 8 (every halving step misses 512), and
    near-degenerate blocks are both slow and fragile in Mosaic; the
    scan finds 512. When s has NO aligned divisor >= multiple (e.g.
    s=250 at multiple=128) the floor `multiple` itself is returned even
    though it does not divide s — callers must keep the _pallas_ok gate,
    which rejects that case into the jnp fallback. Trace-time only,
    <= b/multiple iterations."""
    b = min(b, s)
    b -= b % multiple
    while b > multiple and s % b:
        b -= multiple
    return max(multiple, b)


def _pallas_ok(sq, sk, d, bq, bk):
    # bk is the lane dim of the [bq, bk] score tile → multiple of 128;
    # bq is the sublane dim → multiple of 8.
    return (sq % bq == 0 and sk % bk == 0 and d % 8 == 0
            and bq % 8 == 0 and bk % 128 == 0)


def _validate_bias(bias, b, h, sq, sk):
    """Shared bias validation for BOTH dispatch paths (Pallas and the jnp
    fallback must agree on what is accepted, or a model validated at
    unaligned shapes would crash once shapes become block-aligned)."""
    if bias is None:
        return
    if getattr(bias, "ndim", None) != 4 or bias.shape[2:] != (sq, sk) \
            or bias.shape[0] not in (1, b) or bias.shape[1] not in (1, h):
        raise ValueError(
            f"flash_attention: bias shape {getattr(bias, 'shape', None)} "
            f"not broadcastable to {(b, h, sq, sk)} (rank 4; leading dims "
            "may be 1; the [sq, sk] plane must be full)")


def _canon_bias(bias, bh, h, sq, sk):
    """Canonicalize an additive logits bias broadcastable to [b, h, sq, sk]
    into (bias3 [B*, sq, sk], index fn flat-bh-index → B*-index, have_bias,
    broadcast class).

    Only the leading two dims may broadcast (the [sq, sk] plane is always
    full — a [*, 1, sk] padding mask should be broadcast by the caller,
    which costs sq× memory but keeps the kernel's block map static)."""
    if bias is None:
        return None, (lambda b: 0), False, "none"
    b = bh // h
    _validate_bias(bias, b, h, sq, sk)
    bb, bhh = bias.shape[0], bias.shape[1]
    if bb == 1 and bhh == 1:
        return bias.reshape(1, sq, sk), (lambda i: 0), True, "one"
    if bb == 1:
        return bias.reshape(h, sq, sk), (lambda i: i % h), True, "head"
    if bhh == 1:
        return bias.reshape(b, sq, sk), (lambda i: i // h), True, "batch"
    return bias.reshape(bh, sq, sk), (lambda i: i), True, "full"


def _seed_operand(seed, like):
    """SMEM (1,) int32 seed operand (zeros when dropout is off)."""
    if seed is None:
        arr = jnp.zeros((1,), jnp.int32)
    else:
        arr = jnp.asarray(seed, jnp.int32).reshape(1)
    return _match_vma(arr, like)


_SEED_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd_pallas(q3, k3, v3, segq, segk, scale, causal, bq, bk, interpret,
                bias=None, h=None, dropout_rate=0.0, dropout_seed=None):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    have_segs = segq is not None
    if not have_segs:
        segq = _match_vma(jnp.zeros((bh, sq), jnp.int32), q3)
        segk = _match_vma(jnp.zeros((bh, sk), jnp.int32), q3)
    segq = segq.reshape(bh, 1, sq)
    segk = segk.reshape(bh, 1, sk)
    bias3, bmap, have_bias, _ = _canon_bias(bias, bh, h or 1, sq, sk)
    if not have_bias:
        bias3 = _match_vma(jnp.zeros((1, bq, bk), jnp.float32), q3)
        bias_spec = pl.BlockSpec((1, bq, bk), lambda b, i, j: (0, 0, 0))
    else:
        bias_spec = pl.BlockSpec((1, bq, bk),
                                 lambda b, i, j: (bmap(b), i, j))
    seed1 = _seed_operand(dropout_seed, q3)
    grid = (bh, sq // bq, sk // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, have_segs=have_segs,
                               have_bias=have_bias,
                               dropout_rate=dropout_rate)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),
            pl.BlockSpec((1, 1, sk), lambda b, i, j: (b, 0, 0)),
            bias_spec,
            _SEED_SPEC,
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),
        ],
        out_shape=[
            _sds((bh, sq, d), q3.dtype, q3),
            _sds((bh, 1, sq), jnp.float32, q3),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, segq, segk, bias3, seed1)
    return o, lse


def _bwd_pallas(q3, k3, v3, do3, lse, delta, segq, segk, scale, causal, bq, bk,
                interpret, out_dtype=None, bias=None, h=None,
                dropout_rate=0.0, dropout_seed=None):
    """delta: [bh, 1, sq] fp32 = sum(do * o, -1); lse: [bh, 1, sq] fp32.

    ``out_dtype`` overrides the gradient dtypes (default: match inputs);
    ring attention passes fp32 so cross-chunk accumulation stays exact while
    the kernels still stream bf16 inputs (they upcast per-tile internally).

    With ``bias``, additionally returns dlogits [bh, sq, sk] fp32 (the bias
    cotangent before broadcast-reduction) — an O(s²) buffer, same footprint
    the unfused backward pays; bias-free calls allocate nothing extra.
    """
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    have_segs = segq is not None
    if not have_segs:
        segq = _match_vma(jnp.zeros((bh, sq), jnp.int32), q3)
        segk = _match_vma(jnp.zeros((bh, sk), jnp.int32), q3)
    segq = segq.reshape(bh, 1, sq)
    segk = segk.reshape(bh, 1, sk)
    bias3, bmap, have_bias, bclass = _canon_bias(bias, bh, h or 1, sq, sk)
    if not have_bias:
        bias3 = _match_vma(jnp.zeros((1, bq, bk), jnp.float32), q3)
        bias_spec_ji = pl.BlockSpec((1, bq, bk), lambda b, j, i: (0, 0, 0))
        bias_spec_ij = pl.BlockSpec((1, bq, bk), lambda b, i, j: (0, 0, 0))
    else:
        bias_spec_ji = pl.BlockSpec((1, bq, bk),
                                    lambda b, j, i: (bmap(b), i, j))
        bias_spec_ij = pl.BlockSpec((1, bq, bk),
                                    lambda b, i, j: (bmap(b), i, j))
    # full-rank bias: dlogits IS dbias, emit it straight from the dq kernel;
    # broadcast classes: a separate reduced pass (below) so HBM never holds
    # the [bh, sq, sk] intermediate
    emit_dlog = have_bias and bclass == "full"
    seed1 = _seed_operand(dropout_seed, q3)

    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, have_segs=have_segs,
                          have_bias=have_bias, dropout_rate=dropout_rate),
        grid=(bh, sk // bk, sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda b, j, i: (b, i, 0)),   # do
            pl.BlockSpec((1, 1, sq), lambda b, j, i: (b, 0, 0)),   # lse
            pl.BlockSpec((1, 1, sq), lambda b, j, i: (b, 0, 0)),   # delta
            pl.BlockSpec((1, 1, sq), lambda b, j, i: (b, 0, 0)),   # segq
            pl.BlockSpec((1, 1, sk), lambda b, j, i: (b, 0, 0)),   # segk
            bias_spec_ji,
            _SEED_SPEC,
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, sk, d), out_dtype or k3.dtype, q3),
            _sds((bh, sk, d), out_dtype or v3.dtype, q3),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta, segq, segk, bias3, seed1)

    dq_out_specs = [pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))]
    dq_out_shape = [_sds((bh, sq, d), out_dtype or q3.dtype, q3)]
    if emit_dlog:
        dq_out_specs.append(
            pl.BlockSpec((1, bq, bk), lambda b, i, j: (b, i, j)))
        dq_out_shape.append(_sds((bh, sq, sk), jnp.float32, q3))
    dq_res = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, have_segs=have_segs,
                          have_bias=have_bias, emit_dlog=emit_dlog,
                          dropout_rate=dropout_rate),
        grid=(bh, sq // bq, sk // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),   # do
            pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),   # lse
            pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),   # delta
            pl.BlockSpec((1, 1, sq), lambda b, i, j: (b, 0, 0)),   # segq
            pl.BlockSpec((1, 1, sk), lambda b, i, j: (b, 0, 0)),   # segk
            bias_spec_ij,
            _SEED_SPEC,
        ],
        out_specs=dq_out_specs,
        out_shape=dq_out_shape,
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse, delta, segq, segk, bias3, seed1)
    dq = dq_res[0]
    dlog = dq_res[1] if emit_dlog else None

    if have_bias and not emit_dlog:
        # broadcast classes: one extra recompute pass whose output is the
        # REDUCED cotangent [B*, sq, sk] — bh/B* × less HBM than emitting
        # full dlogits and summing outside
        h_ = h or 1
        b_ = bh // h_
        if bclass == "one":
            B, R = 1, bh
            bexpr = lambda c, r: r                            # noqa: E731
        elif bclass == "head":
            B, R = h_, b_
            bexpr = lambda c, r: r * h_ + c                   # noqa: E731
        else:                                                 # "batch"
            B, R = b_, h_
            bexpr = lambda c, r: c * h_ + r                   # noqa: E731
        dlog = pl.pallas_call(
            functools.partial(_dbias_kernel, scale=scale, causal=causal,
                              block_q=bq, block_k=bk, have_segs=have_segs,
                              n_inner=R, dropout_rate=dropout_rate,
                              bh_of=bexpr),
            grid=(B, sq // bq, sk // bk, R),
            in_specs=[
                pl.BlockSpec((1, bq, d),
                             lambda c, i, j, r: (bexpr(c, r), i, 0)),  # q
                pl.BlockSpec((1, bk, d),
                             lambda c, i, j, r: (bexpr(c, r), j, 0)),  # k
                pl.BlockSpec((1, bk, d),
                             lambda c, i, j, r: (bexpr(c, r), j, 0)),  # v
                pl.BlockSpec((1, bq, d),
                             lambda c, i, j, r: (bexpr(c, r), i, 0)),  # do
                pl.BlockSpec((1, 1, sq),
                             lambda c, i, j, r: (bexpr(c, r), 0, 0)),  # lse
                pl.BlockSpec((1, 1, sq),
                             lambda c, i, j, r: (bexpr(c, r), 0, 0)),  # delta
                pl.BlockSpec((1, 1, sq),
                             lambda c, i, j, r: (bexpr(c, r), 0, 0)),  # segq
                pl.BlockSpec((1, 1, sk),
                             lambda c, i, j, r: (bexpr(c, r), 0, 0)),  # segk
                pl.BlockSpec((1, bq, bk),
                             lambda c, i, j, r: (c, i, j)),            # bias
                _SEED_SPEC,
            ],
            out_specs=[pl.BlockSpec((1, bq, bk),
                                    lambda c, i, j, r: (c, i, j))],
            out_shape=[_sds((B, sq, sk), jnp.float32, q3)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta, segq, segk, bias3, seed1)[0]

    return dq, dkdv[0], dkdv[1], dlog


# ------------------------------------------------- chunk API (ring attention)
def _ref_chunk_keep(dropout_seed, shape, dropout_rate):
    """Fallback-path keep mask: regenerated identically in chunk fwd and
    bwd from the (deterministic) per-chunk-pair seed."""
    key = jax.random.PRNGKey(jnp.asarray(dropout_seed, jnp.int32))
    return jax.random.bernoulli(key, 1.0 - dropout_rate, shape)


def _ref_chunk_fwd(q3, k3, v3, scale, causal, dropout_rate=0.0,
                   dropout_seed=None):
    """jnp chunk forward returning (o fp32-normalized, lse fp32)."""
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q3, k3, v3))
    s = jnp.einsum("bqd,bkd->bqk", q32, k32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)          # normalizer stays UNDROPPED
    l_safe = jnp.where(l == 0.0, 1.0, l)
    p_acc = p
    denom = l_safe[..., None]
    if dropout_rate > 0.0:
        keep = _ref_chunk_keep(dropout_seed, p.shape, dropout_rate)
        p_acc = jnp.where(keep, p, 0.0)
        denom = denom * (1.0 - dropout_rate)
    o = jnp.einsum("bqk,bkd->bqd", p_acc, v32) / denom
    lse = m + jnp.log(l_safe)
    return o, lse


def _ref_chunk_bwd(q3, k3, v3, do3, lse, delta, scale, causal,
                   dropout_rate=0.0, dropout_seed=None):
    """jnp chunk backward given fwd residuals (lse [bh,s], delta=sum(do*o))."""
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q3, k3, v3))
    do32 = jnp.asarray(do3, jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", q32, k32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((sq, sk), bool)), s, _NEG_INF)
    p = jnp.exp(s - lse[..., None])
    dp = jnp.einsum("bqd,bkd->bqk", do32, v32)
    p_d = p
    if dropout_rate > 0.0:
        keep = _ref_chunk_keep(dropout_seed, p.shape, dropout_rate)
        inv = 1.0 / (1.0 - dropout_rate)
        p_d = jnp.where(keep, p * inv, 0.0)
        dp = jnp.where(keep, dp * inv, 0.0)
    dv = jnp.einsum("bqk,bqd->bkd", p_d, do32)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, k32)
    dk = jnp.einsum("bqk,bqd->bkd", ds, q32)
    return dq, dk, dv


def attn_chunk_fwd(q3, k3, v3, *, scale, causal,
                   block_q=None, block_k=None,
                   dropout_rate=0.0, dropout_seed=None,
                   interpret=False):
    """One attention block: [bh, sq, d] x [bh, sk, d] -> (o fp32, lse fp32).

    The building block ring attention rotates KV around (SURVEY §6: the
    kernel is blockwise over KV precisely so context parallelism can reuse
    it). Output is softmax-normalized *within the chunk*; ``lse`` lets the
    caller re-weight when combining chunks (o, lse) -> global softmax.

    ``dropout_rate``/``dropout_seed``: fused softmax dropout; the caller
    must pass a seed unique per (ring step, chunk pair) — ring attention
    derives it via _mix_seed — and the SAME seed to attn_chunk_bwd so the
    mask replays.
    """
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    sq, sk, d = q3.shape[1], k3.shape[1], q3.shape[2]
    block_q, block_k = _resolve_blocks(block_q, block_k)
    bq, bk = _fit_block(block_q, sq, 8), _fit_block(block_k, sk, 128)
    if jax.default_backend() == "cpu":
        interpret = True
    if not _pallas_ok(sq, sk, d, bq, bk) or (interpret and _has_vma(q3)) \
            or (dropout_rate > 0.0 and interpret) \
            or (not interpret and not mosaic_dtype_ok(q3, k3, v3)):
        return _ref_chunk_fwd(q3, k3, v3, scale, causal, dropout_rate,
                              dropout_seed)
    o3, lse = _fwd_pallas(q3, k3, v3, None, None, scale, causal, bq, bk,
                          interpret, dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)
    return jnp.asarray(o3, jnp.float32), lse[:, 0, :]


def attn_chunk_bwd(q3, k3, v3, do3, lse, delta, *, scale, causal,
                   block_q=None, block_k=None,
                   dropout_rate=0.0, dropout_seed=None,
                   interpret=False):
    """Chunk backward given residuals; returns fp32 (dq, dk, dv)."""
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    sq, sk, d = q3.shape[1], k3.shape[1], q3.shape[2]
    blocks_explicit = block_q is not None or block_k is not None
    block_q, block_k = _resolve_blocks(block_q, block_k)
    bq, bk = _fit_block(block_q, sq, 8), _fit_block(block_k, sk, 128)
    if not blocks_explicit:
        # explicit caller blocks win; only tuned/default geometry may
        # take the backward-specific knobs
        bq, bk = _resolve_bwd_blocks(bq, bk, sq, sk, dropout_rate)
    if jax.default_backend() == "cpu":
        interpret = True
    if not _pallas_ok(sq, sk, d, bq, bk) or (interpret and _has_vma(q3)) \
            or (dropout_rate > 0.0 and interpret) \
            or (not interpret and not mosaic_dtype_ok(q3, k3, v3, do3)):
        return _ref_chunk_bwd(q3, k3, v3, do3, lse, delta, scale, causal,
                              dropout_rate, dropout_seed)
    # _bwd_pallas recomputes p from lse and reads delta directly; o3 itself
    # is not needed once delta is in hand, so pass delta through. Inputs keep
    # their storage dtype (the kernels upcast per-tile); only the outputs are
    # forced fp32 for exact cross-chunk accumulation in the ring.
    bh = q3.shape[0]
    lse3 = lse.reshape(bh, 1, sq)
    dq, dk, dv, _ = _bwd_pallas(q3, k3, v3, do3, lse3,
                                delta.reshape(bh, 1, sq), None, None,
                                scale, causal, bq, bk, interpret,
                                out_dtype=jnp.float32,
                                dropout_rate=dropout_rate,
                                dropout_seed=dropout_seed)
    return dq, dk, dv


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, bias, segment_ids, dropout_seed, causal, scale, block_q,
           block_k, interpret, dropout_rate, blocks_explicit):
    out, _ = _flash_fwd(q, k, v, bias, segment_ids, dropout_seed, causal,
                        scale, block_q, block_k, interpret, dropout_rate,
                        blocks_explicit)
    return out


def _flash_fwd(q, k, v, bias, segment_ids, dropout_seed, causal, scale,
               block_q, block_k, interpret, dropout_rate,
               blocks_explicit=False):
    b, h, sq, d = q.shape
    q3, k3, v3 = _flatten(q), _flatten(k), _flatten(v)
    segq = segk = None
    if segment_ids is not None:
        segq = _seg_flat(segment_ids, h)
        segk = segq
    o3, lse = _fwd_pallas(q3, k3, v3, segq, segk, scale, causal, block_q,
                          block_k, interpret, bias=bias, h=h,
                          dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)
    out = o3.reshape(b, h, sq, d)
    return out, (q3, k3, v3, o3, lse, segq, segk, bias, dropout_seed, b, h)


def _flash_bwd(causal, scale, block_q, block_k, interpret, dropout_rate,
               blocks_explicit, res, g):
    q3, k3, v3, o3, lse, segq, segk, bias, dropout_seed, b, h = res
    do3 = _flatten(g)
    bh, sq = q3.shape[0], q3.shape[1]
    if not blocks_explicit:
        # explicit caller blocks win for BOTH passes; only tuned/default
        # geometry may take the backward-specific knobs
        block_q, block_k = _resolve_bwd_blocks(block_q, block_k, sq,
                                               k3.shape[1], dropout_rate)
    delta = jnp.sum(jnp.asarray(do3, jnp.float32) *
                    jnp.asarray(o3, jnp.float32), axis=-1,
                    keepdims=True).reshape(bh, 1, sq)
    dq3, dk3, dv3, dlog = _bwd_pallas(q3, k3, v3, do3, lse, delta, segq,
                                      segk, scale, causal, block_q, block_k,
                                      interpret, bias=bias, h=h,
                                      dropout_rate=dropout_rate,
                                      dropout_seed=dropout_seed)
    sq, d = q3.shape[1], q3.shape[2]
    sk = k3.shape[1]
    dq = dq3.reshape(b, h, sq, d)
    dk = dk3.reshape(b, h, sk, d)
    dv = dv3.reshape(b, h, sk, d)
    dbias = None
    if bias is not None:
        # dlog arrives already reduced to the bias's broadcast class
        # ([B*, sq, sk] with B* = prod of bias's leading dims)
        dbias = dlog.reshape(bias.shape).astype(bias.dtype)
    return dq, dk, dv, dbias, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: Optional[float] = None,
                    segment_ids: Optional[jnp.ndarray] = None,
                    bias: Optional[jnp.ndarray] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False):
    """Fused attention. q,k,v: [batch, heads, seq, head_dim].

    ``segment_ids``: [batch, seq] int — varlen packing (fmhalib parity);
    tokens attend only within equal segment ids. ``scale`` defaults to
    1/sqrt(head_dim) (the reference kernels bake the same default).

    ``bias``: ADDITIVE logits bias of shape [b|1, h|1, sq, sk] (the apex
    additive-mask MHA variants / evoformer pair bias), applied after the
    q·k scale. Differentiable; the bias cotangent costs one O(s²) fp32
    buffer in backward (the same footprint unfused attention pays) — the
    bias-free path allocates nothing extra.

    ``dropout_rate``/``dropout_seed``: fused softmax-probability dropout
    (reference: fast_multihead_attn's fused softmax+dropout with philox
    replay, N11). The mask is generated in-kernel from the hardware PRNG,
    seeded per (batch·head, q-block, k-block) from ``dropout_seed`` (an
    int32 scalar — vary it per training step; inside shard_map also fold
    the shard's ``lax.axis_index`` into it, or every shard draws the same
    mask field), and REPLAYED exactly in backward. On the CPU/interpret
    fallback the mask comes from jax.random instead (same semantics,
    different stream — matching how the reference's python and fused impls
    differ). Hardware replay is covered by tests/tpu/ (self-skipping on
    the CPU CI backend).
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 requires dropout_seed")
    sq, sk = q.shape[2], k.shape[2]
    # validated on EVERY path: the jnp fallback must reject exactly what the
    # Pallas path rejects, or aligned shapes would crash where unaligned ran
    _validate_bias(bias, q.shape[0], q.shape[1], sq, sk)
    # explicitness MUST be read before _resolve_blocks overwrites the
    # Nones — computed after, the flag is always True and the bwd knobs
    # are dead (caught by code review + the gating test)
    blocks_explicit = block_q is not None or block_k is not None
    block_q, block_k = _resolve_blocks(block_q, block_k)
    bq = _fit_block(block_q, sq, 8)
    bk = _fit_block(block_k, sk, 128)
    if jax.default_backend() == "cpu":
        interpret = True  # pallas-TPU lowering needs a TPU; CPU interprets
    if not _pallas_ok(sq, sk, d, bq, bk) or (interpret and _has_vma(q)) \
            or (dropout_rate > 0.0 and interpret) \
            or (not interpret and not mosaic_dtype_ok(q, k, v, bias)):
        # interpret mode has no pltpu PRNG lowering → jnp dropout fallback
        return mha_reference(q, k, v, causal=causal, scale=scale,
                             segment_ids=segment_ids, bias=bias,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed)
    return _flash(q, k, v, bias, segment_ids, dropout_seed, causal, scale,
                  bq, bk, interpret, dropout_rate, blocks_explicit)
