"""Fused scale + causal-mask + softmax Pallas kernel.

TPU-native equivalent of the reference's
``scaled_upper_triang_masked_softmax_cuda`` extension
(apex/contrib → csrc/megatron/scaled_upper_triang_masked_softmax.h —
scaled_upper_triang_masked_softmax_warp_forward/backward; SURVEY N8).
Semantics preserved: half I/O allowed, softmax math in fp32, strictly-upper-
triangular entries masked to zero probability.

Layout: rows ride a (batch, q-block) grid with the full key row in VMEM per
block (the xentropy kernel's layout). The causal structure is applied as an
in-register iota mask; entirely-masked key spans cost no exp/sum work on the
VPU (the "tile-skip win" of the CUDA kernel — note that for a kernel that
MATERIALIZES the probability matrix, HBM traffic bounds throughput, so the
skip is a compute saving; the full fusion of softmax into the surrounding
GEMMs, where skipping saves bandwidth too, is the flash-attention kernel).

Backward: dx = scale * p * (g - sum(g*p, -1)); causal zeros in p make the
masked gradient exactly zero with no explicit mask.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.kernels import vmem

__all__ = ["causal_softmax", "causal_softmax_reference"]

_NEG = -1e30


def causal_softmax_reference(x, scale: float = 1.0):
    """fp32 composed reference (the jnp fallback path)."""
    out_dtype = x.dtype
    x32 = jnp.asarray(x, jnp.float32) * scale
    sq, sk = x32.shape[-2], x32.shape[-1]
    mask = jnp.triu(jnp.ones((sq, sk), jnp.bool_), k=1)
    x32 = jnp.where(mask, _NEG, x32)
    y = jnp.exp(x32 - jnp.max(x32, axis=-1, keepdims=True))
    y = y / jnp.sum(y, axis=-1, keepdims=True)
    return jnp.asarray(y, out_dtype)


def _fwd_kernel(x_ref, out_ref, *, scale, bq):
    q0 = pl.program_id(1) * bq
    x = x_ref[0].astype(jnp.float32) * scale          # [bq, sk]
    rows = jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) + q0
    cols = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    x = jnp.where(cols > rows, _NEG, x)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    out = e / jnp.sum(e, axis=-1, keepdims=True)
    out_ref[0] = out.astype(out_ref.dtype)


def _bwd_kernel(p_ref, g_ref, out_ref, *, scale):
    p = p_ref[0].astype(jnp.float32)                  # [bq, sk]
    g = g_ref[0].astype(jnp.float32)
    dot = jnp.sum(g * p, axis=-1, keepdims=True)
    out_ref[0] = (scale * p * (g - dot)).astype(out_ref.dtype)


def _block_q(sq, sk):
    # fp32 row block + ~3 temporaries (exp, iota, output)
    return vmem.block_rows(sq, row_bytes=4 * sk, n_bufs=4, max_rows=128,
                           divisor_of=sq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _causal_softmax(x, scale, interpret):
    out, _ = _causal_fwd(x, scale, interpret)
    return out


def _causal_fwd(x, scale, interpret):
    n, sq, sk = x.shape
    bq = _block_q(sq, sk)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, bq=bq),
        grid=(n, sq // bq),
        in_specs=[pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0))],
        out_specs=pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, sq, sk), x.dtype),
        interpret=interpret,
    )(x)
    return out, out


def _causal_bwd(scale, interpret, p, g):
    n, sq, sk = p.shape
    bq = _block_q(sq, sk)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(n, sq // bq),
        in_specs=[pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
                  pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0))],
        out_specs=pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, sq, sk), p.dtype),
        interpret=interpret,
    )(p, g)
    return (dx,)


_causal_softmax.defvjp(_causal_fwd, _causal_bwd)


def causal_softmax(x, scale: float = 1.0, interpret: bool = False):
    """probs = softmax(scale * x + causal_mask) over the last dim.

    ``x``: [..., sq, sk], half or fp32; returns probs in the input dtype
    with fp32 softmax math (the reference kernel's contract). Unaligned
    shapes fall back to the jnp reference.
    """
    shape = x.shape
    sq, sk = shape[-2], shape[-1]
    n = 1
    for s in shape[:-2]:
        n *= s
    aligned = sk % 128 == 0 and sq % 8 == 0
    if not aligned:
        return causal_softmax_reference(x, scale)
    if jax.default_backend() == "cpu":
        interpret = True
    return _causal_softmax(x.reshape(n, sq, sk), scale,
                           interpret).reshape(shape)
