"""Fused scale + causal-mask + softmax Pallas kernel.

TPU-native equivalent of the reference's
``scaled_upper_triang_masked_softmax_cuda`` extension
(apex/contrib → csrc/megatron/scaled_upper_triang_masked_softmax.h —
scaled_upper_triang_masked_softmax_warp_forward/backward; SURVEY N8).
Semantics preserved: half I/O allowed, softmax math in fp32, strictly-upper-
triangular entries masked to zero probability.

Layout: rows ride a (batch, q-block) grid with the full key row block in
VMEM (the xentropy kernel's layout — the HBM load is the full row; for a
kernel that MATERIALIZES the probability matrix HBM traffic bounds
throughput either way). The causal structure drives a k-CHUNK compute
skip (VERDICT round-2 weak #3): inside the kernel, max/exp/sum/normalize
loops run only over the ~(q0+bq)/bk chunks that intersect the causal
triangle — the analogue of the CUDA kernel's triangular launch grid —
so the VPU work is ~half the full-row form at sq == sk; chunks strictly
above the diagonal are filled with zeros by a store-only loop. The fp32
exp lives in a VMEM scratch so the final normalize divides full-precision
values (the CUDA kernel's register residency).

Backward: dx = scale * p * (g - sum(g*p, -1)) with the same chunk skip;
causal zeros in p make the masked gradient exactly zero with no explicit
mask.

The full fusion of softmax into the surrounding GEMMs, where the skip
saves bandwidth too, is the flash-attention kernel (N11/N12).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import vmem

__all__ = ["causal_softmax", "causal_softmax_reference"]

_NEG = -1e30


def _chunk_cols(sk: int) -> int:
    """Lane-aligned k-chunk width: largest of 512/256/128 dividing sk."""
    for bk in (512, 256, 128):
        if sk % bk == 0:
            return bk
    return sk


def causal_softmax_reference(x, scale: float = 1.0):
    """fp32 composed reference (the jnp fallback path)."""
    out_dtype = x.dtype
    x32 = jnp.asarray(x, jnp.float32) * scale
    sq, sk = x32.shape[-2], x32.shape[-1]
    mask = jnp.triu(jnp.ones((sq, sk), jnp.bool_), k=1)
    x32 = jnp.where(mask, _NEG, x32)
    y = jnp.exp(x32 - jnp.max(x32, axis=-1, keepdims=True))
    y = y / jnp.sum(y, axis=-1, keepdims=True)
    return jnp.asarray(y, out_dtype)


def _fwd_kernel(x_ref, out_ref, e_scr, *, scale, bq, bk):
    q0 = pl.program_id(1) * bq
    sk = x_ref.shape[-1]
    nchunks = sk // bk
    # chunks intersecting the causal triangle for this q block
    kmax = jnp.minimum((q0 + bq - 1) // bk + 1, nchunks)
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + q0
    cols0 = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def chunk_x(c):
        x = x_ref[0, :, pl.ds(c * bk, bk)].astype(jnp.float32) * scale
        return jnp.where(cols0 + c * bk > rows, _NEG, x)

    m = jax.lax.fori_loop(
        0, kmax,
        lambda c, m: jnp.maximum(m, jnp.max(chunk_x(c), -1, keepdims=True)),
        jnp.full((bq, 1), _NEG, jnp.float32))

    def exp_body(c, l):
        e = jnp.exp(chunk_x(c) - m)
        e_scr[:, pl.ds(c * bk, bk)] = e
        return l + jnp.sum(e, -1, keepdims=True)

    l = jax.lax.fori_loop(0, kmax, exp_body,
                          jnp.zeros((bq, 1), jnp.float32))
    recip = 1.0 / l

    def write_body(c, carry):
        out_ref[0, :, pl.ds(c * bk, bk)] = (
            e_scr[:, pl.ds(c * bk, bk)] * recip).astype(out_ref.dtype)
        return carry

    jax.lax.fori_loop(0, kmax, write_body, 0)

    def zero_body(c, carry):
        out_ref[0, :, pl.ds(c * bk, bk)] = jnp.zeros((bq, bk),
                                                     out_ref.dtype)
        return carry

    jax.lax.fori_loop(kmax, nchunks, zero_body, 0)


def _bwd_kernel(p_ref, g_ref, out_ref, *, scale, bq, bk):
    q0 = pl.program_id(1) * bq
    sk = p_ref.shape[-1]
    nchunks = sk // bk
    kmax = jnp.minimum((q0 + bq - 1) // bk + 1, nchunks)

    def dot_body(c, acc):
        p = p_ref[0, :, pl.ds(c * bk, bk)].astype(jnp.float32)
        g = g_ref[0, :, pl.ds(c * bk, bk)].astype(jnp.float32)
        return acc + jnp.sum(g * p, -1, keepdims=True)

    dot = jax.lax.fori_loop(0, kmax, dot_body,
                            jnp.zeros((bq, 1), jnp.float32))

    def write_body(c, carry):
        p = p_ref[0, :, pl.ds(c * bk, bk)].astype(jnp.float32)
        g = g_ref[0, :, pl.ds(c * bk, bk)].astype(jnp.float32)
        out_ref[0, :, pl.ds(c * bk, bk)] = (
            scale * p * (g - dot)).astype(out_ref.dtype)
        return carry

    jax.lax.fori_loop(0, kmax, write_body, 0)

    def zero_body(c, carry):
        out_ref[0, :, pl.ds(c * bk, bk)] = jnp.zeros((bq, bk),
                                                     out_ref.dtype)
        return carry

    jax.lax.fori_loop(kmax, nchunks, zero_body, 0)


def _block_q(sq, sk):
    # fp32 row block + exp scratch + output + chunk temporaries
    return vmem.block_rows(sq, row_bytes=4 * sk, n_bufs=5, max_rows=128,
                           divisor_of=sq, key="causal_softmax.block_q")


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _causal_softmax(x, scale, interpret):
    out, _ = _causal_fwd(x, scale, interpret)
    return out


def _causal_fwd(x, scale, interpret):
    n, sq, sk = x.shape
    bq = _block_q(sq, sk)
    bk = _chunk_cols(sk)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk),
        grid=(n, sq // bq),
        in_specs=[pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0))],
        out_specs=pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, sq, sk), x.dtype),
        scratch_shapes=[pltpu.VMEM((bq, sk), jnp.float32)],
        interpret=interpret,
    )(x)
    return out, out


def _causal_bwd(scale, interpret, p, g):
    n, sq, sk = p.shape
    bq = _block_q(sq, sk)
    bk = _chunk_cols(sk)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, bq=bq, bk=bk),
        grid=(n, sq // bq),
        in_specs=[pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
                  pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0))],
        out_specs=pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, sq, sk), p.dtype),
        interpret=interpret,
    )(p, g)
    return (dx,)


_causal_softmax.defvjp(_causal_fwd, _causal_bwd)


def causal_softmax(x, scale: float = 1.0, interpret: bool = False):
    """probs = softmax(scale * x + causal_mask) over the last dim.

    ``x``: [..., sq, sk], half or fp32; returns probs in the input dtype
    with fp32 softmax math (the reference kernel's contract). Unaligned
    shapes fall back to the jnp reference.
    """
    shape = x.shape
    sq, sk = shape[-2], shape[-1]
    n = 1
    for s in shape[:-2]:
        n *= s
    aligned = sk % 128 == 0 and sq % 8 == 0
    if not aligned:
        return causal_softmax_reference(x, scale)
    if jax.default_backend() == "cpu":
        interpret = True
    return _causal_softmax(x.reshape(n, sq, sk), scale,
                           interpret).reshape(shape)
