"""Fused scale + arbitrary-mask + softmax Pallas kernel.

TPU-native equivalent of the reference's ``scaled_masked_softmax_cuda``
extension (apex/contrib → csrc/megatron/scaled_masked_softmax.h —
scaled_masked_softmax_warp_forward/backward; SURVEY N8 — this is the
SECOND kernel N8 names, the arbitrary-mask variant the padded-mask BERT
path hits; the causal one is kernels/causal_softmax.py). Semantics
preserved: half I/O allowed, softmax math in fp32, masked entries get the
additive ``-10000`` the CUDA kernel applies (probabilities underflow to
exactly zero in fp32 except for the degenerate all-masked row, which —
like the reference kernel — softmaxes to uniform).

Layout: rows ride a (batch, q-block) grid with the full key row block and
its MASK TILE in VMEM (same layout as causal_softmax; no tile-skip is
possible for arbitrary masks — the CUDA generic kernel also walks full
rows). The mask rides its own BlockSpec whose index map folds the
reference's broadcast pattern (mask ``[b, 1, sq, sk]`` against
``x [b, h, sq, sk]``): batch index ``i`` reads mask block ``i // rep``,
so the h-fold broadcast costs no HBM duplication.

Backward: dx = scale * p * (g - sum(g*p, -1)) — the CUDA backward's
formula, which does not re-apply the mask (masked p are exact zeros, so
masked dx are zeros, except in the all-masked-row corner where the CUDA
kernel also lets gradient flow).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.kernels import vmem

__all__ = ["masked_softmax", "masked_softmax_reference"]

_MASK_VALUE = -10000.0


def masked_softmax_reference(x, mask, scale: float = 1.0):
    """fp32 composed reference (the jnp fallback path). ``mask`` bool,
    True = masked out, broadcastable against x."""
    out_dtype = x.dtype
    x32 = jnp.asarray(x, jnp.float32) * scale
    if mask is not None:
        x32 = jnp.where(mask, _MASK_VALUE, x32)
    y = jnp.exp(x32 - jnp.max(x32, axis=-1, keepdims=True))
    y = y / jnp.sum(y, axis=-1, keepdims=True)
    return jnp.asarray(y, out_dtype)


def _fwd_kernel(x_ref, m_ref, out_ref, *, scale):
    x = x_ref[0].astype(jnp.float32) * scale          # [bq, sk]
    masked = m_ref[0] != 0
    x = jnp.where(masked, _MASK_VALUE, x)
    mx = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - mx)
    out_ref[0] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(
        out_ref.dtype)


def _bwd_kernel(p_ref, g_ref, out_ref, *, scale):
    p = p_ref[0].astype(jnp.float32)                  # [bq, sk]
    g = g_ref[0].astype(jnp.float32)
    dot = jnp.sum(g * p, axis=-1, keepdims=True)
    out_ref[0] = (scale * p * (g - dot)).astype(out_ref.dtype)


def _block_q(sq, sk):
    # fp32 row block + mask tile + ~3 temporaries
    return vmem.block_rows(sq, row_bytes=4 * sk, n_bufs=5, max_rows=128,
                           divisor_of=sq, key="masked_softmax.block_q")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _masked_softmax(x, mask_i8, scale, rep, interpret):
    out, _ = _masked_fwd(x, mask_i8, scale, rep, interpret)
    return out


def _masked_fwd(x, mask_i8, scale, rep, interpret):
    n, sq, sk = x.shape
    bq = _block_q(sq, sk)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale),
        grid=(n, sq // bq),
        in_specs=[pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
                  pl.BlockSpec((1, bq, sk),
                               lambda i, j: (i // rep, j, 0))],
        out_specs=pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, sq, sk), x.dtype),
        interpret=interpret,
    )(x, mask_i8)
    return out, out


def _masked_bwd(scale, rep, interpret, p, g):
    n, sq, sk = p.shape
    bq = _block_q(sq, sk)
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(n, sq // bq),
        in_specs=[pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
                  pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0))],
        out_specs=pl.BlockSpec((1, bq, sk), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((n, sq, sk), p.dtype),
        interpret=interpret,
    )(p, g)
    return (dx, None)


_masked_softmax.defvjp(_masked_fwd, _masked_bwd)


def _broadcast_rep(x_shape, mask_shape):
    """rep such that flat batch i of x reads flat mask batch i // rep, or
    None when the broadcast pattern isn't prefix-contiguous."""
    lead_x = x_shape[:-2]
    lead_m = mask_shape[:-2]
    if len(lead_m) > len(lead_x):
        return None
    lead_m = (1,) * (len(lead_x) - len(lead_m)) + tuple(lead_m)
    seen_one = False
    rep = 1
    for dx, dm in zip(lead_x, lead_m):
        if dm == dx and not seen_one:
            continue
        if dm == 1:
            seen_one = True
            rep *= dx
            continue
        return None
    return rep


def masked_softmax(x, mask, scale: float = 1.0, interpret: bool = False):
    """probs = softmax(scale * x + (-10000 where mask)) over the last dim.

    ``x``: [..., sq, sk], half or fp32; ``mask``: bool (True = masked
    out), trailing dims (sq, sk), leading dims equal to x's or a prefix
    of them followed by 1s (the reference's [b, 1, sq, sk] head
    broadcast). Returns probs in the input dtype with fp32 softmax math.
    Unaligned shapes or non-prefix broadcasts fall back to the jnp
    reference.
    """
    if mask is None:
        return masked_softmax_reference(x, None, scale)
    shape = x.shape
    sq, sk = shape[-2], shape[-1]
    n = 1
    for s in shape[:-2]:
        n *= s
    rep = None
    if mask.shape[-2:] == (sq, sk):
        rep = _broadcast_rep(shape, mask.shape)
    aligned = sk % 128 == 0 and sq % 8 == 0
    if not aligned or rep is None:
        return masked_softmax_reference(x, mask, scale)
    if jax.default_backend() == "cpu":
        interpret = True
    nm = n // rep
    mask_i8 = jnp.asarray(mask, jnp.int8).reshape(nm, sq, sk)
    return _masked_softmax(x.reshape(n, sq, sk), mask_i8, scale, rep,
                           interpret).reshape(shape)
