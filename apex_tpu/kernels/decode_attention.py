"""Cached-K/V decode attention — the serving tier's single-token kernel.

Training attention (:mod:`apex_tpu.kernels.flash_attention`) answers
"every query attends to every earlier key"; decode answers a different
question: ONE new query per sequence against a **preallocated KV cache**
of which only the first ``lengths[b]`` positions are valid. This is the
same move the flash-attention kernel lineage makes from training kernels
to cached inference: the blockwise online-softmax inner loop is
unchanged, but the query block degenerates to a single row and the
causal-block skip becomes a *length* skip — KV blocks entirely past the
sequence's valid length are never touched, so a request of length 37 in
a 1024-slot cache pays for ceil(38/block_k) blocks, not 8.

Layouts (matching the serving cache, one slot per batch row):

- ``q``: ``[batch, heads, head_dim]`` — the current token's query.
- ``k``/``v``: ``[batch, heads, max_len, head_dim]`` — the cache view.
- ``lengths``: ``[batch]`` int32 — valid positions per row (the current
  token's K/V must already be written at ``lengths-1``).

Numerics follow the kernel tier's contract: fp32 accumulation regardless
of I/O dtype (the cache is normally bf16 via the amp cast policies), and
a pure-jnp reference that doubles as the CPU/unaligned fallback and the
test oracle. Rows with ``lengths == 0`` return zeros (a defined value for
inactive serving slots — their output is discarded by the engine).

Block geometry rides the shared tuned-override registry
(:mod:`apex_tpu.kernels.vmem`) under new ``decode.*`` keys:
``decode.block_k`` (KV positions per grid step, lane-multiple 128) here,
and ``decode.prefill_block_q``/``decode.prefill_block_k`` consumed by
``serving.Engine`` for its prefill flash-attention geometry (prefill
shapes — short sequences, single-request batch — want different blocks
than the training sweep).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import mosaic_dtype_ok, vmem

__all__ = ["decode_attention", "decode_attention_reference"]

_NEG_INF = -1e30
DEFAULT_BLOCK_K = 256


# --------------------------------------------------------------- jnp reference
def decode_attention_reference(q, k, v, lengths, *, scale: float = 1.0):
    """fp32-math oracle: masked softmax over the valid cache prefix.

    ``q`` [b, h, d]; ``k``/``v`` [b, h, L, d]; ``lengths`` [b] int32.
    Returns [b, h, d] in ``q.dtype``; rows with ``lengths == 0`` are 0.
    """
    out_dtype = q.dtype
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bhd,bhld->bhl", q32, k32) * scale
    L = k.shape[2]
    valid = (jnp.arange(L, dtype=jnp.int32)[None, None, :]
             < lengths[:, None, None])
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,bhld->bhd", p, v32)
    live = (lengths > 0)[:, None, None]
    return jnp.asarray(jnp.where(live, out, 0.0), out_dtype)


# -------------------------------------------------------------------- kernel
def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, scale, block_k):
    """Grid (bh, nk): one batch·head row, blockwise over cached KV.

    Online softmax identical to the training forward kernel's (m, l)
    recurrence, with the causal tile-skip replaced by a length skip:
    a block whose first position is already past this row's valid
    length contributes nothing and is skipped entirely.
    """
    b = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)
    length = len_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(ki * block_k < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)                      # [1, d]
        k = k_ref[0].astype(jnp.float32)                      # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [1, bk]
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(cols < length, s, _NEG_INF)
        m_prev = m_ref[:1, :1]                                # [1, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                # [1, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:1, :1] = alpha * l_ref[:1, :1] + jnp.sum(
            p, axis=-1, keepdims=True)
        acc_ref[:1, :] = acc_ref[:1, :] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:1, :1] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:1, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:1, :] / l_safe).astype(o_ref.dtype)


def _decode_pallas(q3, k3, v3, len3, scale, bk, interpret):
    bh, d = q3.shape
    L = k3.shape[1]
    kernel = functools.partial(_decode_kernel, scale=scale, block_k=bk)
    out = pl.pallas_call(
        kernel,
        grid=(bh, L // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                # lengths
            pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),      # q
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),     # k
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),     # v
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((8, d), jnp.float32),      # acc (row 0 live)
            pltpu.VMEM((8, 128), jnp.float32),    # m
            pltpu.VMEM((8, 128), jnp.float32),    # l
        ],
        interpret=interpret,
    )(len3, q3.reshape(bh, 1, d), k3, v3)
    return out.reshape(bh, d)


# ------------------------------------------------------------------ dispatch
def _resolve_block(block_k):
    if block_k is None:
        block_k = vmem.get_override("decode.block_k", DEFAULT_BLOCK_K,
                                    multiple=128)
    return block_k


def decode_attention(q, k, v, lengths, *, scale: Optional[float] = None,
                     block_k: Optional[int] = None,
                     interpret: bool = False):
    """Single-token attention against a length-masked KV cache.

    ``q`` [batch, heads, head_dim]; ``k``/``v`` [batch, heads, max_len,
    head_dim] (the serving cache's per-layer view); ``lengths`` [batch]
    int32 — positions ``[0, lengths[b])`` are attended, everything past
    is masked. The current token's own K/V must already be written at
    position ``lengths[b] - 1`` (the serving engine's write-then-attend
    order). ``scale`` defaults to ``1/sqrt(head_dim)``.

    Inference-only (no VJP — decode never backprops). The Pallas path
    skips KV blocks past ``lengths[b]`` entirely, so short sequences in
    a long cache cost O(length), not O(max_len); unaligned shapes and
    non-Mosaic dtypes fall back to the jnp reference, which XLA fuses
    acceptably at decode's tiny per-step footprint.

    Tuned geometry: ``decode.block_k`` in the
    :mod:`apex_tpu.kernels.vmem` override registry (lane-multiple 128,
    clamped to the largest aligned divisor of ``max_len``).
    """
    b, h, d = q.shape
    L = k.shape[2]
    if k.shape != (b, h, L, d) or v.shape != k.shape:
        raise ValueError(f"decode_attention: k/v {k.shape}/{v.shape} do "
                         f"not match q {q.shape} + max_len")
    if lengths.shape != (b,):
        raise ValueError(f"decode_attention: lengths {lengths.shape} must "
                         f"be [{b}]")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    from apex_tpu.kernels.flash_attention import _fit_block, _has_vma
    bk = _fit_block(_resolve_block(block_k), L, 128)
    if jax.default_backend() == "cpu":
        interpret = True
    pallas_ok = (L % bk == 0 and d % 8 == 0 and bk % 128 == 0)
    if not pallas_ok or (interpret and _has_vma(q)) \
            or (not interpret and not mosaic_dtype_ok(q, k, v)):
        return decode_attention_reference(q, k, v, lengths, scale=scale)
    q3 = q.reshape(b * h, d)
    k3 = k.reshape(b * h, L, d)
    v3 = v.reshape(b * h, L, d)
    len3 = jnp.repeat(jnp.asarray(lengths, jnp.int32), h)
    out = _decode_pallas(q3, k3, v3, len3, scale, bk, interpret)
    live = (lengths > 0)[:, None, None]
    return jnp.where(live, out.reshape(b, h, d), 0).astype(q.dtype)
