"""Cached-K/V decode attention — the serving tier's single-token kernel.

Training attention (:mod:`apex_tpu.kernels.flash_attention`) answers
"every query attends to every earlier key"; decode answers a different
question: ONE new query per sequence against a **preallocated KV cache**
of which only the first ``lengths[b]`` positions are valid. This is the
same move the flash-attention kernel lineage makes from training kernels
to cached inference: the blockwise online-softmax inner loop is
unchanged, but the query block degenerates to a single row and the
causal-block skip becomes a *length* skip — KV blocks entirely past the
sequence's valid length are never touched, so a request of length 37 in
a 1024-slot cache pays for ceil(38/block_k) blocks, not 8.

Layouts (matching the serving cache, one slot per batch row):

- ``q``: ``[batch, heads, head_dim]`` — the current token's query.
- ``k``/``v``: ``[batch, heads, max_len, head_dim]`` — the cache view.
- ``lengths``: ``[batch]`` int32 — valid positions per row (the current
  token's K/V must already be written at ``lengths-1``).

Numerics follow the kernel tier's contract: fp32 accumulation regardless
of I/O dtype (the cache is normally bf16 via the amp cast policies), and
a pure-jnp reference that doubles as the CPU/unaligned fallback and the
test oracle. Rows with ``lengths == 0`` return zeros (a defined value for
inactive serving slots — their output is discarded by the engine).

Block geometry rides the shared tuned-override registry
(:mod:`apex_tpu.kernels.vmem`) under new ``decode.*`` keys:
``decode.block_k`` (KV positions per grid step, lane-multiple 128) here,
and ``decode.prefill_block_q``/``decode.prefill_block_k`` consumed by
``serving.Engine`` for its prefill flash-attention geometry (prefill
shapes — short sequences, single-request batch — want different blocks
than the training sweep).

**Paged variant** (:func:`paged_decode_attention`): the serving tier's
block-table refactor replaces the per-slot cache row with a dense pool
of fixed-size pages plus a ``[batch, max_pages]`` page table. The
kernel is the same online-softmax recurrence with ONE structural
change: the KV block index is no longer an affine function of the grid
position — block ``j`` of batch row ``b`` lives wherever
``page_table[b, j]`` says. Pallas expresses exactly that through
scalar-prefetch block index maps (``PrefetchScalarGridSpec``): the page
table rides SMEM ahead of the grid, and each (b, h, j) step DMAs pool
page ``page_table[b, j]`` instead of row offset ``j``. The length skip
is unchanged — pages wholly past ``lengths[b]`` are masked to the
sentinel page and their compute skipped.

**Tensor parallelism** (``serving.Engine(mesh=...)``): the kernels need
NO sharded variant. The grid iterates ``batch x heads`` (flattened to
``b*h`` rows here, an explicit heads dimension in the paged grid), so a
heads-sharded pool — ``[num_pages, heads/tp, page_len, head_dim]`` per
shard, the serving tier's TP layout — simply hands each shard a grid
with fewer heads-axis blocks over its own pool slice: the index maps
never mix heads, every DMA stays shard-local, and the per-shard math is
bit-identical to the single-chip kernel over that head subset.
Attention therefore contributes ZERO collectives to the sharded serving
programs (the psums live in the projection GEMMs; see
:mod:`apex_tpu.serving.sharding`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import mosaic_dtype_ok, vmem

__all__ = ["decode_attention", "decode_attention_reference",
           "paged_decode_attention", "paged_decode_attention_reference",
           "gather_pages"]

_NEG_INF = -1e30
DEFAULT_BLOCK_K = 256


# --------------------------------------------------------------- jnp reference
def decode_attention_reference(q, k, v, lengths, *, scale: float = 1.0,
                               k_scale=None, v_scale=None):
    """fp32-math oracle: masked softmax over the valid cache prefix.

    ``q`` [b, h, d]; ``k``/``v`` [b, h, L, d]; ``lengths`` [b] int32.
    Returns [b, h, d] in ``q.dtype``; rows with ``lengths == 0`` are 0.
    ``k_scale``/``v_scale`` ([h] fp32) are the quantized-cache tier's
    per-head dequantization scales: when given, ``k``/``v`` hold int8
    codes and are dequantized (cast + scale multiply) before the exact
    fp32 math — the gather-dequant oracle the in-kernel path is tested
    against.
    """
    out_dtype = q.dtype
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q, k, v))
    if k_scale is not None:
        k32 = k32 * jnp.asarray(k_scale, jnp.float32)[None, :, None, None]
    if v_scale is not None:
        v32 = v32 * jnp.asarray(v_scale, jnp.float32)[None, :, None, None]
    s = jnp.einsum("bhd,bhld->bhl", q32, k32) * scale
    L = k.shape[2]
    valid = (jnp.arange(L, dtype=jnp.int32)[None, None, :]
             < lengths[:, None, None])
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhl,bhld->bhd", p, v32)
    live = (lengths > 0)[:, None, None]
    return jnp.asarray(jnp.where(live, out, 0.0), out_dtype)


# -------------------------------------------------------------------- kernel
def _decode_kernel(len_ref, *refs, scale, block_k, quant):
    """Grid (bh, nk): one batch·head row, blockwise over cached KV.

    Online softmax identical to the training forward kernel's (m, l)
    recurrence, with the causal tile-skip replaced by a length skip:
    a block whose first position is already past this row's valid
    length contributes nothing and is skipped entirely.

    ``quant`` (static) threads the int8-cache tier through: two extra
    SMEM refs carry the per-row K/V dequantization scales, the K scale
    folds into the existing logit multiply and the V scale into the
    accumulator update — dequantization fused with the attend, the
    int8 block never expanding outside VMEM. The non-quant trace is
    byte-identical to before the tier existed.
    """
    if quant:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, \
            l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    ki = pl.program_id(1)
    nk = pl.num_programs(1)
    length = len_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(ki * block_k < length)
    def _body():
        q = q_ref[0].astype(jnp.float32)                      # [1, d]
        k = k_ref[0].astype(jnp.float32)                      # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [1, bk]
        if quant:
            # dequant-in-kernel: the per-head K scale is constant over
            # the row, so it factors out of the int8 dot product
            s = s * ks_ref[b]
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        s = jnp.where(cols < length, s, _NEG_INF)
        m_prev = m_ref[:1, :1]                                # [1, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                # [1, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:1, :1] = alpha * l_ref[:1, :1] + jnp.sum(
            p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quant:
            pv = pv * vs_ref[b]
        acc_ref[:1, :] = acc_ref[:1, :] * alpha + pv
        m_ref[:1, :1] = m_new

    @pl.when(ki == nk - 1)
    def _finish():
        l = l_ref[:1, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:1, :] / l_safe).astype(o_ref.dtype)


def _decode_pallas(q3, k3, v3, len3, scale, bk, interpret, ks3=None,
                   vs3=None):
    bh, d = q3.shape
    L = k3.shape[1]
    quant = ks3 is not None
    kernel = functools.partial(_decode_kernel, scale=scale, block_k=bk,
                               quant=quant)
    scale_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2 \
        if quant else []
    scale_ops = (ks3, vs3) if quant else ()
    out = pl.pallas_call(
        kernel,
        grid=(bh, L // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                # lengths
            *scale_specs,                         # k/v dequant scales
            pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),      # q
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),     # k
            pl.BlockSpec((1, bk, d), lambda b, j: (b, j, 0)),     # v
        ],
        out_specs=pl.BlockSpec((1, 1, d), lambda b, j: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, 1, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((8, d), jnp.float32),      # acc (row 0 live)
            pltpu.VMEM((8, 128), jnp.float32),    # m
            pltpu.VMEM((8, 128), jnp.float32),    # l
        ],
        interpret=interpret,
    )(len3, *scale_ops, q3.reshape(bh, 1, d), k3, v3)
    return out.reshape(bh, d)


# ------------------------------------------------------------------ dispatch
def _resolve_block(block_k):
    if block_k is None:
        block_k = vmem.get_override("decode.block_k", DEFAULT_BLOCK_K,
                                    multiple=128)
    return block_k


def _check_head_scales(name, h, k_scale, v_scale):
    """Quantized-cache scale validation shared by the four dispatchers:
    scales come as a pair of [heads] fp32 vectors or not at all."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError(f"{name}: k_scale and v_scale must be given "
                         f"together (int8 K and V are stored with "
                         f"independent per-head scales)")
    if k_scale is not None:
        for nm, s in (("k_scale", k_scale), ("v_scale", v_scale)):
            if s.shape != (h,):
                raise ValueError(f"{name}: {nm} {s.shape} must be "
                                 f"[{h}] (one scale per head)")


def decode_attention(q, k, v, lengths, *, scale: Optional[float] = None,
                     block_k: Optional[int] = None,
                     k_scale=None, v_scale=None,
                     interpret: bool = False):
    """Single-token attention against a length-masked KV cache.

    ``q`` [batch, heads, head_dim]; ``k``/``v`` [batch, heads, max_len,
    head_dim] (the serving cache's per-layer view); ``lengths`` [batch]
    int32 — positions ``[0, lengths[b])`` are attended, everything past
    is masked. The current token's own K/V must already be written at
    position ``lengths[b] - 1`` (the serving engine's write-then-attend
    order). ``scale`` defaults to ``1/sqrt(head_dim)``.

    Inference-only (no VJP — decode never backprops). The Pallas path
    skips KV blocks past ``lengths[b]`` entirely, so short sequences in
    a long cache cost O(length), not O(max_len); unaligned shapes and
    non-Mosaic dtypes fall back to the jnp reference, which XLA fuses
    acceptably at decode's tiny per-step footprint.

    Quantized cache (``k_scale``/``v_scale``, both ``[heads]`` fp32):
    ``k``/``v`` hold int8 codes dequantized IN-KERNEL — the K scale
    rides the logit multiply, the V scale the accumulator update — so
    the half-width cache bytes stream through VMEM and never expand in
    HBM. The fallback path dequantizes in the jnp oracle instead (same
    math, materialised).

    Tuned geometry: ``decode.block_k`` in the
    :mod:`apex_tpu.kernels.vmem` override registry (lane-multiple 128,
    clamped to the largest aligned divisor of ``max_len``).
    """
    b, h, d = q.shape
    L = k.shape[2]
    if k.shape != (b, h, L, d) or v.shape != k.shape:
        raise ValueError(f"decode_attention: k/v {k.shape}/{v.shape} do "
                         f"not match q {q.shape} + max_len")
    if lengths.shape != (b,):
        raise ValueError(f"decode_attention: lengths {lengths.shape} must "
                         f"be [{b}]")
    _check_head_scales("decode_attention", h, k_scale, v_scale)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    from apex_tpu.kernels.flash_attention import _fit_block, _has_vma
    bk = _fit_block(_resolve_block(block_k), L, 128)
    if jax.default_backend() == "cpu":
        interpret = True
    pallas_ok = (L % bk == 0 and d % 8 == 0 and bk % 128 == 0)
    if not pallas_ok or (interpret and _has_vma(q)) \
            or (not interpret and not mosaic_dtype_ok(q, k, v)):
        return decode_attention_reference(q, k, v, lengths, scale=scale,
                                          k_scale=k_scale,
                                          v_scale=v_scale)
    q3 = q.reshape(b * h, d)
    k3 = k.reshape(b * h, L, d)
    v3 = v.reshape(b * h, L, d)
    len3 = jnp.repeat(jnp.asarray(lengths, jnp.int32), h)
    ks3 = vs3 = None
    if k_scale is not None:
        # flattened bh rows walk heads fastest: row b*h + hh -> head hh
        ks3 = jnp.tile(jnp.asarray(k_scale, jnp.float32), b)
        vs3 = jnp.tile(jnp.asarray(v_scale, jnp.float32), b)
    out = _decode_pallas(q3, k3, v3, len3, scale, bk, interpret, ks3,
                         vs3)
    live = (lengths > 0)[:, None, None]
    return jnp.where(live, out.reshape(b, h, d), 0).astype(q.dtype)


# ------------------------------------------------------------ paged variant
def gather_pages(pool, page_table):
    """Materialise a contiguous per-row cache view from a paged pool:
    ``pool`` [num_pages, heads, page_len, d] + ``page_table``
    [batch, max_pages] int32 -> [batch, heads, max_pages * page_len, d].

    The paged kernels' oracle building block (and the CPU/unaligned
    fallback's first step): positions ``[j*page_len, (j+1)*page_len)``
    of row ``b`` are pool page ``page_table[b, j]``. Entries past a
    row's allocated pages point at the sentinel page — garbage the
    length/causal masks keep out of every softmax."""
    B, P = page_table.shape
    h, page_len, d = pool.shape[1], pool.shape[2], pool.shape[3]
    gathered = pool[page_table]              # [B, P, h, page_len, d]
    return gathered.transpose(0, 2, 1, 3, 4).reshape(
        B, h, P * page_len, d)


def paged_decode_attention_reference(q, k_pool, v_pool, page_table,
                                     lengths, *, scale: float = 1.0,
                                     k_scale=None, v_scale=None):
    """fp32-math oracle: gather the page-table view, then the exact
    contiguous decode reference. ``q`` [b, h, d]; pools
    [num_pages, h, page_len, d]; ``page_table`` [b, max_pages];
    ``lengths`` [b] int32. With ``k_scale``/``v_scale`` ([h] fp32) the
    gathered int8 pages are dequantized before the exact math — the
    gather-dequant oracle of the quantized-cache tier."""
    k = gather_pages(k_pool, page_table)
    v = gather_pages(v_pool, page_table)
    return decode_attention_reference(q, k, v, lengths, scale=scale,
                                      k_scale=k_scale, v_scale=v_scale)


def _paged_decode_kernel(pt_ref, len_ref, *refs, scale, page_len, quant):
    """Grid (b, h, max_pages): one batch row x head, one pool page per
    step. The (m, l) recurrence is :func:`_decode_kernel`'s; the page
    the DMA fetched was chosen by the scalar-prefetch index map
    (``pt_ref[b, j]``), so the kernel body only needs the length skip/
    mask on GLOBAL positions ``j * page_len + lane``. ``quant``
    (static) adds two scalar-prefetch scale refs and the same fused
    per-head dequant multiplies as :func:`_decode_kernel`."""
    if quant:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, \
            l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    hh = pl.program_id(1)
    j = pl.program_id(2)
    nj = pl.num_programs(2)
    length = len_ref[b]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    @pl.when(j * page_len < length)
    def _body():
        q = q_ref[0, 0][None, :].astype(jnp.float32)          # [1, d]
        k = k_ref[0, 0].astype(jnp.float32)                   # [pl, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [1, pl]
        if quant:
            s = s * ks_ref[hh]
        cols = j * page_len + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_len), 1)
        s = jnp.where(cols < length, s, _NEG_INF)
        m_prev = m_ref[:1, :1]                                # [1, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                                # [1, pl]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:1, :1] = alpha * l_ref[:1, :1] + jnp.sum(
            p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quant:
            pv = pv * vs_ref[hh]
        acc_ref[:1, :] = acc_ref[:1, :] * alpha + pv
        m_ref[:1, :1] = m_new

    @pl.when(j == nj - 1)
    def _finish():
        l = l_ref[:1, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:1, :] / l_safe)[0].astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pool, v_pool, pt, lengths, scale,
                         interpret, ks=None, vs=None):
    B, h, d = q.shape
    page_len = k_pool.shape[2]
    max_pages = pt.shape[1]
    quant = ks is not None
    kernel = functools.partial(_paged_decode_kernel, scale=scale,
                               page_len=page_len, quant=quant)
    # the dequant scales ride as two extra scalar-prefetch operands (the
    # variadic tail absorbs them — only the kernel body reads them)
    def _q_idx(b, hh, j, pt, ln, *_scales):
        return (b, hh, 0)

    def _kv_idx(b, hh, j, pt, ln, *_scales):
        return (pt[b, j], hh, 0, 0)

    n_prefetch, extra_ops = (4, (ks, vs)) if quant else (2, ())
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,   # page_table, lengths[, ks, vs]
        grid=(B, h, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, d), _q_idx),
            pl.BlockSpec((1, 1, page_len, d), _kv_idx),
            pl.BlockSpec((1, 1, page_len, d), _kv_idx),
        ],
        out_specs=pl.BlockSpec((1, 1, d), _q_idx),
        scratch_shapes=[
            pltpu.VMEM((8, d), jnp.float32),      # acc (row 0 live)
            pltpu.VMEM((8, 128), jnp.float32),    # m
            pltpu.VMEM((8, 128), jnp.float32),    # l
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h, d), q.dtype),
        interpret=interpret,
    )(pt, lengths, *extra_ops, q, k_pool, v_pool)


def paged_decode_attention(q, k_pool, v_pool, page_table, lengths, *,
                           scale: Optional[float] = None,
                           k_scale=None, v_scale=None,
                           interpret: bool = False):
    """Single-token attention against a PAGED, length-masked KV pool.

    ``q`` [batch, heads, head_dim]; ``k_pool``/``v_pool``
    [num_pages, heads, page_len, head_dim] (one layer of the serving
    pool — pages are shared across batch rows); ``page_table``
    [batch, max_pages] int32 maps row ``b``'s logical block ``j`` to a
    pool page (sentinel ids for unallocated blocks — masked, never
    attended); ``lengths`` [batch] int32 as in
    :func:`decode_attention`. The current token's K/V must already be
    written at logical position ``lengths[b] - 1`` of its row's pages.
    ``scale`` defaults to ``1/sqrt(head_dim)``.

    Inference-only. The Pallas path walks each row's page list through
    scalar-prefetch index maps — one pool-page DMA per grid step, with
    pages past ``lengths[b]`` skipping their compute — so a short
    request in a big pool costs O(length) MXU work exactly like the
    contiguous kernel, while the pool itself stays dense and shared.
    Unaligned shapes and non-Mosaic dtypes fall back to the
    gather-then-reference oracle.
    """
    B, h, d = q.shape
    P, hp, page_len, dp = k_pool.shape
    if v_pool.shape != k_pool.shape or hp != h or dp != d:
        raise ValueError(f"paged_decode_attention: pools "
                         f"{k_pool.shape}/{v_pool.shape} do not match q "
                         f"{q.shape}")
    if page_table.ndim != 2 or page_table.shape[0] != B:
        raise ValueError(f"paged_decode_attention: page_table "
                         f"{page_table.shape} must be [{B}, max_pages]")
    if lengths.shape != (B,):
        raise ValueError(f"paged_decode_attention: lengths "
                         f"{lengths.shape} must be [{B}]")
    _check_head_scales("paged_decode_attention", h, k_scale, v_scale)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    from apex_tpu.kernels.flash_attention import _has_vma
    if jax.default_backend() == "cpu":
        interpret = True
    pallas_ok = (d % 8 == 0 and page_len % 128 == 0)
    if not pallas_ok or (interpret and _has_vma(q)) \
            or (not interpret and not mosaic_dtype_ok(q, k_pool, v_pool)):
        return paged_decode_attention_reference(
            q, k_pool, v_pool, page_table, lengths, scale=scale,
            k_scale=k_scale, v_scale=v_scale)
    pt = jnp.asarray(page_table, jnp.int32)
    len32 = jnp.asarray(lengths, jnp.int32)
    ks = vs = None
    if k_scale is not None:
        ks = jnp.asarray(k_scale, jnp.float32)
        vs = jnp.asarray(v_scale, jnp.float32)
    out = _paged_decode_pallas(q, k_pool, v_pool, pt, len32, scale,
                               interpret, ks, vs)
    live = (lengths > 0)[:, None, None]
    return jnp.where(live, out, 0).astype(q.dtype)
