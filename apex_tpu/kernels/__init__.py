"""apex_tpu.kernels — the Pallas (Mosaic) kernel tier.

TPU-native equivalents of the reference's csrc/ CUDA kernels (SURVEY §3.2).
Every kernel:

- accumulates in fp32 regardless of I/O dtype (matching apex's kernels);
- has a pure-jnp reference implementation used both as the CPU/interpret
  fallback and as the oracle in tests (the reference's test strategy:
  fused-vs-composed-eager comparison, tests/L0/run_fused_layer_norm/);
- auto-falls back to the jnp path off-TPU so the suite runs hermetically
  (the reference's "usable as pure-Python when exts missing" property).
"""

import jax


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


from .layer_norm import (  # noqa: E402,F401
    layer_norm, rms_norm, layer_norm_reference, rms_norm_reference)
from .multi_tensor import (  # noqa: E402,F401
    fused_scale, fused_axpby, fused_l2norm, fused_adam_step, fused_sgd_step)
