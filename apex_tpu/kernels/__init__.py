"""apex_tpu.kernels — the Pallas (Mosaic) kernel tier.

TPU-native equivalents of the reference's csrc/ CUDA kernels (SURVEY §3.2).
Every kernel:

- accumulates in fp32 regardless of I/O dtype (matching apex's kernels);
- has a pure-jnp reference implementation used both as the CPU/interpret
  fallback and as the oracle in tests (the reference's test strategy:
  fused-vs-composed-eager comparison, tests/L0/run_fused_layer_norm/);
- auto-falls back to the jnp path off-TPU so the suite runs hermetically
  (the reference's "usable as pure-Python when exts missing" property).
"""

import jax


def on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def mosaic_dtype_ok(*xs) -> bool:
    """TPU Mosaic has no fp16 (the MXU/VPU are bf16/fp32 machines): a
    float16 operand must take the jnp fallback, where XLA upconverts —
    found by the on-silicon scaler soak, whose fp16 model crashed every
    fused kernel's real lowering. interpret mode is unaffected (callers
    keep `or interpret`). Accepts arrays OR bare dtypes; None skipped."""
    import jax.numpy as jnp
    import numpy as np

    def dt(x):
        return np.dtype(getattr(x, "dtype", x))

    return all(dt(x) != jnp.float16 for x in xs if x is not None)


from .layer_norm import (  # noqa: E402,F401
    layer_norm, rms_norm, layer_norm_reference, rms_norm_reference)
from .multi_tensor import (  # noqa: E402,F401
    fused_scale, fused_axpby, fused_l2norm, fused_adam_step, fused_sgd_step)
from .decode_attention import (  # noqa: E402,F401
    decode_attention, decode_attention_reference)
from .prefill_attention import (  # noqa: E402,F401
    prefill_attention, prefill_attention_reference)
