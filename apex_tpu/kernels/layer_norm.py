"""Fused LayerNorm / RMSNorm Pallas kernels with custom VJP.

TPU-native equivalent of the reference's ``fused_layer_norm_cuda`` extension
(csrc/layer_norm_cuda_kernel.cu — cuApplyLayerNorm, cuWelfordMuSigma2,
cuComputeGradInput, cuComputePartGradGammaBeta) and the contrib "fast layer
norm" (apex/contrib/csrc/layer_norm/ln_fwd_kernels.cuh). Semantics preserved:

- forward saves (mean, invvar) in fp32 for backward — not the normalized
  output (memory_efficient=False semantics, the apex default);
- ``memory_efficient=True`` mirrors apex's flag of the same name
  (fused_layer_norm.py — memory_efficient forward): the backward keeps the
  OUTPUT y (plus rstd) instead of the input x and reconstructs
  xhat = (y - beta)/gamma, so a mid-graph x dies right after the forward —
  the round-5 answer to the priced LN residency negative (BASELINE.md).
  Like apex, it requires gamma nonzero everywhere (the reconstruction
  divides by it);
- all statistics and grad reductions accumulate in fp32 whatever the I/O
  dtype (apex computes Welford in accscalar_t = float);
- gamma/beta gradients are column reductions accumulated across row blocks
  (apex's two-stage cuComputePartGradGammaBeta/cuComputeGradGammaBeta
  becomes a grid-revisited accumulator block).

Design notes (TPU): rows are blocked over a 1-D grid; the full hidden dim
sits in VMEM per block (lane-aligned H). Unaligned hidden sizes fall back to
the jnp reference path — XLA fuses that chain well; the Pallas win is for the
transformer-shaped (H % 128 == 0) hot path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.kernels import vmem
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _block_rows(n_rows: int, hidden: int, n_bufs: int) -> int:
    # shared scoped-VMEM budget heuristic (kernels/vmem.py) clamps to n_rows
    return vmem.block_rows(n_rows, row_bytes=4 * hidden, n_bufs=n_bufs,
                           key="layer_norm.block_rows")


def _pallas_ok(n: int, h: int, dtype=None) -> bool:
    from . import mosaic_dtype_ok, on_tpu

    return on_tpu() and h % 128 == 0 and mosaic_dtype_ok(dtype)


# ----------------------------------------------------------------- references
def layer_norm_reference(x, weight=None, bias=None, eps=1e-5):
    """Composed-op oracle (the reference tests compare against
    torch.nn.LayerNorm; here: pure jnp in fp32)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_reference(x, weight=None, eps=1e-5):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x.dtype)


# -------------------------------------------------------------------- kernels
def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps,
                   affine, rms):
    x = x_ref[:].astype(jnp.float32)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(x * x, axis=1, keepdims=True)
    else:
        mean = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    if affine:
        y = y * g_ref[:].astype(jnp.float32)
        if not rms:
            y = y + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _bwd_from_xhat(dy, xhat, dyg, rstd, rms):
    """cuComputeGradInput: dx = rstd*(dyg - mean(dyg) - xhat*mean(dyg*xhat))
    (RMS: no mean(dyg) term — no mean was subtracted in fwd). Shared by
    the save-x and save-y (memory_efficient) backwards, Pallas and jnp —
    the two variants differ ONLY in how xhat is derived. Returns
    (dx, dg_rows, db_rows) in fp32; dg/db still need the column
    reduction."""
    c2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
    if rms:
        dx = rstd * (dyg - xhat * c2)
    else:
        c1 = jnp.mean(dyg, axis=-1, keepdims=True)
        dx = rstd * (dyg - c1 - xhat * c2)
    return dx, dy * xhat, dy


def _ln_bwd_kernel(dy_ref, src_ref, g_ref, aux_ref, rstd_ref,
                   dx_ref, dg_ref, db_ref, *, affine, rms, mem_eff):
    """One backward kernel for both residual layouts. Default (save-x):
    ``src`` is the input x, ``aux`` its per-row mean, xhat=(x-mean)*rstd.
    memory_efficient (save-y, apex's flag): ``src`` is the OUTPUT y,
    ``aux`` is beta broadcast as a (1, h) row, xhat=(y-beta)/gamma —
    gamma must be nonzero, as in apex."""
    i = pl.program_id(0)
    dy = dy_ref[:].astype(jnp.float32)
    src = src_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    g = g_ref[:].astype(jnp.float32) if affine else None
    if mem_eff:
        if affine:
            xhat = (src / g if rms
                    else (src - aux_ref[:].astype(jnp.float32)) / g)
        else:
            xhat = src
    else:
        xhat = (src - aux_ref[:]) * rstd
    dyg = dy * g if affine else dy
    dx, dg_rows, db_rows = _bwd_from_xhat(dy, xhat, dyg, rstd, rms)
    dx_ref[:] = dx.astype(dx_ref.dtype)

    if affine:
        # grid-revisited accumulator block — the two-stage gamma/beta grad
        # reduction (cuComputePartGradGammaBeta) collapses to this.
        @pl.when(i == 0)
        def _():
            dg_ref[:] = jnp.zeros_like(dg_ref)
            if not rms:
                db_ref[:] = jnp.zeros_like(db_ref)

        dg_ref[:] += jnp.sum(dg_rows, axis=0, keepdims=True)
        if not rms:
            db_ref[:] += jnp.sum(db_rows, axis=0, keepdims=True)


def _pad_rows(arr, rows_p):
    n = arr.shape[0]
    if n == rows_p:
        return arr
    return jnp.pad(arr, ((0, rows_p - n), (0, 0)))


def _ln_fwd_pallas(x2, gamma, beta, eps, rms, interpret):
    n, h = x2.shape
    affine = gamma is not None
    nbufs = 3 + (2 if affine else 0)
    bm = _block_rows(n, h, nbufs)
    rows_p = ((n + bm - 1) // bm) * bm
    xp = _pad_rows(x2, rows_p)
    g2 = (gamma if affine else jnp.zeros((h,), x2.dtype)).reshape(1, h)
    # beta may be None even with a weight (weight-only affine)
    b2 = (beta if (affine and not rms and beta is not None)
          else jnp.zeros((h,), x2.dtype)).reshape(1, h)
    grid = (rows_p // bm,)
    kernel = functools.partial(_ln_fwd_kernel, eps=eps, affine=affine, rms=rms)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, h), x2.dtype),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows_p, 1), jnp.float32),
        ],
        interpret=interpret,
    )(xp, g2, b2)
    return y[:n], mean[:n], rstd[:n]


def _ln_bwd_pallas(dy2, src2, gamma, aux, rstd, rms, interpret,
                   mem_eff=False):
    """Shared backward wrapper. Default: ``src2``=x, ``aux``=mean [n,1].
    memory_efficient: ``src2``=y, ``aux``=beta (h,) or None."""
    n, h = src2.shape
    affine = gamma is not None
    nbufs = 4 + (3 if affine else 0)
    bm = _block_rows(n, h, nbufs)
    rows_p = ((n + bm - 1) // bm) * bm
    dyp, srcp = _pad_rows(dy2, rows_p), _pad_rows(src2, rows_p)
    rstdp = _pad_rows(rstd, rows_p)
    g2 = (gamma if affine else jnp.zeros((h,), src2.dtype)).reshape(1, h)
    if mem_eff:
        aux_arr = (aux if (affine and not rms and aux is not None)
                   else jnp.zeros((h,), src2.dtype)).reshape(1, h)
        aux_spec = pl.BlockSpec((1, h), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)
    else:
        aux_arr = _pad_rows(aux, rows_p)
        aux_spec = pl.BlockSpec((bm, 1), lambda i: (i, 0),
                                memory_space=pltpu.VMEM)
    grid = (rows_p // bm,)
    kernel = functools.partial(_ln_bwd_kernel, affine=affine, rms=rms,
                               mem_eff=mem_eff)
    dx, dg, db = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            aux_spec,
            pl.BlockSpec((bm, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, h), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_p, h), src2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret,
    )(dyp, srcp, g2, aux_arr, rstdp)
    return dx[:n], dg.reshape(h), db.reshape(h)


# ----------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _layer_norm(x2, gamma, beta, eps, rms, interpret, mem_eff=False):
    y, _, _ = _ln_fwd(x2, gamma, beta, eps, rms, interpret)
    return y


def _ln_fwd(x2, gamma, beta, eps, rms, interpret):
    n, h = x2.shape
    if _pallas_ok(n, h, x2.dtype) or interpret:
        return _ln_fwd_pallas(x2, gamma, beta, eps, rms, interpret)
    # jnp fallback still saves (mean, rstd) so bwd matches
    x32 = x2.astype(jnp.float32)
    if rms:
        mean = jnp.zeros((n, 1), jnp.float32)
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x32 - mean) * rstd
    if gamma is not None:
        y = y * gamma.astype(jnp.float32)
        if beta is not None and not rms:
            y = y + beta.astype(jnp.float32)
    return y.astype(x2.dtype), mean, rstd


def _layer_norm_fwd(x2, gamma, beta, eps, rms, interpret, mem_eff=False):
    y, mean, rstd = _ln_fwd(x2, gamma, beta, eps, rms, interpret)
    if mem_eff:
        # keep the OUTPUT, drop the input: x can die after the forward
        # (apex memory_efficient=True residuals: output + invvar)
        return y, (y, gamma, beta, rstd)
    return y, (x2, gamma, mean, rstd)


def _finish_affine(dx, dg, db, gamma, rms, affine):
    if not affine:
        return dx, None, None
    dgamma = dg.astype(gamma.dtype)
    dbeta = None if rms else db.astype(gamma.dtype)
    return dx, dgamma, dbeta


def _layer_norm_bwd(eps, rms, interpret, mem_eff, res, dy):
    if mem_eff:
        src2, gamma, beta, rstd = res      # src = the saved OUTPUT y
        aux = beta
    else:
        src2, gamma, aux, rstd = res       # src = the saved input x, aux = mean
    n, h = src2.shape
    affine = gamma is not None
    if _pallas_ok(n, h, src2.dtype) or interpret:
        dx, dg, db = _ln_bwd_pallas(dy, src2, gamma, aux, rstd, rms,
                                    interpret, mem_eff=mem_eff)
    else:
        dy32 = dy.astype(jnp.float32)
        src32 = src2.astype(jnp.float32)
        if mem_eff:
            if affine:
                g32 = gamma.astype(jnp.float32)
                # bias may be None with a weight (public API allows it;
                # the Pallas branch zero-fills the same way)
                b32 = (beta.astype(jnp.float32)
                       if (beta is not None and not rms) else 0.0)
                xhat = (src32 - b32) / g32
            else:
                xhat = src32
        else:
            xhat = (src32 - aux) * rstd
        dyg = dy32 * gamma.astype(jnp.float32) if affine else dy32
        dx, dg_rows, db_rows = _bwd_from_xhat(dy32, xhat, dyg, rstd, rms)
        dx = dx.astype(src2.dtype)
        dg = jnp.sum(dg_rows, axis=0)
        db = jnp.sum(db_rows, axis=0)
    return _finish_affine(dx, dg, db, gamma, rms, affine)


_layer_norm.defvjp(_layer_norm_fwd, _layer_norm_bwd)


def layer_norm(x, weight: Optional[jnp.ndarray] = None,
               bias: Optional[jnp.ndarray] = None, eps: float = 1e-5,
               interpret: bool = False, memory_efficient: bool = False):
    """Fused layer norm over the last dim (apex FusedLayerNormAffineFunction).

    ``weight``/``bias`` of shape (H,) or None (non-affine variant,
    apex FusedLayerNormFunction). ``memory_efficient`` keeps the OUTPUT
    (not the input) for backward, reconstructing xhat=(y-beta)/gamma —
    apex's flag of the same name; requires nonzero gamma."""
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    y = _layer_norm(x2, weight, bias, float(eps), False, interpret,
                    memory_efficient)
    return y.reshape(shape)


def rms_norm(x, weight: Optional[jnp.ndarray] = None, eps: float = 1e-5,
             interpret: bool = False, memory_efficient: bool = False):
    """Fused RMS norm (apex FusedRMSNormAffineFunction); see
    :func:`layer_norm` for ``memory_efficient``."""
    shape = x.shape
    h = shape[-1]
    x2 = x.reshape(-1, h)
    y = _layer_norm(x2, weight, None, float(eps), True, interpret,
                    memory_efficient)
    return y.reshape(shape)
