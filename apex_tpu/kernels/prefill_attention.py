"""Chunked-prefill attention — a block of queries against the KV cache.

:mod:`~apex_tpu.kernels.decode_attention` answers "ONE new token per
sequence against the cached prefix"; chunked prefill (Sarathi-style)
asks the in-between question: a CHUNK of ``C`` consecutive prompt tokens
per sequence, already written into the cache at positions
``[offset, offset + C)``, each attending causally over everything before
and including itself. The serving engine runs one such chunk per decode
heartbeat, so in-flight decodes never wait for a whole prompt — the
monolithic ``[1, prefill_len]`` prefill's head-of-line blocking becomes
at most one chunk of latency.

Geometry is the flash kernel's blockwise online softmax with the causal
diagonal shifted by a per-row *cache offset*: query row ``i`` of batch
row ``b`` sits at global position ``offsets[b] + i`` and attends cache
positions ``[0, offsets[b] + i]``. KV blocks entirely past the chunk's
last query position skip their compute (the decode kernel's length
skip, lifted to a q-block × k-block skip), so an early chunk of a long
prompt pays MXU work for the prefix it can see, not for ``max_len``
(the block pipeline still streams the full cache row through VMEM —
bounding the DMA extent too needs a trace-time cap on offsets, a
future lever).

Layouts (matching the serving cache, one slot per batch row):

- ``q``: ``[batch, heads, C, d]`` — the chunk's queries.
- ``k``/``v``: ``[batch, heads, max_len, d]`` — the cache view; the
  chunk's own K/V must already be written at ``[offset, offset + C)``
  (the serving tier's write-then-attend order).
- ``offsets``: ``[batch]`` int32 — valid cache positions before the
  chunk (= the slot's pre-chunk length).

Numerics follow the kernel tier's contract: fp32 accumulation regardless
of I/O dtype, and a pure-jnp reference that doubles as the CPU/unaligned
fallback and the test oracle. Pad rows of a final partial chunk compute
garbage that the engine never samples from.

Block geometry rides the shared tuned-override registry
(:mod:`apex_tpu.kernels.vmem`) under ``decode.chunk_block_q``
(sublane-multiple 8) and ``decode.chunk_block_k`` (lane-multiple 128).

**Paged variant** (:func:`paged_prefill_attention`): the block-table
refactor's chunk-ingestion kernel. Same shifted-causal online softmax,
but K/V arrive from a dense page pool through a ``[batch, max_pages]``
page table rather than a contiguous cache row: the KV grid dimension
walks the row's page list via scalar-prefetch block index maps (page
``j`` of row ``b`` DMAs pool page ``page_table[b, j]``, clamped at the
row's last reachable page ``(offsets[b] + C - 1) // page_len`` so grid
steps past the chunk's extent re-issue the same block index and cost
no new DMA — the fetch walk is O(offset + C) like the compute, not
O(max_pages)), the q-block × page skip runs on global positions
exactly as the contiguous kernel's q-block × k-block skip. The q-block
knob is ``decode.page_block_q`` (the KV block is pinned to one page —
the pool's DMA granule).

**Tensor parallelism** (``serving.Engine(mesh=...)``): no sharded
variant needed — the grid's heads dimension simply shrinks. A
heads-sharded pool (``heads/tp`` per shard) gives each shard the same
index maps over fewer heads-axis blocks of its own pool slice; no DMA
or mask ever crosses heads, so the per-shard kernel is unchanged math
over its head subset and attention adds no collectives to the sharded
serving programs (the block knobs above tune per-shard exactly as they
do single-chip — same shapes per head, fewer heads).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import mosaic_dtype_ok, vmem

__all__ = ["prefill_attention", "prefill_attention_reference",
           "paged_prefill_attention", "paged_prefill_attention_reference"]

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 256


# --------------------------------------------------------------- jnp reference
def prefill_attention_reference(q, k, v, offsets, *, scale: float = 1.0,
                                k_scale=None, v_scale=None):
    """fp32-math oracle: per-row shifted-causal softmax over the cache.

    ``q`` [b, h, C, d]; ``k``/``v`` [b, h, L, d]; ``offsets`` [b] int32.
    Query row ``i`` attends cache positions ``j <= offsets[b] + i``.
    Returns [b, h, C, d] in ``q.dtype``. ``k_scale``/``v_scale`` ([h]
    fp32) dequantize an int8 cache before the exact math (the
    quantized tier's oracle — see
    :func:`~apex_tpu.kernels.decode_attention.decode_attention_reference`).
    """
    out_dtype = q.dtype
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q, k, v))
    if k_scale is not None:
        k32 = k32 * jnp.asarray(k_scale, jnp.float32)[None, :, None, None]
    if v_scale is not None:
        v32 = v32 * jnp.asarray(v_scale, jnp.float32)[None, :, None, None]
    s = jnp.einsum("bhqd,bhld->bhql", q32, k32) * scale
    C, L = q.shape[2], k.shape[2]
    rows = (offsets[:, None, None, None]
            + jnp.arange(C, dtype=jnp.int32)[None, None, :, None])
    cols = jnp.arange(L, dtype=jnp.int32)[None, None, None, :]
    s = jnp.where(cols <= rows, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.asarray(jnp.einsum("bhql,bhld->bhqd", p, v32), out_dtype)


# -------------------------------------------------------------------- kernel
def _prefill_kernel(off_ref, *refs, scale, block_q, block_k, quant):
    """Grid (bh, nq, nk): one batch·head row, q-blocked chunk, blockwise
    over cached KV. The (m, l) recurrence is the flash forward kernel's;
    the causal skip/mask runs on GLOBAL query positions ``offset + row``
    instead of chunk-local ones, which is the whole difference between
    training attention and chunked prefill. ``quant`` (static) adds two
    per-row SMEM scale refs and fuses the int8-cache dequant multiplies
    into the logit/accumulator updates (the decode kernel's pattern)."""
    if quant:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, \
            l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    offset = off_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip KV blocks entirely past this q-block's LAST global position
    @pl.when(ki * block_k <= offset + qi * block_q + block_q - 1)
    def _body():
        q = q_ref[0].astype(jnp.float32)                     # [bq, d]
        k = k_ref[0].astype(jnp.float32)                     # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bq, bk]
        if quant:
            s = s * ks_ref[b]
        rows = offset + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:, :1]                                # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                               # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quant:
            pv = pv * vs_ref[b]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        # every row attends at least its own position, so l > 0 always;
        # the guard only keeps a mis-called kernel finite
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _prefill_pallas(q3, k3, v3, off3, scale, bq, bk, interpret,
                    ks3=None, vs3=None):
    bh, C, d = q3.shape
    L = k3.shape[1]
    quant = ks3 is not None
    kernel = functools.partial(_prefill_kernel, scale=scale, block_q=bq,
                               block_k=bk, quant=quant)
    scale_specs = [pl.BlockSpec(memory_space=pltpu.SMEM)] * 2 \
        if quant else []
    scale_ops = (ks3, vs3) if quant else ()
    return pl.pallas_call(
        kernel,
        grid=(bh, C // bq, L // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # offsets
            *scale_specs,                          # k/v dequant scales
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # v
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, C, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),      # acc
            pltpu.VMEM((bq, 128), jnp.float32),    # m (col 0 live)
            pltpu.VMEM((bq, 128), jnp.float32),    # l (col 0 live)
        ],
        interpret=interpret,
    )(off3, *scale_ops, q3, k3, v3)


# ------------------------------------------------------------------ dispatch
def _resolve_blocks(block_q, block_k):
    if block_q is None:
        block_q = vmem.get_override("decode.chunk_block_q",
                                    DEFAULT_BLOCK_Q, multiple=8)
    if block_k is None:
        block_k = vmem.get_override("decode.chunk_block_k",
                                    DEFAULT_BLOCK_K, multiple=128)
    return block_q, block_k


def prefill_attention(q, k, v, offsets, *, scale: Optional[float] = None,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      k_scale=None, v_scale=None,
                      interpret: bool = False):
    """Chunk-of-queries attention against a cached, offset prefix.

    ``q`` [batch, heads, C, head_dim] — C consecutive prompt tokens whose
    K/V are already written into the cache at ``[offsets[b],
    offsets[b] + C)``; ``k``/``v`` [batch, heads, max_len, head_dim] (the
    serving cache's per-layer view); ``offsets`` [batch] int32. Query
    row ``i`` attends cache positions ``[0, offsets[b] + i]`` — the
    shifted-causal mask of chunked prefill. ``scale`` defaults to
    ``1/sqrt(head_dim)``.

    Inference-only (no VJP — prefill never backprops). The Pallas path
    skips the compute of KV blocks past each q-block's last global
    position, so chunk ``n`` of a prompt costs O(offset + C) MXU work
    rather than O(max_len) (block DMA still covers the cache row);
    unaligned shapes and non-Mosaic dtypes fall back to the jnp
    reference.

    Tuned geometry: ``decode.chunk_block_q`` / ``decode.chunk_block_k``
    in the :mod:`apex_tpu.kernels.vmem` override registry (clamped to
    aligned divisors of the chunk / cache lengths).
    """
    b, h, C, d = q.shape
    L = k.shape[2]
    if k.shape != (b, h, L, d) or v.shape != k.shape:
        raise ValueError(f"prefill_attention: k/v {k.shape}/{v.shape} do "
                         f"not match q {q.shape} + max_len")
    if offsets.shape != (b,):
        raise ValueError(f"prefill_attention: offsets {offsets.shape} "
                         f"must be [{b}]")
    from apex_tpu.kernels.decode_attention import _check_head_scales
    _check_head_scales("prefill_attention", h, k_scale, v_scale)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    from apex_tpu.kernels.flash_attention import _fit_block, _has_vma
    bq, bk = _resolve_blocks(block_q, block_k)
    bq = _fit_block(bq, C, 8)
    bk = _fit_block(bk, L, 128)
    if jax.default_backend() == "cpu":
        interpret = True
    pallas_ok = (C % bq == 0 and L % bk == 0 and d % 8 == 0
                 and bq % 8 == 0 and bk % 128 == 0)
    if not pallas_ok or (interpret and _has_vma(q)) \
            or (not interpret and not mosaic_dtype_ok(q, k, v)):
        return prefill_attention_reference(q, k, v, offsets, scale=scale,
                                           k_scale=k_scale,
                                           v_scale=v_scale)
    q3 = q.reshape(b * h, C, d)
    k3 = k.reshape(b * h, L, d)
    v3 = v.reshape(b * h, L, d)
    off3 = jnp.repeat(jnp.asarray(offsets, jnp.int32), h)
    ks3 = vs3 = None
    if k_scale is not None:
        ks3 = jnp.tile(jnp.asarray(k_scale, jnp.float32), b)
        vs3 = jnp.tile(jnp.asarray(v_scale, jnp.float32), b)
    out = _prefill_pallas(q3, k3, v3, off3, scale, bq, bk, interpret,
                          ks3, vs3)
    return out.reshape(b, h, C, d).astype(q.dtype)


# ------------------------------------------------------------ paged variant
def paged_prefill_attention_reference(q, k_pool, v_pool, page_table,
                                      offsets, *, scale: float = 1.0,
                                      k_scale=None, v_scale=None):
    """fp32-math oracle: gather the page-table view, then the exact
    contiguous chunk-prefill reference. ``q`` [b, h, C, d]; pools
    [num_pages, h, page_len, d]; ``page_table`` [b, max_pages];
    ``offsets`` [b] int32. With ``k_scale``/``v_scale`` ([h] fp32) the
    gathered int8 pages are dequantized before the exact math — the
    quantized tier's gather-dequant oracle."""
    from apex_tpu.kernels.decode_attention import gather_pages

    k = gather_pages(k_pool, page_table)
    v = gather_pages(v_pool, page_table)
    return prefill_attention_reference(q, k, v, offsets, scale=scale,
                                       k_scale=k_scale, v_scale=v_scale)


def _paged_prefill_kernel(pt_ref, off_ref, *refs, scale, block_q,
                          page_len, quant):
    """Grid (b, h, nq, max_pages): one batch row x head, q-blocked
    chunk, one pool page per KV step. :func:`_prefill_kernel`'s (m, l)
    recurrence and global-position shifted-causal mask; the page the
    DMA fetched was chosen by the scalar-prefetch index map. ``quant``
    (static) adds two scalar-prefetch scale refs and the fused per-head
    dequant multiplies."""
    if quant:
        ks_ref, vs_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, \
            l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    hh = pl.program_id(1)
    qi = pl.program_id(2)
    ji = pl.program_id(3)
    nj = pl.num_programs(3)
    offset = off_ref[b]

    @pl.when(ji == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip pages entirely past this q-block's LAST global position
    @pl.when(ji * page_len <= offset + qi * block_q + block_q - 1)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)                  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)                  # [pl, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bq, pl]
        if quant:
            s = s * ks_ref[hh]
        rows = offset + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, page_len), 0)
        cols = ji * page_len + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, page_len), 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:, :1]                                # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                               # [bq, pl]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        if quant:
            pv = pv * vs_ref[hh]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ji == nj - 1)
    def _finish():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _paged_prefill_pallas(q, k_pool, v_pool, pt, offsets, scale, bq,
                          interpret, ks=None, vs=None):
    B, h, C, d = q.shape
    page_len = k_pool.shape[2]
    max_pages = pt.shape[1]
    quant = ks is not None
    kernel = functools.partial(_paged_prefill_kernel, scale=scale,
                               block_q=bq, page_len=page_len,
                               quant=quant)

    # the dequant scales ride as two extra scalar-prefetch operands;
    # the index maps' variadic tails absorb them (only the kernel body
    # reads them)
    def _q_idx(b, hh, i, j, pt, off, *_scales):
        return (b, hh, i, 0)

    def _kv_page(b, hh, i, j, pt, off, *_scales):
        # Bound the DMA extent by the chunk's offset: row b's queries
        # reach global position off[b] + C - 1 at most, so pages past
        # index (off[b] + C - 1) // page_len are never computed over
        # (the kernel's q-block × page skip). Clamping the page walk
        # there makes every later grid step re-issue the SAME block
        # index, which the Pallas pipeline does not re-fetch — the
        # kernel stops paying DMA for the max_pages tail just as it
        # already stopped paying MXU for it. Computed steps always have
        # j <= last, so the clamp never changes what the compute reads
        # (outputs stay bitwise identical to the oracle).
        last = (off[b] + (C - 1)) // page_len
        return (pt[b, jnp.minimum(j, last)], hh, 0, 0)

    n_prefetch, extra_ops = (4, (ks, vs)) if quant else (2, ())

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # page_table, offsets[, ks, vs]
        grid=(B, h, C // bq, max_pages),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), _q_idx),
            pl.BlockSpec((1, 1, page_len, d), _kv_page),
            pl.BlockSpec((1, 1, page_len, d), _kv_page),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), _q_idx),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),      # acc
            pltpu.VMEM((bq, 128), jnp.float32),    # m (col 0 live)
            pltpu.VMEM((bq, 128), jnp.float32),    # l (col 0 live)
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, h, C, d), q.dtype),
        interpret=interpret,
    )(pt, offsets, *extra_ops, q, k_pool, v_pool)


def _resolve_page_block_q(block_q):
    if block_q is None:
        block_q = vmem.get_override("decode.page_block_q",
                                    DEFAULT_BLOCK_Q, multiple=8)
    return block_q


def paged_prefill_attention(q, k_pool, v_pool, page_table, offsets, *,
                            scale: Optional[float] = None,
                            block_q: Optional[int] = None,
                            k_scale=None, v_scale=None,
                            interpret: bool = False):
    """Chunk-of-queries attention against a PAGED cached prefix.

    ``q`` [batch, heads, C, head_dim] — C consecutive prompt tokens
    whose K/V are already written into the pool at logical positions
    ``[offsets[b], offsets[b] + C)`` of row ``b``'s pages; ``k_pool``/
    ``v_pool`` [num_pages, heads, page_len, head_dim] (one layer of the
    serving pool); ``page_table`` [batch, max_pages] int32;
    ``offsets`` [batch] int32. Query row ``i`` attends logical cache
    positions ``[0, offsets[b] + i]`` — the shifted-causal mask of
    chunked prefill, unchanged by the paging. ``scale`` defaults to
    ``1/sqrt(head_dim)``.

    Inference-only. The Pallas path walks each row's page list via
    scalar-prefetch index maps and skips pages past each q-block's last
    global position — O(offset + C) MXU work per chunk, same as the
    contiguous kernel, over a pool that is dense and shared instead of
    slot-partitioned. The DMA extent is bounded the same way: the page
    index map clamps at each row's last reachable page
    (``(offsets[b] + C - 1) // page_len``), so grid steps past the
    prefix re-issue the same block index and the pipeline fetches
    nothing new — an early chunk of a long prompt pays O(offset + C)
    DMA, not O(max_pages) (the clamp only ever retargets steps whose
    compute is skipped, so outputs are bitwise unchanged). Unaligned
    shapes and non-Mosaic dtypes fall back to the gather-then-reference
    oracle.

    Tuned geometry: ``decode.page_block_q`` in the
    :mod:`apex_tpu.kernels.vmem` override registry (the KV block is one
    pool page by construction).
    """
    B, h, C, d = q.shape
    P, hp, page_len, dp = k_pool.shape
    if v_pool.shape != k_pool.shape or hp != h or dp != d:
        raise ValueError(f"paged_prefill_attention: pools "
                         f"{k_pool.shape}/{v_pool.shape} do not match q "
                         f"{q.shape}")
    if page_table.ndim != 2 or page_table.shape[0] != B:
        raise ValueError(f"paged_prefill_attention: page_table "
                         f"{page_table.shape} must be [{B}, max_pages]")
    if offsets.shape != (B,):
        raise ValueError(f"paged_prefill_attention: offsets "
                         f"{offsets.shape} must be [{B}]")
    from apex_tpu.kernels.decode_attention import _check_head_scales
    _check_head_scales("paged_prefill_attention", h, k_scale, v_scale)
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    from apex_tpu.kernels.flash_attention import _fit_block, _has_vma
    bq = _fit_block(_resolve_page_block_q(block_q), C, 8)
    if jax.default_backend() == "cpu":
        interpret = True
    pallas_ok = (C % bq == 0 and bq % 8 == 0 and d % 8 == 0
                 and page_len % 128 == 0)
    if not pallas_ok or (interpret and _has_vma(q)) \
            or (not interpret and not mosaic_dtype_ok(q, k_pool, v_pool)):
        return paged_prefill_attention_reference(
            q, k_pool, v_pool, page_table, offsets, scale=scale,
            k_scale=k_scale, v_scale=v_scale)
    pt = jnp.asarray(page_table, jnp.int32)
    off32 = jnp.asarray(offsets, jnp.int32)
    ks = vs = None
    if k_scale is not None:
        ks = jnp.asarray(k_scale, jnp.float32)
        vs = jnp.asarray(v_scale, jnp.float32)
    return _paged_prefill_pallas(q, k_pool, v_pool, pt, off32, scale, bq,
                                 interpret, ks, vs).astype(q.dtype)
