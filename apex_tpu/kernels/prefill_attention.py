"""Chunked-prefill attention — a block of queries against the KV cache.

:mod:`~apex_tpu.kernels.decode_attention` answers "ONE new token per
sequence against the cached prefix"; chunked prefill (Sarathi-style)
asks the in-between question: a CHUNK of ``C`` consecutive prompt tokens
per sequence, already written into the cache at positions
``[offset, offset + C)``, each attending causally over everything before
and including itself. The serving engine runs one such chunk per decode
heartbeat, so in-flight decodes never wait for a whole prompt — the
monolithic ``[1, prefill_len]`` prefill's head-of-line blocking becomes
at most one chunk of latency.

Geometry is the flash kernel's blockwise online softmax with the causal
diagonal shifted by a per-row *cache offset*: query row ``i`` of batch
row ``b`` sits at global position ``offsets[b] + i`` and attends cache
positions ``[0, offsets[b] + i]``. KV blocks entirely past the chunk's
last query position skip their compute (the decode kernel's length
skip, lifted to a q-block × k-block skip), so an early chunk of a long
prompt pays MXU work for the prefix it can see, not for ``max_len``
(the block pipeline still streams the full cache row through VMEM —
bounding the DMA extent too needs a trace-time cap on offsets, a
future lever).

Layouts (matching the serving cache, one slot per batch row):

- ``q``: ``[batch, heads, C, d]`` — the chunk's queries.
- ``k``/``v``: ``[batch, heads, max_len, d]`` — the cache view; the
  chunk's own K/V must already be written at ``[offset, offset + C)``
  (the serving tier's write-then-attend order).
- ``offsets``: ``[batch]`` int32 — valid cache positions before the
  chunk (= the slot's pre-chunk length).

Numerics follow the kernel tier's contract: fp32 accumulation regardless
of I/O dtype, and a pure-jnp reference that doubles as the CPU/unaligned
fallback and the test oracle. Pad rows of a final partial chunk compute
garbage that the engine never samples from.

Block geometry rides the shared tuned-override registry
(:mod:`apex_tpu.kernels.vmem`) under ``decode.chunk_block_q``
(sublane-multiple 8) and ``decode.chunk_block_k`` (lane-multiple 128).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import mosaic_dtype_ok, vmem

__all__ = ["prefill_attention", "prefill_attention_reference"]

_NEG_INF = -1e30
DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 256


# --------------------------------------------------------------- jnp reference
def prefill_attention_reference(q, k, v, offsets, *, scale: float = 1.0):
    """fp32-math oracle: per-row shifted-causal softmax over the cache.

    ``q`` [b, h, C, d]; ``k``/``v`` [b, h, L, d]; ``offsets`` [b] int32.
    Query row ``i`` attends cache positions ``j <= offsets[b] + i``.
    Returns [b, h, C, d] in ``q.dtype``.
    """
    out_dtype = q.dtype
    q32, k32, v32 = (jnp.asarray(t, jnp.float32) for t in (q, k, v))
    s = jnp.einsum("bhqd,bhld->bhql", q32, k32) * scale
    C, L = q.shape[2], k.shape[2]
    rows = (offsets[:, None, None, None]
            + jnp.arange(C, dtype=jnp.int32)[None, None, :, None])
    cols = jnp.arange(L, dtype=jnp.int32)[None, None, None, :]
    s = jnp.where(cols <= rows, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.asarray(jnp.einsum("bhql,bhld->bhqd", p, v32), out_dtype)


# -------------------------------------------------------------------- kernel
def _prefill_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                    l_ref, *, scale, block_q, block_k):
    """Grid (bh, nq, nk): one batch·head row, q-blocked chunk, blockwise
    over cached KV. The (m, l) recurrence is the flash forward kernel's;
    the causal skip/mask runs on GLOBAL query positions ``offset + row``
    instead of chunk-local ones, which is the whole difference between
    training attention and chunked prefill."""
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    offset = off_ref[b]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip KV blocks entirely past this q-block's LAST global position
    @pl.when(ki * block_k <= offset + qi * block_q + block_q - 1)
    def _body():
        q = q_ref[0].astype(jnp.float32)                     # [bq, d]
        k = k_ref[0].astype(jnp.float32)                     # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale      # [bq, bk]
        rows = offset + qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_ref[:, :1]                                # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                               # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        # every row attends at least its own position, so l > 0 always;
        # the guard only keeps a mis-called kernel finite
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def _prefill_pallas(q3, k3, v3, off3, scale, bq, bk, interpret):
    bh, C, d = q3.shape
    L = k3.shape[1]
    kernel = functools.partial(_prefill_kernel, scale=scale, block_q=bq,
                               block_k=bk)
    return pl.pallas_call(
        kernel,
        grid=(bh, C // bq, L // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # offsets
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # v
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, C, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),      # acc
            pltpu.VMEM((bq, 128), jnp.float32),    # m (col 0 live)
            pltpu.VMEM((bq, 128), jnp.float32),    # l (col 0 live)
        ],
        interpret=interpret,
    )(off3, q3, k3, v3)


# ------------------------------------------------------------------ dispatch
def _resolve_blocks(block_q, block_k):
    if block_q is None:
        block_q = vmem.get_override("decode.chunk_block_q",
                                    DEFAULT_BLOCK_Q, multiple=8)
    if block_k is None:
        block_k = vmem.get_override("decode.chunk_block_k",
                                    DEFAULT_BLOCK_K, multiple=128)
    return block_q, block_k


def prefill_attention(q, k, v, offsets, *, scale: Optional[float] = None,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      interpret: bool = False):
    """Chunk-of-queries attention against a cached, offset prefix.

    ``q`` [batch, heads, C, head_dim] — C consecutive prompt tokens whose
    K/V are already written into the cache at ``[offsets[b],
    offsets[b] + C)``; ``k``/``v`` [batch, heads, max_len, head_dim] (the
    serving cache's per-layer view); ``offsets`` [batch] int32. Query
    row ``i`` attends cache positions ``[0, offsets[b] + i]`` — the
    shifted-causal mask of chunked prefill. ``scale`` defaults to
    ``1/sqrt(head_dim)``.

    Inference-only (no VJP — prefill never backprops). The Pallas path
    skips the compute of KV blocks past each q-block's last global
    position, so chunk ``n`` of a prompt costs O(offset + C) MXU work
    rather than O(max_len) (block DMA still covers the cache row);
    unaligned shapes and non-Mosaic dtypes fall back to the jnp
    reference.

    Tuned geometry: ``decode.chunk_block_q`` / ``decode.chunk_block_k``
    in the :mod:`apex_tpu.kernels.vmem` override registry (clamped to
    aligned divisors of the chunk / cache lengths).
    """
    b, h, C, d = q.shape
    L = k.shape[2]
    if k.shape != (b, h, L, d) or v.shape != k.shape:
        raise ValueError(f"prefill_attention: k/v {k.shape}/{v.shape} do "
                         f"not match q {q.shape} + max_len")
    if offsets.shape != (b,):
        raise ValueError(f"prefill_attention: offsets {offsets.shape} "
                         f"must be [{b}]")
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    from apex_tpu.kernels.flash_attention import _fit_block, _has_vma
    bq, bk = _resolve_blocks(block_q, block_k)
    bq = _fit_block(bq, C, 8)
    bk = _fit_block(bk, L, 128)
    if jax.default_backend() == "cpu":
        interpret = True
    pallas_ok = (C % bq == 0 and L % bk == 0 and d % 8 == 0
                 and bq % 8 == 0 and bk % 128 == 0)
    if not pallas_ok or (interpret and _has_vma(q)) \
            or (not interpret and not mosaic_dtype_ok(q, k, v)):
        return prefill_attention_reference(q, k, v, offsets, scale=scale)
    q3 = q.reshape(b * h, C, d)
    k3 = k.reshape(b * h, L, d)
    v3 = v.reshape(b * h, L, d)
    off3 = jnp.repeat(jnp.asarray(offsets, jnp.int32), h)
    out = _prefill_pallas(q3, k3, v3, off3, scale, bq, bk, interpret)
    return out.reshape(b, h, C, d).astype(q.dtype)
