"""NHWC GroupNorm (+ fused SiLU) Pallas kernels.

TPU-native equivalent of the reference's ``group_norm_cuda`` extension
(apex/contrib/csrc/group_norm/group_norm_nhwc_fwd/bwd_*.cu — SURVEY N23:
NHWC GroupNorm with fused SiLU for diffusion UNets). Design:

- NHWC is the TPU-native layout: channels ride the LANE dimension, spatial
  rows the sublane/grid dimensions. Nothing is ever transposed.
- Stats are two-pass like the CUDA kernels (sum-pass → normalize-pass):
  a per-(sample, channel) (sum, sumsq) reduction kernel accumulates across
  spatial blocks (the LN kernel's grid-revisited-accumulator pattern), the
  tiny [N, C] → [N, G] group combine happens in plain jnp between passes,
  and the normalize kernel applies per-channel (mean, rstd, gamma, beta)
  with the SiLU epilogue fused — one VMEM round trip each pass.
- Backward mirrors it: one reduction kernel produces the per-(n, c) sums
  that yield BOTH the group terms (c1, c2) and, summed over n, dgamma /
  dbeta; a second kernel computes dx. SiLU's chain rule re-derives z from
  (x, mean, rstd, gamma, beta) — residuals are just (x, mean, rstd), the
  reference's memory shape.

Channels not a lane multiple (C % 128 != 0, e.g. diffusion's 320) and
non-TPU backends use the jnp fallback (XLA fuses it well; the Pallas win
is the guaranteed two-pass HBM traffic).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.kernels import vmem

__all__ = ["group_norm_nhwc", "group_norm_reference"]


def _silu(z):
    return z * jax.nn.sigmoid(z)


def _dsilu(z):
    s = jax.nn.sigmoid(z)
    return s * (1.0 + z * (1.0 - s))


def group_norm_reference(x, num_groups: int, weight=None, bias=None,
                         eps: float = 1e-5, act: Optional[str] = None):
    """fp32 composed oracle (and the fallback path). x: [N, H, W, C] or
    [N, S, C]."""
    if act not in (None, "", "identity", "silu"):
        raise ValueError(f"unsupported act {act!r}")
    act = act if act == "silu" else None
    shape = x.shape
    n, c = shape[0], shape[-1]
    x32 = jnp.asarray(x, jnp.float32).reshape(n, -1, num_groups,
                                              c // num_groups)
    mean = jnp.mean(x32, axis=(1, 3), keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=(1, 3), keepdims=True)
    y = ((x32 - mean) * jax.lax.rsqrt(var + eps)).reshape(n, -1, c)
    if weight is not None:
        y = y * jnp.asarray(weight, jnp.float32)
    if bias is not None:
        y = y + jnp.asarray(bias, jnp.float32)
    if act == "silu":
        y = _silu(y)
    return jnp.asarray(y, x.dtype).reshape(shape)


# ------------------------------------------------------------------ kernels
def _stats_kernel(x_ref, mean_ref, m2_ref, *, bs, s):
    """Per-(n, channel) running (mean, M2) via Chan's parallel combine —
    the numerically stable form (csrc/welford.cu — welford_parallel_CUDA);
    a sum/sumsq formulation cancels catastrophically for large-mean
    inputs. Padded tail rows are masked out of the block statistics."""
    j = pl.program_id(1)
    x = x_ref[0].astype(jnp.float32)                    # [bs, C]
    valid = jnp.minimum(bs, s - j * bs).astype(jnp.float32)
    mask = (jax.lax.broadcasted_iota(jnp.int32, x.shape, 0)
            < valid.astype(jnp.int32))
    xm = jnp.where(mask, x, 0.0)
    bmean = jnp.sum(xm, axis=0, keepdims=True) / valid
    xc = jnp.where(mask, x - bmean, 0.0)
    bm2 = jnp.sum(xc * xc, axis=0, keepdims=True)

    @pl.when(j == 0)
    def _():
        mean_ref[0] = jnp.zeros_like(mean_ref[0])
        m2_ref[0] = jnp.zeros_like(m2_ref[0])

    na = (j * bs).astype(jnp.float32)
    delta = bmean - mean_ref[0]
    total = na + valid
    mean_ref[0] += delta * (valid / total)
    m2_ref[0] += bm2 + delta * delta * (na * valid / total)


def _norm_kernel(x_ref, mean_ref, rstd_ref, g_ref, b_ref, y_ref, *, act):
    x = x_ref[0].astype(jnp.float32)                    # [bs, C]
    z = (x - mean_ref[0]) * rstd_ref[0]
    z = z * g_ref[0] + b_ref[0]
    if act == "silu":
        z = _silu(z)
    y_ref[0] = z.astype(y_ref.dtype)


def _bwd_sums_kernel(dy_ref, x_ref, mean_ref, rstd_ref, g_ref, b_ref,
                     sdz_ref, sdzx_ref, *, act):
    j = pl.program_id(1)
    dy = dy_ref[0].astype(jnp.float32)
    x = x_ref[0].astype(jnp.float32)
    xhat = (x - mean_ref[0]) * rstd_ref[0]
    if act == "silu":
        z = xhat * g_ref[0] + b_ref[0]
        dy = dy * _dsilu(z)
    # dz = d(loss)/d(pre-activation affine output)

    @pl.when(j == 0)
    def _():
        sdz_ref[0] = jnp.zeros_like(sdz_ref[0])
        sdzx_ref[0] = jnp.zeros_like(sdzx_ref[0])

    sdz_ref[0] += jnp.sum(dy, axis=0, keepdims=True)
    sdzx_ref[0] += jnp.sum(dy * xhat, axis=0, keepdims=True)


def _bwd_dx_kernel(dy_ref, x_ref, mean_ref, rstd_ref, g_ref, b_ref,
                   c1_ref, c2_ref, dx_ref, *, act):
    dy = dy_ref[0].astype(jnp.float32)
    x = x_ref[0].astype(jnp.float32)
    xhat = (x - mean_ref[0]) * rstd_ref[0]
    if act == "silu":
        z = xhat * g_ref[0] + b_ref[0]
        dy = dy * _dsilu(z)
    dxhat = dy * g_ref[0]
    # dx = rstd * (dxhat - mean_g(dxhat) - xhat * mean_g(dxhat·xhat));
    # the per-group means arrive broadcast per channel as c1, c2
    dx = rstd_ref[0] * (dxhat - c1_ref[0] - xhat * c2_ref[0])
    dx_ref[0] = dx.astype(dx_ref.dtype)


# ------------------------------------------------------------------ plumbing
def _block_spatial(srows, c, nbufs, key="group_norm.block_spatial"):
    # fwd and bwd carry separate tuned keys: on v5e the forward wants the
    # largest block that fits (fewer grid steps over the Welford state)
    # while the backward — five live buffers and two reduction outputs —
    # prefers a small one (swept readings in BASELINE.md round-5 tier)
    return vmem.block_rows(srows, row_bytes=4 * c, n_bufs=nbufs,
                           max_rows=256, key=key)


def _pad_s(x3, sp):
    n, s, c = x3.shape
    if s == sp:
        return x3
    return jnp.pad(x3, ((0, 0), (0, sp - s), (0, 0)))


def _row_specs(count, bs, c):
    """count spatial-blocked [1, bs, C] input specs."""
    return [pl.BlockSpec((1, bs, c), lambda i, j: (i, j, 0),
                         memory_space=pltpu.VMEM) for _ in range(count)]


def _vec_spec(c):
    """per-sample [1, 1, C] row-vector spec (constant over j)."""
    return pl.BlockSpec((1, 1, c), lambda i, j: (i, 0, 0),
                        memory_space=pltpu.VMEM)


def _group_stats(mean_c, m2_c, groups, s, eps):
    """Per-channel (mean, M2) with count s each → per-channel-broadcast
    group (mean, rstd) [N, 1, C], via Chan's combine across the group's
    channels (equal counts simplify it)."""
    n, c = mean_c.shape
    gc = c // groups
    mc = mean_c.reshape(n, groups, gc)
    mean_g = jnp.mean(mc, axis=-1)                           # [N, G]
    m2_g = jnp.sum(m2_c.reshape(n, groups, gc), axis=-1) \
        + s * jnp.sum(jnp.square(mc - mean_g[..., None]), axis=-1)
    var_g = m2_g / (s * gc)
    rstd_g = jax.lax.rsqrt(var_g + eps)
    rep = lambda a: jnp.repeat(a, gc, axis=-1).reshape(n, 1, c)
    return rep(mean_g), rep(rstd_g)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _group_norm(x3, gamma, beta, groups, eps, act, interpret):
    y, _ = _gn_fwd(x3, gamma, beta, groups, eps, act, interpret)
    return y


def _gn_fwd(x3, gamma, beta, groups, eps, act, interpret):
    n, s, c = x3.shape
    bs = _block_spatial(s, c, 3)
    sp = ((s + bs - 1) // bs) * bs
    xp = _pad_s(x3, sp)
    grid = (n, sp // bs)
    mean_ch, m2_ch = pl.pallas_call(
        functools.partial(_stats_kernel, bs=bs, s=s),
        grid=grid,
        in_specs=_row_specs(1, bs, c),
        out_specs=[_vec_spec(c), _vec_spec(c)],
        out_shape=[jax.ShapeDtypeStruct((n, 1, c), jnp.float32)] * 2,
        interpret=interpret,
    )(xp)
    mean_c, rstd_c = _group_stats(mean_ch[:, 0], m2_ch[:, 0], groups, s,
                                  eps)
    g2 = gamma.astype(jnp.float32).reshape(1, 1, c)
    b2 = beta.astype(jnp.float32).reshape(1, 1, c)
    y = pl.pallas_call(
        functools.partial(_norm_kernel, act=act),
        grid=grid,
        in_specs=_row_specs(1, bs, c) + [
            _vec_spec(c), _vec_spec(c),
            pl.BlockSpec((1, 1, c), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, c), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bs, c), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, sp, c), x3.dtype),
        interpret=interpret,
    )(xp, mean_c, rstd_c, g2, b2)
    return y[:, :s], (x3, gamma, beta, mean_c, rstd_c)


def _gn_bwd(groups, eps, act, interpret, res, dy):
    x3, gamma, beta, mean_c, rstd_c = res
    n, s, c = x3.shape
    bs = _block_spatial(s, c, 5, key="group_norm.bwd_block_spatial")
    sp = ((s + bs - 1) // bs) * bs
    xp, dyp = _pad_s(x3, sp), _pad_s(dy, sp)
    grid = (n, sp // bs)
    g2 = gamma.astype(jnp.float32).reshape(1, 1, c)
    b2 = beta.astype(jnp.float32).reshape(1, 1, c)
    const_vec = pl.BlockSpec((1, 1, c), lambda i, j: (0, 0, 0),
                             memory_space=pltpu.VMEM)
    sdz, sdzx = pl.pallas_call(
        functools.partial(_bwd_sums_kernel, act=act),
        grid=grid,
        in_specs=_row_specs(2, bs, c) + [_vec_spec(c), _vec_spec(c),
                                         const_vec, const_vec],
        out_specs=[_vec_spec(c), _vec_spec(c)],
        out_shape=[jax.ShapeDtypeStruct((n, 1, c), jnp.float32)] * 2,
        interpret=interpret,
    )(dyp, xp, mean_c, rstd_c, g2, b2)
    sdz2, sdzx2 = sdz[:, 0], sdzx[:, 0]                     # [N, C]
    dgamma = jnp.sum(sdzx2, axis=0).astype(gamma.dtype)
    dbeta = jnp.sum(sdz2, axis=0).astype(beta.dtype)

    # group means of dxhat and dxhat·xhat, broadcast per channel. The sums
    # above are of dz (pre-affine grads); dxhat = dz * gamma, so fold gamma
    # in before the group reduction.
    m = s * (c // groups)
    gc = c // groups
    g32 = gamma.astype(jnp.float32)[None]                    # [1, C]
    c1_g = jnp.sum((sdz2 * g32).reshape(n, groups, gc), axis=-1) / m
    c2_g = jnp.sum((sdzx2 * g32).reshape(n, groups, gc), axis=-1) / m
    rep = lambda a: jnp.repeat(a, gc, axis=-1).reshape(n, 1, c)
    c1_c, c2_c = rep(c1_g), rep(c2_g)

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, act=act),
        grid=grid,
        in_specs=_row_specs(2, bs, c) + [_vec_spec(c), _vec_spec(c),
                                         const_vec, const_vec,
                                         _vec_spec(c), _vec_spec(c)],
        out_specs=pl.BlockSpec((1, bs, c), lambda i, j: (i, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, sp, c), x3.dtype),
        interpret=interpret,
    )(dyp, xp, mean_c, rstd_c, g2, b2, c1_c, c2_c)
    return dx[:, :s], dgamma, dbeta


_group_norm.defvjp(_gn_fwd, _gn_bwd)


def _pallas_ok(c, dtype=None):
    from . import mosaic_dtype_ok, on_tpu

    return on_tpu() and c % 128 == 0 and mosaic_dtype_ok(dtype)


def group_norm_nhwc(x, num_groups: int, weight=None, bias=None,
                    eps: float = 1e-5, act: Optional[str] = None,
                    interpret: bool = False):
    """Fused NHWC GroupNorm(+SiLU). x: [N, H, W, C] (or [N, S, C]);
    stats per (sample, group) in fp32 (reference: group_norm_nhwc kernels).

    Affine weight/bias are required for the Pallas path's fused backward
    (the reference kernels are affine-only too); pass None to use the
    composed fallback.
    """
    if act not in (None, "", "identity", "silu"):
        raise ValueError(f"unsupported act {act!r}")
    c = x.shape[-1]
    if c % num_groups:
        raise ValueError(
            f"channels {c} not divisible by groups {num_groups}")
    act = act if act == "silu" else None
    usable = weight is not None and bias is not None and \
        (_pallas_ok(c, x.dtype) or interpret)
    if not usable:
        return group_norm_reference(x, num_groups, weight, bias, eps, act)
    shape = x.shape
    x3 = x.reshape(shape[0], -1, c)
    y = _group_norm(x3, weight, bias, num_groups, float(eps), act,
                    interpret)
    return y.reshape(shape)
