"""Process-level replica fleet: out-of-process workers behind a
stdlib transport, fronted by a :class:`FleetController`.

The :class:`~apex_tpu.serving.Router` scales replicas as THREADS in
one interpreter — N replicas share one GIL and one core pool, which
is why its CPU-regime notes carry every aggregate-throughput claim to
silicon. This module takes the same fleet out-of-process: each
replica is a separate OS process (``python -m
apex_tpu.serving.fleet_worker``) owning its own JAX runtime, engine,
scheduler and telemetry registry, and the controller talks to it over
a thin stdlib transport. Per-process runtimes stop sharing a GIL, so
replica *scaling* finally becomes an honest CPU-box measurement too —
and the same seam is where a multi-host pod fleet lands.

**Transport.** One AF_UNIX listening socket per controller (in a
private temp dir); each worker connects at startup and sends a hello.
Frames are length-prefixed pickles::

    +----------------+----------------------------------------+
    | 4 bytes, >I    | pickled payload (versioned wire dicts) |
    | payload length |                                        |
    +----------------+----------------------------------------+

Every payload that crosses is an EXPLICIT wire form — requests and
load snapshots via :func:`~apex_tpu.serving.request_to_wire` /
:func:`~apex_tpu.serving.snapshot_to_wire` (scheduler.py), disagg
arena records via :func:`~apex_tpu.serving.record_to_wire`
(host_tier.py) — each versioned and loud on a version mismatch, so a
controller and worker from different trees fail fast instead of
deserializing garbage. RPCs are strictly request-response per worker
connection with a monotonic ``id``; stale replies (a pong that lost
its race against a ping timeout) are discarded by id, never
misattributed.

**Routing** is the Router's decision code, verbatim: the controller
ranks candidates with :mod:`~apex_tpu.serving.routing_policy` (the
SAME functions the in-process Router calls) over serialized probe
results and load snapshots polled per routed request, spills across
the order, and raises fleet-level
:class:`~apex_tpu.serving.QueueFull` with the max-of-hints
``retry_after_s`` only when every live worker is saturated. That
sharing is what makes the bitwise pin possible: in-process Router vs
process fleet produce token-identical streams on a seeded greedy
session workload (``tests/L0/test_fleet.py``).

**Health.** Every controller step pings every live worker
(``ping_timeout_s`` per ping). A missed ping marks the worker
*suspect* — it stops receiving routed work and step RPCs — and
``max_missed_beats`` consecutive misses declare it dead: the process
is killed (it may be alive-but-hung — the ``worker_hang`` fault kind
injects exactly that), its un-finished requests re-route onto
survivors with no retry charged, and its load gauges zero. A
transport EOF (the process actually died) skips the grace period and
declares death immediately.

**Rolling restart** (:meth:`FleetController.rolling_restart`): one
worker at a time, drain → close → wait → respawn → rejoin. Drained
requests re-route onto the rest of the fleet with their paid-compute
counters absorbed and no retry charged; the respawned worker rejoins
cold and re-registers prefixes warm as re-routed multi-turn traffic
lands on it (post-restart hit rate > 0, pinned via
``PrefixCache.stats_since`` deltas over the ``prefix_stats`` RPC).

**Elastic scale**: :meth:`~FleetController.add_replica` /
:meth:`~FleetController.remove_replica` under live traffic (the new
member is probed per routed request like any other — cold caches lose
affinity ties and win least-loaded ties, so it fills), and
:meth:`~FleetController.set_role` re-roles a worker under traffic
shift (the PR 17 residue: a disaggregated fleet refits a prefill
worker to decode when the mix moves). Disagg handoffs cross the
process boundary BY VALUE: the prefill worker exports the finished
arena record (bytes + swap-out CRCs — :meth:`HostTier.export_record`),
the controller ships it, and the decode worker imports it into its own
arena, where the ordinary CRC-verified swap-in resumes at the
committed offset; corruption anywhere degrades to the verified miss,
never a wrong token.

Telemetry: the controller emits ``serving.fleet.routed`` /
``affinity_hits`` / ``spills`` / ``requeued`` / ``worker_deaths`` /
``hangs_detected`` / ``restarts`` counters, the
``serving.fleet.workers_alive`` gauge, and the
``serving.fleet.heartbeat_s`` / ``serving.fleet.restart_s``
histograms; per-worker load gauges reuse the Router's documented
``serving.router.replica<i>.*`` namespace (one dashboard serves both
fronts, and ``render_prometheus`` already collapses it into labeled
families). Each worker process keeps its own
:class:`~apex_tpu.telemetry.MetricsRegistry`;
:meth:`FleetController.metrics_snapshot` merges them into one fleet
view (counters summed fleet-wide — the Router's shared-registry
semantics — gauges and histograms namespaced per worker). Request
``uid``\\ s cross the boundary verbatim in every wire form, so the
controller's ``route`` spans and a worker's completion records refer
to the same trace identity.
"""

from __future__ import annotations

import collections
import os
import pickle
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.log_util import get_logger

from .prefix_cache import PrefixCache
from .routing_policy import (ROUTE_POLICIES, fleet_retry_hint,
                             note_placement, random_order,
                             rank_replicas)
from .scheduler import (QueueFull, Request, RequestStatus,
                        request_from_wire, request_to_wire,
                        snapshot_from_wire)

__all__ = ["FleetController", "WorkerDied", "WorkerHandle",
           "recv_frame", "send_frame"]

_logger = get_logger("serving")

# ------------------------------------------------------------------ framing

_FRAME_HEADER = struct.Struct(">I")

#: Frames above this are a protocol error, not a big message: the
#: largest legitimate payload (a disagg record's page bytes) is tens
#: of MB on any geometry this stack runs.
MAX_FRAME_BYTES = 1 << 30


def send_frame(sock: socket.socket, obj) -> None:
    """Write ``obj`` as one length-prefixed pickle frame (4-byte
    big-endian length + payload). Pickle rather than JSON because
    arena-record wire forms carry raw ``bytes``; every dict that
    crosses is still an explicit versioned wire form — the pickle is
    transport encoding, never the contract."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(payload)} bytes exceeds the "
                         f"{MAX_FRAME_BYTES}-byte transport bound")
    sock.sendall(_FRAME_HEADER.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError(
                f"peer closed mid-frame ({len(buf)}/{n} bytes)")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket):
    """Read one frame (blocking, honoring the socket's timeout).
    Raises :class:`EOFError` on a closed peer — the transport-level
    death signal — and ``ValueError`` on a length prefix past the
    transport bound (a desynced or corrupt stream, not a message)."""
    (n,) = _FRAME_HEADER.unpack(_recv_exact(sock, _FRAME_HEADER.size))
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {n} exceeds the "
                         f"{MAX_FRAME_BYTES}-byte transport bound "
                         "(desynced stream?)")
    return pickle.loads(_recv_exact(sock, n))


class WorkerDied(RuntimeError):
    """The transport to a worker broke mid-RPC (EOF / reset): the
    process is gone or unreachable. The controller converts this into
    a death event — never into a caller-visible request failure."""


class WorkerHandle:
    """One worker process: its :class:`subprocess.Popen`, its
    connected transport socket, and its health state. RPCs are
    strictly request-response with a per-handle monotonic id; replies
    with a stale id (e.g. a pong that lost its race against a ping
    timeout) are discarded, so one timed-out RPC never desyncs the
    stream for the next."""

    def __init__(self, index: int, proc: subprocess.Popen,
                 conn: socket.socket, role: str):
        self.index = int(index)
        self.proc = proc
        self.conn = conn
        self.role = role
        self.alive = True
        self.missed_beats = 0
        self.geometry: Optional[dict] = None
        self._seq = 0

    def rpc(self, op: str, *, timeout: Optional[float] = None,
            **payload) -> dict:
        """One request-response round trip. Raises
        :class:`WorkerDied` on a broken transport, ``TimeoutError``
        when no matching reply lands within ``timeout`` (the caller
        decides whether that is a missed beat or a death), and
        ``RuntimeError`` when the worker reports an application-level
        error."""
        self._seq += 1
        seq = self._seq
        try:
            self.conn.settimeout(timeout)
            send_frame(self.conn, {"op": op, "id": seq, **payload})
            while True:
                reply = recv_frame(self.conn)
                if reply.get("id") == seq:
                    break               # stale replies fall through
        except socket.timeout as e:
            raise TimeoutError(
                f"worker {self.index} {op} RPC timed out after "
                f"{timeout}s") from e
        except (EOFError, OSError) as e:
            raise WorkerDied(
                f"worker {self.index} transport broke during {op}: "
                f"{e}") from e
        if "error" in reply:
            raise RuntimeError(
                f"worker {self.index} {op} failed: {reply['error']}")
        return reply

    def send_oneway(self, op: str, **payload) -> None:
        """Fire-and-forget (no reply expected — the ``hang``
        injection, which by design never answers). Transport errors
        are swallowed: a one-way to a corpse is a no-op."""
        try:
            self.conn.settimeout(5.0)
            send_frame(self.conn, {"op": op, "id": None, **payload})
        except (EOFError, OSError):
            pass

    def destroy(self) -> None:
        """Kill the process (idempotent) and close the transport."""
        if self.proc.poll() is None:
            self.proc.kill()
        try:
            self.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:       # pragma: no cover
            pass
        try:
            self.conn.close()
        except OSError:                         # pragma: no cover
            pass
        self.alive = False


def _kill_procs(procs: List[subprocess.Popen]) -> None:
    """Finalizer backstop: no worker process may outlive a forgotten
    controller (the no-orphan contract even without close())."""
    for p in procs:
        if p.poll() is None:
            p.kill()
    for p in procs:
        try:
            p.wait(timeout=10)
        except Exception:                       # pragma: no cover
            pass


#: Scheduler keywords a fleet init may ship to workers — everything a
#: plain value can express. Callables and live objects (fault_policy,
#: auditor, tracer, on_requeue) cannot cross a process boundary and
#: are rejected loudly at construction. ``slo`` rides along because
#: SLOConfig is a frozen picklable dataclass; ``tenant_ledger`` is
#: deliberately ABSENT — a TenantLedger is process-local shared state
#: (and refuses to pickle), so each worker process builds its own from
#: the shipped config's tenant_weights (per-process fairness scope,
#: documented in docs/serving.md "Overload & SLO").
_WIRE_SCHED_KW = ("max_queue", "default_timeout_s", "eos_id",
                  "chunked", "chunk_budget", "retain_prefixes",
                  "speculative", "pipeline_depth", "slo")


class FleetController:
    """N out-of-process replica workers behind one prefix-aware
    least-loaded ``submit()`` — the :class:`~apex_tpu.serving.Router`
    surface, out-of-process (see module docstring).

    Parameters
    ----------
    specs:
        One engine-spec dict per worker (see
        :func:`~apex_tpu.serving.fleet_worker.build_engine_from_spec`
        for the schema) — usually N references to the same dict.
        Specs must be plain serializable values: each worker builds
        its OWN engine from its spec, which is also what makes the
        fleet's bitwise-parity pin meaningful (a test builds the
        in-process oracle engines from the same specs).
    registry:
        CONTROLLER-side :class:`~apex_tpu.telemetry.MetricsRegistry`
        (``serving.fleet.*`` + per-worker load gauges). Workers keep
        their own per-process registries;
        :meth:`metrics_snapshot` merges all of them into one view.
    route_policy / seed / roles / fault_plan / tracer:
        Exactly the Router's parameters. ``fault_plan`` is a
        CONTROLLER-tier plan: ``replica_death`` specs kill a real
        worker process (SIGKILL — no drain, the crash-consistency
        path), ``worker_hang`` specs make a worker stop answering its
        transport so the missed-beat detector must catch it.
    heartbeat ``ping_timeout_s`` / ``max_missed_beats``:
        One ping per live worker per step; a missed ping suspends
        routing to the worker, ``max_missed_beats`` consecutive
        misses (or any transport EOF, immediately) declare it dead.
    rpc_timeout_s:
        The working-RPC bound (init/submit/step/drain) — generous,
        because a worker's first step may be compiling.
    transport:
        ``None`` / ``("unix",)`` for the default AF_UNIX socket in a
        private temp dir; ``("tcp", host, port)`` for an AF_INET
        listener (``port=0`` picks a free port). Same frame codec,
        same RPC surface — the loopback TCP fleet is bitwise the
        AF_UNIX one.
    **scheduler_kw:
        Plain-value :class:`~apex_tpu.serving.Scheduler` keywords
        (:data:`_WIRE_SCHED_KW`), shipped to and applied by every
        worker.
    """

    def __init__(self, specs: Sequence[dict], *, registry=None,
                 route_policy: str = "affinity", seed: int = 0,
                 roles: Optional[Sequence[str]] = None,
                 fault_plan=None, tracer=None,
                 ping_timeout_s: float = 5.0,
                 max_missed_beats: int = 3,
                 rpc_timeout_s: float = 600.0,
                 spawn_timeout_s: float = 180.0,
                 python: Optional[str] = None,
                 transport: Optional[Sequence] = None,
                 **scheduler_kw):
        specs = [dict(s) for s in specs]
        if not specs:
            raise ValueError("FleetController needs at least one "
                             "worker spec")
        if route_policy not in ROUTE_POLICIES:
            raise ValueError(f"route_policy {route_policy!r} not in "
                             f"{ROUTE_POLICIES}")
        for k in scheduler_kw:
            if k not in _WIRE_SCHED_KW:
                raise ValueError(
                    f"scheduler keyword {k!r} cannot cross a process "
                    f"boundary (wire-able keywords: {_WIRE_SCHED_KW}; "
                    "role/on_requeue are fleet policy — pass "
                    "roles=[...])")
        self.roles: List[str] = [str(r) for r in roles] \
            if roles is not None else ["both"] * len(specs)
        if len(self.roles) != len(specs):
            raise ValueError(f"roles has {len(self.roles)} entries "
                             f"for {len(specs)} workers")
        self._validate_role_mix(self.roles)
        self.registry = registry
        self.route_policy = route_policy
        self.fault_plan = fault_plan
        self.tracer = tracer
        self._rng = np.random.default_rng(seed)
        self._sched_kw = dict(scheduler_kw)
        # routing reads only STATIC priority arithmetic from the
        # config (base_priority — no clock), so controller and workers
        # rank identically from the same shipped SLOConfig
        self._slo = self._sched_kw.get("slo")
        self._specs = specs
        self._python = python or sys.executable
        self.ping_timeout_s = float(ping_timeout_s)
        self.max_missed_beats = int(max_missed_beats)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.spawn_timeout_s = float(spawn_timeout_s)

        self.workers: List[WorkerHandle] = []
        self.placements: Dict[int, int] = {}    # observability log
        self._home: Dict[int, int] = {}         # uid -> live placement
        self._inflight: Dict[int, Request] = {}  # canonical requests
        self._t0: Dict[int, float] = {}         # controller submit clock
        self.completed: List[Request] = []
        self._overflow: collections.deque = collections.deque()
        self._handoff_overflow: collections.deque = collections.deque()
        self._tick = 0
        self._closed = False
        self.affinity_enabled = False
        self._hasher: Optional[PrefixCache] = None

        self._dir = tempfile.mkdtemp(prefix="apex-fleet-")
        # transport: None / ("unix",) binds the default AF_UNIX path;
        # ("tcp", host, port) binds an AF_INET listener (port 0 asks
        # the OS for a free one — the bound port is re-read from
        # getsockname, so tests never race for a fixed port). The
        # frame codec is address-family-agnostic; workers get the
        # address as a "tcp:host:port" --socket argument.
        if transport is None or tuple(transport) == ("unix",):
            self._sock_path = os.path.join(self._dir, "fleet.sock")
            self._listener = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
            self._listener.bind(self._sock_path)
            self._worker_addr = self._sock_path
        elif transport[0] == "tcp":
            kind, host, port = transport
            self._sock_path = None
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((str(host), int(port)))
            bound_port = self._listener.getsockname()[1]
            self._worker_addr = f"tcp:{host}:{bound_port}"
        else:
            raise ValueError(
                f"unknown transport spec {transport!r} — expected "
                "None, ('unix',) or ('tcp', host, port)")
        self._listener.listen(64)
        # every Popen ever spawned (respawns included): the finalizer
        # and close() reap them ALL — no worker outlives the fleet
        self._procs: List[subprocess.Popen] = []
        self._finalizer = weakref.finalize(self, _kill_procs,
                                           self._procs)
        try:
            procs = [self._launch(i) for i in range(len(specs))]
            conns = self._accept(len(specs))
            for i, proc in enumerate(procs):
                self.workers.append(WorkerHandle(
                    i, proc, conns[i], self.roles[i]))
            for i, w in enumerate(self.workers):
                self._init_worker(w, specs[i])
            self._finish_geometry()
        except BaseException:
            self.close()
            raise

    # ----------------------------------------------------------- spawning
    def _launch(self, index: int) -> subprocess.Popen:
        """Start worker ``index``'s process (it connects back to the
        fleet socket and says hello). The environment is inherited
        verbatim — ``JAX_PLATFORMS=cpu`` in the parent reaches every
        worker — plus a PYTHONPATH entry for this tree so ``python
        -m apex_tpu.serving.fleet_worker`` resolves regardless of
        cwd."""
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        prev = env.get("PYTHONPATH")
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + prev if prev else "")
        proc = subprocess.Popen(
            [self._python, "-m", "apex_tpu.serving.fleet_worker",
             "--socket", self._worker_addr, "--replica", str(index)],
            env=env)
        self._procs.append(proc)
        return proc

    def _accept(self, n: int) -> Dict[int, socket.socket]:
        """Accept ``n`` worker connections (workers identify
        themselves in their hello frame — accept order is
        connection-race order, never worker order)."""
        conns: Dict[int, socket.socket] = {}
        self._listener.settimeout(self.spawn_timeout_s)
        try:
            while len(conns) < n:
                conn, _ = self._listener.accept()
                conn.settimeout(self.spawn_timeout_s)
                if conn.family == socket.AF_INET:
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                hello = recv_frame(conn)
                if hello.get("op") != "hello":
                    conn.close()
                    raise RuntimeError(
                        f"expected a worker hello, got {hello!r}")
                conns[int(hello["replica"])] = conn
        except socket.timeout as e:
            raise RuntimeError(
                f"worker spawn timed out after {self.spawn_timeout_s}s "
                f"({len(conns)}/{n} connected) — check the worker "
                "process logs") from e
        return conns

    def _init_worker(self, w: WorkerHandle, spec: dict) -> None:
        reply = w.rpc("init", timeout=self.spawn_timeout_s, spec=spec,
                      scheduler=self._sched_kw, role=w.role,
                      replica=w.index)
        w.geometry = reply["geometry"]

    def _finish_geometry(self) -> None:
        """Post-init fleet validation — the Router's geometry and
        affinity rules, read from the workers' init replies."""
        geos = [w.geometry for w in self.workers]
        g0 = {k: geos[0][k] for k in ("slots", "max_len",
                                      "prefill_len", "chunk_len")}
        for i, g in enumerate(geos[1:], 1):
            gi = {k: g[k] for k in g0}
            if gi != g0:
                raise ValueError(
                    f"worker {i} serving geometry {gi} differs from "
                    f"worker 0's {g0} — the fleet routes any request "
                    "to any worker, so geometry must agree")
        self.affinity_enabled = (
            self.route_policy == "affinity"
            and all(g["retain_prefixes"] for g in geos))
        if self.affinity_enabled:
            blocks = {g["block_len"] for g in geos}
            if len(blocks) > 1:
                raise ValueError(
                    f"prefix block_len differs across workers "
                    f"({sorted(blocks)}): one set of rolling hashes "
                    "must probe every cache")
            # a host-only hasher: the controller computes each
            # prompt's rolling block keys ONCE and ships them in
            # probe and submit payloads (same hash function, no
            # engine, no recompute per worker)
            self._hasher = PrefixCache(block_len=blocks.pop())

    @staticmethod
    def _validate_role_mix(roles: Sequence[str]) -> None:
        if any(r != "both" for r in roles):
            if not any(r in ("prefill", "both") for r in roles):
                raise ValueError(
                    f"roles {list(roles)} has no prefill-capable "
                    "worker: nothing can ingest a prompt")
            if not any(r in ("decode", "both") for r in roles):
                raise ValueError(
                    f"roles {list(roles)} has no decode-capable "
                    "worker: nothing can emit a token")

    @property
    def _mixed(self) -> bool:
        return any(w.role != "both" for w in self.workers)

    # ------------------------------------------------------------- routing
    def _alive_indices(self) -> List[int]:
        idx = [i for i, w in enumerate(self.workers)
               if w.alive and w.missed_beats == 0]
        if not idx:
            raise RuntimeError(
                "no live workers — the fleet is an outage, not a "
                "routing event")
        return idx

    def _capable_indices(self, capability: Optional[str]) -> List[int]:
        idx = self._alive_indices()
        if capability is None or not self._mixed:
            return idx
        want = ("prefill", "both") if capability == "prefill" \
            else ("decode", "both")
        idx = [i for i in idx if self.workers[i].role in want]
        if not idx:
            raise RuntimeError(
                f"no live {capability}-capable worker — the fleet "
                "lost a whole role tier (outage, not a routing "
                "event)")
        return idx

    def _route_order(self, request: Request,
                     capability: Optional[str] = None):
        """``(keys, ordered_workers, match_lens)`` — the Router's
        `_route_order`, with probes and load snapshots arriving as
        wire forms over one ``probe`` RPC per candidate. A worker
        whose transport breaks mid-probe is declared dead and simply
        drops out of the candidate set."""
        alive = self._capable_indices(capability)
        if self.route_policy == "random":
            order = random_order(alive, self._rng)
            snaps = self._poll(alive)
            order = [i for i in order if i in snaps]
            if not order:
                raise RuntimeError("no live workers — the fleet is "
                                   "an outage, not a routing event")
            return None, order, {i: 0 for i in order}
        keys = None
        send_prompt = False
        if self.affinity_enabled:
            if len(request.prompt) < self._hasher.block_len:
                keys = []       # sub-block: can never match, skip probes
            else:
                prompt = tuple(request.prompt)
                keys = self._hasher.block_keys(
                    prompt, len(prompt) // self._hasher.block_len)
                send_prompt = True
        lens: Dict[int, int] = {i: 0 for i in alive}
        snaps: Dict[int, dict] = {}
        for i in alive:
            try:
                reply = self.workers[i].rpc(
                    "probe", timeout=self.rpc_timeout_s,
                    prompt=[int(t) for t in request.prompt]
                    if send_prompt else None,
                    keys=keys if send_prompt else None)
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            lens[i] = int(reply["match_len"])
            snaps[i] = snapshot_from_wire(reply["snapshot"])
        cand = [i for i in alive if i in snaps]
        if not cand:
            raise RuntimeError("no live workers — the fleet is an "
                               "outage, not a routing event")
        pri = self._slo.base_priority(request) \
            if self._slo is not None else 0
        # LoRA adapter affinity — the Router's rule verbatim, read
        # from the snapshot wire form's resident_adapters column
        hits = None
        if request.adapter is not None:
            hits = {i: int(request.adapter
                           in (snaps[i].get("resident_adapters") or ()))
                    for i in cand}
        return keys, rank_replicas(cand, lens, snaps, priority=pri,
                                   adapter_hits=hits), lens

    def _poll(self, indices: Sequence[int]) -> Dict[int, dict]:
        """Load snapshots (wire → plain dict) for ``indices``; dead
        transports drop out after being declared."""
        snaps: Dict[int, dict] = {}
        for i in list(indices):
            try:
                reply = self.workers[i].rpc(
                    "probe", timeout=self.rpc_timeout_s,
                    prompt=None, keys=None)
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            snaps[i] = snapshot_from_wire(reply["snapshot"])
        return snaps

    def lora_register(self, name: str, sites, *,
                      alpha: float = 1.0) -> None:
        """Broadcast adapter ``name`` into every LIVE worker's LoRA
        host store (by value — ``{site: (A, B)}`` numpy pairs cross
        the frame codec like disagg arena records). Any worker's
        rejection (no LoRA tier, bad geometry, store full of pinned
        records) propagates loudly: the fleet routes any adapter
        request to any worker, so registration must be all-or-error,
        never a partial fleet that serves some replicas and fails
        others."""
        for i in self._alive_indices():
            self.workers[i].rpc("lora_register",
                                timeout=self.rpc_timeout_s,
                                name=str(name), sites=sites,
                                alpha=float(alpha))

    def submit(self, request: Request) -> Request:
        """Route ``request`` to the best live worker — the Router's
        submit contract verbatim: spills across the ranked order,
        fleet-level :class:`QueueFull` with the max-of-hints
        ``retry_after_s`` when every live worker is saturated."""
        t_route = self.tracer.now() if self.tracer is not None else 0.0
        keys, order, lens = self._route_order(request, "prefill")
        hints: List[Optional[float]] = []
        n_spilled = 0
        for i in order:
            try:
                reply = self.workers[i].rpc(
                    "submit", timeout=self.rpc_timeout_s,
                    request=request_to_wire(request),
                    prefix_keys=keys, handoff=None,
                    is_handoff=False)
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            if "queue_full" in reply:
                hints.append(reply["retry_after_s"])
                n_spilled += 1
                continue
            note_placement(self.placements, request.uid, i)
            self._home[request.uid] = i
            self._inflight[request.uid] = request
            self._t0.setdefault(request.uid, time.perf_counter())
            if self.registry is not None:
                self.registry.counter_inc("serving.fleet.routed")
                if lens.get(i, 0) > 0:
                    self.registry.counter_inc(
                        "serving.fleet.affinity_hits")
                if n_spilled:
                    self.registry.counter_inc("serving.fleet.spills",
                                              n_spilled)
            if self.tracer is not None:
                self.tracer.event(request.uid, "route", t0=t_route,
                                  dur=self.tracer.now() - t_route,
                                  pid=i, replica=i,
                                  policy=self.route_policy,
                                  affinity_len=lens.get(i, 0),
                                  spills=n_spilled)
            return request
        hint = fleet_retry_hint(hints)
        if self.registry is not None:
            self.registry.counter_inc("serving.requests.rejected")
        suffix = f" (retry_after_s~{hint:.3f})" if hint else ""
        raise QueueFull(
            f"all {len(order)} live worker queues at capacity; retry "
            f"after a step() or shed load{suffix}", retry_after_s=hint)

    # ------------------------------------------------------------ stepping
    def step(self) -> bool:
        """One controller beat: consume scheduled chaos (process
        kills, hangs), run the heartbeat detector, re-route overflow,
        step every live worker and absorb its completions, then move
        disagg handoffs. Returns True if anything progressed."""
        tick = self._tick
        self._tick += 1
        if self.fault_plan is not None:
            for victim in self.fault_plan.take_replica_deaths(tick):
                self.kill_worker(victim, tick=tick)
            for victim in self.fault_plan.take_worker_hangs(tick):
                if 0 <= victim < len(self.workers) \
                        and self.workers[victim].alive:
                    _logger.warning(
                        "injecting worker_hang into worker %d at "
                        "tick %d", victim, tick)
                    self.workers[victim].send_oneway("hang")
        self._check_heartbeats()
        progress = self._drain_overflow()
        for i in list(self._alive_indices()):
            w = self.workers[i]
            if not w.alive:
                continue
            try:
                reply = w.rpc("step", timeout=self.rpc_timeout_s)
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            progress = bool(reply["progress"]) or progress
            for wire in reply["completed"]:
                self._absorb_completion(wire)
                progress = True
        if self._mixed:
            progress = self._collect_handoffs() or progress
        self._emit_gauges()
        return progress

    def _check_heartbeats(self) -> None:
        """Ping every live worker. EOF → dead now; a timeout →
        suspect (missed beat, no routing) until ``max_missed_beats``
        consecutive misses declare it dead — the ``worker_hang``
        detector (an alive-but-unresponsive process never EOFs)."""
        for i, w in enumerate(self.workers):
            if not w.alive:
                continue
            t0 = time.perf_counter()
            try:
                w.rpc("ping", timeout=self.ping_timeout_s)
            except WorkerDied as e:
                self._declare_dead(i, reason=str(e))
                continue
            except TimeoutError:
                w.missed_beats += 1
                _logger.warning(
                    "worker %d missed heartbeat %d/%d", i,
                    w.missed_beats, self.max_missed_beats)
                if w.missed_beats >= self.max_missed_beats:
                    if self.registry is not None:
                        self.registry.counter_inc(
                            "serving.fleet.hangs_detected")
                    self._declare_dead(
                        i, reason=f"{w.missed_beats} consecutive "
                        "missed heartbeats")
                continue
            w.missed_beats = 0
            if self.registry is not None:
                self.registry.observe("serving.fleet.heartbeat_s",
                                      time.perf_counter() - t0)

    def _declare_dead(self, index: int, *, reason: str = "") -> None:
        """A worker is gone (transport EOF, missed-beat breach, or a
        kill): reap the process, re-route its un-finished canonical
        requests onto the survivors with no retry charged, zero its
        gauges. Raises only when the fleet is now empty — that is an
        outage."""
        w = self.workers[index]
        if not w.alive:
            return
        w.destroy()
        victims = [uid for uid, home in self._home.items()
                   if home == index]
        drained: List[Request] = []
        for uid in victims:
            self._home.pop(uid, None)
            r = self._inflight.pop(uid, None)
            if r is not None:
                drained.append(r)
        if self.registry is not None:
            self.registry.counter_inc("serving.fleet.worker_deaths")
            if drained:
                self.registry.counter_inc("serving.fleet.requeued",
                                          len(drained))
            prefix = f"serving.router.replica{index}."
            for gauge in ("queue_depth", "slots_busy", "pages_free",
                          "host_bytes_free"):
                self.registry.gauge_set(prefix + gauge, 0.0)
        _logger.warning(
            "worker %d died at controller tick %d (%s): %d "
            "request(s) re-routing onto %d survivor(s)", index,
            self._tick, reason or "declared dead", len(drained),
            sum(w.alive for w in self.workers))
        self._overflow.extend(drained)
        if not any(w.alive for w in self.workers):
            raise RuntimeError(
                "the fleet's last worker died — outage, not a "
                "routing event")
        self._drain_overflow()

    def kill_worker(self, index: int, *,
                    tick: Optional[int] = None) -> None:
        """HARD-kill worker ``index``'s process (SIGKILL — no drain,
        no goodbye: the chaos ``replica_death`` path and the
        operator's dead-backend hammer). Un-finished requests
        re-route with no retry charged. Idempotent on a dead worker;
        killing the LAST live worker raises — an outage, and
        silently absorbing it would strand every re-routed
        request."""
        index = int(index)
        if not 0 <= index < len(self.workers):
            raise ValueError(f"worker {index} out of range "
                             f"[0, {len(self.workers)})")
        if not self.workers[index].alive:
            return
        if sum(w.alive for w in self.workers) == 1:
            raise RuntimeError(
                f"worker {index} is the last one alive — a fleet of "
                "zero cannot absorb its requests (outage, not a "
                "routing event)")
        _logger.warning("killing worker %d at tick %s", index,
                        self._tick if tick is None else tick)
        self._declare_dead(index, reason="killed")

    def _drain_overflow(self) -> bool:
        placed = False
        for _ in range(len(self._overflow)):
            r = self._overflow.popleft()
            try:
                self.submit(r)
                placed = True
            except QueueFull:
                self._overflow.append(r)
        return placed

    def _absorb_completion(self, wire: dict) -> None:
        """Fold a completion wire back onto the controller's
        canonical :class:`Request` (the object the caller submitted):
        outputs, terminal status and per-episode timings are the
        worker's; ``latency_s`` is re-stamped from the CONTROLLER's
        submit clock (perf_counter bases don't cross processes, and
        the controller's clock spans re-routes)."""
        done = request_from_wire(wire)
        r = self._inflight.pop(done.uid, None)
        self._home.pop(done.uid, None)
        if r is None:
            return      # stale (already re-routed after a drain race)
        for f in ("output_tokens", "status", "finish_reason",
                  "ttft_s", "queue_wait_s", "prefill_s", "chunks",
                  "reused_tokens", "spec_drafted", "spec_accepted",
                  "retries", "error"):
            setattr(r, f, getattr(done, f))
        t0 = self._t0.pop(done.uid, None)
        r.latency_s = (time.perf_counter() - t0) \
            if t0 is not None else done.latency_s
        self.completed.append(r)

    def _absorb_progress(self, r: Request, wire: dict) -> None:
        """Fold a DRAINED request's paid-compute counters onto the
        canonical object before it re-routes (chunks / prefill_s /
        reused tokens / spec counters accumulate across homes, like an
        in-process drain; retries stay untouched — a drain is never
        the request's fault)."""
        done = request_from_wire(wire)
        for f in ("prefill_s", "chunks", "reused_tokens",
                  "spec_drafted", "spec_accepted", "retries"):
            setattr(r, f, getattr(done, f))
        r.output_tokens = []
        r.status = RequestStatus.QUEUED

    # ------------------------------------------------------------ handoffs
    def _collect_handoffs(self) -> bool:
        """Move ready disagg handoffs: prefill workers export
        ``(request, record wire, keys)`` triples — the arena record's
        bytes and CRCs BY VALUE — and each lands on the best
        decode-capable worker, which imports the record into its own
        arena. An export that came back record-less (evicted or still
        pending at collection) stays a valid handoff: the decode side
        re-prefills cold, per the verified-miss contract."""
        ready: List[Tuple[Request, Optional[dict], list]] = \
            list(self._handoff_overflow)
        self._handoff_overflow.clear()
        for i in self._alive_indices():
            if self.workers[i].role != "prefill":
                continue
            try:
                reply = self.workers[i].rpc(
                    "take_handoffs", timeout=self.rpc_timeout_s)
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            for item in reply["handoffs"]:
                wire = item["request"]
                r = self._inflight.get(wire["uid"])
                if r is None:       # pragma: no cover — defensive
                    r = request_from_wire(wire)
                    self._inflight[r.uid] = r
                else:
                    self._absorb_progress(r, wire)
                self._home.pop(r.uid, None)
                ready.append((r, item["record"], item["keys"]))
        placed = False
        for r, rec, keys in ready:
            placed = self._dispatch_handoff(r, rec, keys) or placed
        return placed

    def _dispatch_handoff(self, r: Request, rec: Optional[dict],
                          keys) -> bool:
        t_route = self.tracer.now() if self.tracer is not None else 0.0
        _keys, order, lens = self._route_order(r, "decode")
        n_spilled = 0
        for i in order:
            try:
                reply = self.workers[i].rpc(
                    "submit", timeout=self.rpc_timeout_s,
                    request=request_to_wire(r), prefix_keys=keys,
                    handoff=rec, is_handoff=True)
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            if "queue_full" in reply:
                n_spilled += 1
                continue
            note_placement(self.placements, r.uid, i)
            self._home[r.uid] = i
            if self.registry is not None and n_spilled:
                self.registry.counter_inc("serving.fleet.spills",
                                          n_spilled)
            if self.tracer is not None:
                self.tracer.event(r.uid, "route", t0=t_route,
                                  dur=self.tracer.now() - t_route,
                                  pid=i, replica=i,
                                  policy=self.route_policy,
                                  affinity_len=lens.get(i, 0),
                                  spills=n_spilled, handoff=True)
            return True
        self._handoff_overflow.append((r, rec, keys))
        return False

    # ---------------------------------------------------------- lifecycle
    def _graceful_stop(self, index: int) -> None:
        """Drain worker ``index`` and stop its process cleanly:
        drained requests absorb their paid counters and join the
        overflow (re-routed, no retry charged). A worker that dies
        MID-drain degrades to the hard-death path — its requests
        re-route from the controller's canonical copies instead."""
        w = self.workers[index]
        try:
            reply = w.rpc("drain", timeout=self.rpc_timeout_s)
            for wire in reply["requests"]:
                r = self._inflight.get(wire["uid"])
                if r is None:       # pragma: no cover — defensive
                    r = request_from_wire(wire)
                    self._inflight[r.uid] = r
                else:
                    self._absorb_progress(r, wire)
                self._home.pop(r.uid, None)
                self._overflow.append(r)
            w.rpc("close", timeout=self.rpc_timeout_s)
        except (WorkerDied, TimeoutError, RuntimeError) as e:
            _logger.warning(
                "worker %d died during drain (%s) — falling back to "
                "hard-death re-route", index, e)
            self._declare_dead(index, reason=f"died during drain: {e}")
            return
        try:
            w.proc.wait(timeout=30)
        except subprocess.TimeoutExpired:       # pragma: no cover
            pass
        w.destroy()
        victims = [uid for uid, home in self._home.items()
                   if home == index]
        for uid in victims:         # pragma: no cover — drain got all
            self._home.pop(uid, None)
            r = self._inflight.pop(uid, None)
            if r is not None:
                self._overflow.append(r)
        if self.registry is not None:
            prefix = f"serving.router.replica{index}."
            for gauge in ("queue_depth", "slots_busy", "pages_free",
                          "host_bytes_free"):
                self.registry.gauge_set(prefix + gauge, 0.0)

    def _respawn(self, index: int) -> None:
        """Start a fresh process for slot ``index`` and rejoin it to
        the fleet (same spec, same role, geometry re-validated)."""
        proc = self._launch(index)
        conns = self._accept(1)
        if index not in conns:
            raise RuntimeError(
                f"respawned worker {index} connected with the wrong "
                f"identity {sorted(conns)}")
        w = WorkerHandle(index, proc, conns[index], self.roles[index])
        self.workers[index] = w
        self._init_worker(w, self._specs[index])
        self._check_new_geometry(w)

    def _check_new_geometry(self, w: WorkerHandle) -> None:
        ref = next((x.geometry for x in self.workers
                    if x is not w and x.geometry is not None), None)
        if ref is not None:
            keys = ("slots", "max_len", "prefill_len", "chunk_len")
            gi = {k: w.geometry[k] for k in keys}
            g0 = {k: ref[k] for k in keys}
            if gi != g0:
                raise ValueError(
                    f"worker {w.index} serving geometry {gi} differs "
                    f"from the fleet's {g0}")
        if self.affinity_enabled and not w.geometry["retain_prefixes"]:
            raise ValueError(
                f"worker {w.index} joined without prefix retention "
                "but the fleet routes by affinity")

    def rolling_restart(self) -> None:
        """Restart every live worker, one at a time: drain → close →
        wait → respawn → rejoin. The fleet keeps serving throughout
        (drained requests re-route, no retry charged); each respawned
        worker rejoins cold and re-registers prefixes warm as traffic
        lands on it. Per-worker restart latency lands in the
        ``serving.fleet.restart_s`` histogram."""
        for index in [i for i, w in enumerate(self.workers)
                      if w.alive]:
            if not self.workers[index].alive:
                continue            # died while restarting a sibling
            if sum(w.alive for w in self.workers) == 1:
                raise RuntimeError(
                    f"worker {index} is the last one alive — a "
                    "rolling restart needs survivors to drain onto")
            t0 = time.perf_counter()
            self._graceful_stop(index)
            self._respawn(index)
            if self.registry is not None:
                self.registry.counter_inc("serving.fleet.restarts")
                self.registry.observe("serving.fleet.restart_s",
                                      time.perf_counter() - t0)
            _logger.info("worker %d restarted in %.3fs", index,
                         time.perf_counter() - t0)
            self._drain_overflow()

    def respawn_worker(self, index: int) -> None:
        """Revive a DEAD slot (after a chaos kill, a hang
        declaration, or a crash): spawn a fresh process from the
        slot's spec and rejoin it — cold caches, same geometry, same
        role. Counts as a restart. Raises on a live slot (use
        :meth:`rolling_restart` to recycle those)."""
        index = int(index)
        if self.workers[index].alive:
            raise RuntimeError(
                f"worker {index} is alive — respawn_worker revives "
                "dead slots; rolling_restart recycles live ones")
        t0 = time.perf_counter()
        self._respawn(index)
        if self.registry is not None:
            self.registry.counter_inc("serving.fleet.restarts")
            self.registry.observe("serving.fleet.restart_s",
                                  time.perf_counter() - t0)
        _logger.info("worker %d respawned in %.3fs", index,
                     time.perf_counter() - t0)
        self._drain_overflow()

    def add_replica(self, spec: Optional[dict] = None,
                    role: str = "both") -> int:
        """Grow the fleet under live traffic: spawn a new worker
        (``spec`` defaults to worker 0's), join it, and return its
        index. The next routed request probes it like any other
        member — cold caches lose affinity ties and win least-loaded
        ties, so the new member fills naturally."""
        spec = dict(spec) if spec is not None else dict(self._specs[0])
        index = len(self.workers)
        self._validate_role_mix([w.role for w in self.workers
                                 if w.alive] + [str(role)])
        self._specs.append(spec)
        self.roles.append(str(role))
        proc = self._launch(index)
        conns = self._accept(1)
        if index not in conns:
            raise RuntimeError(
                f"new worker {index} connected with the wrong "
                f"identity {sorted(conns)}")
        w = WorkerHandle(index, proc, conns[index], str(role))
        self.workers.append(w)
        self._init_worker(w, spec)
        self._check_new_geometry(w)
        _logger.info("worker %d (%s) joined the fleet", index, role)
        return index

    def remove_replica(self, index: int) -> None:
        """Shrink the fleet under live traffic: drain worker
        ``index`` (its requests re-route, no retry charged) and stop
        its process. The slot stays dead — indices are stable.
        Removing the last live worker raises."""
        index = int(index)
        if not 0 <= index < len(self.workers):
            raise ValueError(f"worker {index} out of range "
                             f"[0, {len(self.workers)})")
        if not self.workers[index].alive:
            return
        if sum(w.alive for w in self.workers) == 1:
            raise RuntimeError(
                f"worker {index} is the last one alive — removing it "
                "is an outage, not elasticity")
        remaining = [w.role for i, w in enumerate(self.workers)
                     if w.alive and i != index]
        self._validate_role_mix(remaining)
        self._graceful_stop(index)
        self._drain_overflow()
        _logger.info("worker %d removed from the fleet", index)

    def set_role(self, index: int, role: str) -> None:
        """Re-role worker ``index`` under traffic shift (the
        disaggregated fleet's elastic refit: a prefill worker becomes
        a decode worker when the mix moves). The worker drains (its
        requests re-route), rebuilds its scheduler in the new role on
        the SAME engine — pool, prefix cache and arena survive — and
        rejoins. Raises if the resulting mix would lose a whole role
        tier."""
        index = int(index)
        role = str(role)
        w = self.workers[index]
        if not w.alive:
            raise RuntimeError(f"worker {index} is dead — respawn it "
                               "before re-roling")
        mix = [x.role for i, x in enumerate(self.workers)
               if x.alive and i != index] + [role]
        self._validate_role_mix(mix)
        reply = w.rpc("drain", timeout=self.rpc_timeout_s)
        for wire in reply["requests"]:
            r = self._inflight.get(wire["uid"])
            if r is not None:
                self._absorb_progress(r, wire)
                self._home.pop(r.uid, None)
                self._overflow.append(r)
        w.rpc("set_role", timeout=self.rpc_timeout_s, role=role)
        w.role = role
        self.roles[index] = role
        _logger.info("worker %d re-roled to %s", index, role)
        self._drain_overflow()

    # ------------------------------------------------------------ telemetry
    def _emit_gauges(self) -> None:
        if self.registry is None:
            return
        self.registry.gauge_set(
            "serving.fleet.workers_alive",
            float(sum(w.alive for w in self.workers)))
        for i, snap in self._poll(self._alive_indices()).items():
            prefix = f"serving.router.replica{i}."
            self.registry.gauge_set(prefix + "queue_depth",
                                    float(snap["queue_depth"]))
            self.registry.gauge_set(prefix + "slots_busy",
                                    float(snap["slots_busy"]))
            if snap["pages_free"] is not None:
                self.registry.gauge_set(prefix + "pages_free",
                                        float(snap["pages_free"]))
            if snap["host_bytes_free"] is not None:
                self.registry.gauge_set(
                    prefix + "host_bytes_free",
                    float(snap["host_bytes_free"]))

    def metrics_snapshot(self) -> dict:
        """One fleet view over N+1 registries: the controller's
        counters/gauges/histograms, every live worker's counters
        SUMMED in (fleet-wide aggregates — the Router's
        shared-registry semantics), and worker gauges/histogram
        summaries namespaced ``worker<i>/<name>`` (they are
        per-process readings; summing them would be a lie)."""
        if self.registry is not None:
            merged = self.registry.snapshot()
        else:
            merged = {"counters": {}, "gauges": {}, "histograms": {}}
        for i in range(len(self.workers)):
            w = self.workers[i]
            if not w.alive:
                continue
            try:
                snap = w.rpc("metrics",
                             timeout=self.rpc_timeout_s)["snapshot"]
            except (WorkerDied, TimeoutError) as e:
                self._declare_dead(i, reason=str(e))
                continue
            for k, v in snap["counters"].items():
                merged["counters"][k] = \
                    merged["counters"].get(k, 0.0) + v
            for k, v in snap["gauges"].items():
                merged["gauges"][f"worker{i}/{k}"] = v
            for k, v in snap["histograms"].items():
                merged["histograms"][f"worker{i}/{k}"] = v
        return merged

    def prefix_stats(self, index: int) -> dict:
        """Worker ``index``'s prefix-cache counters (the warm-restart
        pin reads deltas of these across a restart)."""
        return self.workers[index].rpc(
            "prefix_stats", timeout=self.rpc_timeout_s)["stats"]

    def audit_worker(self, index: int) -> dict:
        """Run the worker's own :class:`~apex_tpu.serving
        .PoolAuditor` + clearing reset and return the audit dict —
        the cross-process zero-leak pin (raises through the RPC if
        the worker's pool invariants fail)."""
        return self.workers[index].rpc(
            "audit_drained", timeout=self.rpc_timeout_s)["audit"]

    # ---------------------------------------------------------------- runs
    @property
    def pending(self) -> int:
        """Requests the fleet still owes the caller."""
        return len(self._overflow) + len(self._handoff_overflow) \
            + len(self._inflight)

    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100000) -> List[Request]:
        """Submit ``requests`` (stepping through :class:`QueueFull`
        backpressure) and step until every one is terminal — the
        Router's run loop over the process fleet. Returns the
        submitted list; results land on the SAME objects the caller
        passed (completions are folded back onto them)."""
        requests = list(requests)
        t0 = time.perf_counter()
        tok0 = sum(len(r.output_tokens) for r in self.completed)
        for r in requests:
            while True:
                try:
                    self.submit(r)
                    break
                except QueueFull:
                    if not self.step():
                        time.sleep(0.002)
        steps = 0
        while self.pending and steps < max_steps:
            if not self.step():
                time.sleep(0.002)
            steps += 1
        dt = time.perf_counter() - t0
        toks = sum(len(r.output_tokens)
                   for r in self.completed) - tok0
        if self.registry is not None and dt > 0:
            self.registry.gauge_set("serving.tokens_per_s", toks / dt)
        _logger.info(
            "fleet served %d request(s) over %d/%d live worker(s): "
            "%d tokens in %.3fs (%.1f tok/s)", len(requests),
            sum(w.alive for w in self.workers), len(self.workers),
            toks, dt, toks / dt if dt > 0 else float("inf"))
        return requests

    def close(self) -> None:
        """Stop every worker process and release the transport.
        Idempotent — safe mid-construction, safe after kills, safe
        twice. Live workers get one polite close RPC, then the
        process is reaped regardless; the temp socket dir is removed.
        The weakref finalizer backstops a forgotten controller: no
        worker process ever outlives the fleet object."""
        if self._closed:
            return
        self._closed = True
        for w in self.workers:
            if w.alive:
                try:
                    w.rpc("close", timeout=10.0)
                except (WorkerDied, TimeoutError, RuntimeError):
                    pass
            w.destroy()
        _kill_procs(self._procs)
        try:
            self._listener.close()
        except OSError:                         # pragma: no cover
            pass
        shutil.rmtree(self._dir, ignore_errors=True)
