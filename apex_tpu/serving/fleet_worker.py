"""One fleet worker process: ``python -m apex_tpu.serving.fleet_worker
--socket <path> --replica <i>``.

Spawned by :class:`~apex_tpu.serving.FleetController`, never by hand:
the worker connects back to the controller's AF_UNIX socket,
identifies itself with a hello frame, builds its OWN engine +
:class:`~apex_tpu.serving.Scheduler` from the spec the controller
ships in the ``init`` RPC, and then serves a strict request-response
loop until ``close`` (or its process is killed — the fleet's chaos
``replica_death`` is a real SIGKILL at this process).

Everything that crosses the transport is a versioned wire form (see
:mod:`~apex_tpu.serving.fleet`); the worker's replies carry the same
``id`` as the request, so a controller that timed out on one RPC can
discard the late reply by id instead of desyncing. A handler
exception is reported as an ``error`` reply — the controller decides
whether that is fatal — EXCEPT :class:`~apex_tpu.serving.QueueFull`
on ``submit``, which is a protocol-level outcome (``queue_full`` +
the measured ``retry_after_s`` hint), not an error: the controller's
spill loop consumes it.

:func:`build_engine_from_spec` is module-level and importable on
purpose: the fleet's bitwise-parity test builds its IN-PROCESS oracle
engines with the same function and the same spec dicts it hands the
controller, so the only difference between the two fronts is the
process boundary. Engine construction is deterministic — the model's
parameters come from ``init_seed`` via ``jax.random.PRNGKey``, so two
processes building from one spec hold bitwise-identical weights on
the same backend.
"""

from __future__ import annotations

import argparse
import socket
import time
from typing import List, Optional

__all__ = ["build_engine_from_spec", "build_scheduler_from_spec",
           "main"]


def build_engine_from_spec(spec: dict):
    """Deterministically build an :class:`~apex_tpu.serving.Engine`
    from a plain-dict ``spec`` (the only engine description that can
    cross a process boundary)::

        {"model": {"vocab_size": 64, "hidden": 32, ...}     # TransformerLM
                  | {"preset": "small", "vocab_size": ...}, # create_lm
         "init_seed": 0,                # PRNGKey for m.init → params
         "engine": {"slots": 2, "max_len": 64, "prefill_len": 24,
                    "chunk_len": 8, "prefix_pool": 4, "seed": 5,
                    "policy": "O0",     # resolved by name per process
                    # optional: paged, page_len, num_pages, top_k,
                    # "lora": {"rank": 4, ...} → per-worker LoRAConfig,
                    "host_tier_bytes": 1 << 20}}  # → per-worker HostTier

    Imports live inside the function: the controller imports this
    module's codec-free helpers without paying for jax, and the test
    suite calls it directly to build bitwise-identical oracle
    engines.
    """
    import jax
    import jax.numpy as jnp

    from apex_tpu.amp.policy import resolve_policy
    from apex_tpu.models.transformer_lm import TransformerLM, create_lm
    from apex_tpu.serving import Engine

    model_kw = dict(spec.get("model", {}))
    if "preset" in model_kw:
        size = model_kw.pop("preset")
        m = create_lm(size=size, **model_kw)
    else:
        m = TransformerLM(**model_kw)
    params = m.init(
        jax.random.PRNGKey(int(spec.get("init_seed", 0))),
        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    eng_kw = dict(spec.get("engine", {}))
    policy = resolve_policy(eng_kw.pop("policy", "O0"), verbose=False)
    tier_bytes = eng_kw.pop("host_tier_bytes", None)
    if tier_bytes is not None:
        eng_kw["host_tier"] = int(tier_bytes)
    lora_kw = eng_kw.pop("lora", None)
    if lora_kw is not None:
        # the LoRA tier crosses the process boundary as a plain dict
        # of LoRAConfig fields — resolved per process, like policy
        from apex_tpu.serving.lora import LoRAConfig
        eng_kw["lora"] = LoRAConfig(**dict(lora_kw))
    return Engine(m, params, policy=policy, **eng_kw)


def build_scheduler_from_spec(engine, scheduler_kw: dict, *,
                              role: str = "both", registry=None):
    """The worker's :class:`~apex_tpu.serving.Scheduler` from the
    controller-shipped plain-value keywords (callable seams —
    fault_policy, on_requeue — cannot cross and stay None)."""
    from apex_tpu.serving import Scheduler

    return Scheduler(engine, role=role, registry=registry,
                     **dict(scheduler_kw))


class _WorkerState:
    """Everything one worker process owns: its engine, scheduler,
    per-process telemetry registry, and the completion cursor (the
    index into ``scheduler.completed`` up to which the controller has
    already absorbed results)."""

    def __init__(self, replica: int):
        self.replica = int(replica)
        self.engine = None
        self.sched = None
        self.registry = None
        self.sched_kw: dict = {}
        self.completed_seen = 0


def _geometry(state: _WorkerState) -> dict:
    eng = state.engine
    pc = getattr(eng, "prefix_cache", None)
    return {
        "slots": eng.slots,
        "max_len": eng.max_len,
        "prefill_len": eng.prefill_len,
        "chunk_len": eng.chunk_len,
        "paged": bool(getattr(eng, "paged", False)),
        "retain_prefixes": bool(state.sched.retain_prefixes),
        "block_len": pc.block_len if pc is not None else None,
        "role": state.sched.role,
    }


def _handle(state: _WorkerState, msg: dict) -> Optional[dict]:
    """Dispatch one RPC. Returns the reply payload (without the id),
    or None for one-way ops that must not answer. Raising propagates
    to the serve loop, which reports it as an ``error`` reply."""
    from apex_tpu.serving import (PoolAuditor, QueueFull,
                                  request_from_wire, request_to_wire,
                                  snapshot_to_wire)
    from apex_tpu.telemetry import MetricsRegistry

    op = msg["op"]

    if op == "ping":
        return {"pong": True}

    if op == "hang":
        # the chaos worker_hang: stop answering the transport while
        # the process stays alive — exactly what the controller's
        # missed-beat detector (and nothing else) must catch. The
        # sleep outlives any test; the controller SIGKILLs the
        # process once it declares the hang.
        time.sleep(float(msg.get("hang_s", 3600.0)))
        return None                         # pragma: no cover

    if op == "init":
        state.registry = MetricsRegistry()
        state.engine = build_engine_from_spec(msg["spec"])
        state.sched_kw = dict(msg.get("scheduler") or {})
        state.sched = build_scheduler_from_spec(
            state.engine, state.sched_kw,
            role=msg.get("role", "both"), registry=state.registry)
        state.sched.replica_index = int(msg.get("replica",
                                                state.replica))
        state.completed_seen = 0
        return {"ok": True, "geometry": _geometry(state)}

    if op == "probe":
        match_len = 0
        prompt = msg.get("prompt")
        pc = getattr(state.engine, "prefix_cache", None)
        if prompt is not None and pc is not None:
            match_len = pc.probe(prompt, keys=msg.get("keys"))
        return {"match_len": int(match_len),
                "snapshot":
                    snapshot_to_wire(state.sched.load_snapshot())}

    if op == "submit":
        r = request_from_wire(msg["request"])
        is_handoff = bool(msg.get("is_handoff"))
        try:
            state.sched.submit(r, prefix_keys=msg.get("prefix_keys"),
                               count_rejection=False,
                               _handoff=is_handoff)
        except QueueFull as e:
            return {"queue_full": True,
                    "retry_after_s": e.retry_after_s}
        if is_handoff and msg.get("handoff") is not None:
            _import_handoff(state, r, msg["handoff"],
                            msg.get("prefix_keys"))
        return {"ok": True}

    if op == "step":
        progress = state.sched.step()
        done = state.sched.completed[state.completed_seen:]
        state.completed_seen = len(state.sched.completed)
        return {"progress": bool(progress),
                "completed": [request_to_wire(r) for r in done]}

    if op == "drain":
        drained = state.sched.drain_requests()
        return {"requests": [request_to_wire(r) for r in drained]}

    if op == "take_handoffs":
        return {"handoffs": _export_handoffs(state)}

    if op == "lora_register":
        # adapter payloads cross as plain {site: (A, B)} numpy pairs —
        # the same by-value discipline as disagg arena records; the
        # engine CRCs them at rest like any local registration
        state.engine.lora_register(msg["name"], msg["sites"],
                                   alpha=float(msg.get("alpha", 1.0)))
        return {"ok": True}

    if op == "prefix_stats":
        pc = getattr(state.engine, "prefix_cache", None)
        return {"stats": pc.stats() if pc is not None else {}}

    if op == "metrics":
        return {"snapshot": state.registry.snapshot()}

    if op == "audit_drained":
        # the cross-process zero-leak pin: the pool's invariants hold
        # (audit raises PoolInvariantError otherwise) and a clearing
        # reset leaves nothing but the sentinel allocated
        aud = PoolAuditor()
        aud.audit(state.engine)
        state.engine.reset(clear_prefixes=True)
        after = aud.audit(state.engine)
        if after["pages_in_use"] != 0:
            raise RuntimeError(
                f"{after['pages_in_use']} page(s) still allocated "
                "after a clearing reset — the drain leaked")
        return {"audit": after}

    if op == "set_role":
        # elastic re-role on the SAME engine: pool, prefix cache and
        # arena survive; only the scheduler (whose role gates
        # admission) is rebuilt. The controller drained us first.
        state.sched.close()
        state.sched = build_scheduler_from_spec(
            state.engine, state.sched_kw, role=msg["role"],
            registry=state.registry)
        state.sched.replica_index = state.replica
        state.completed_seen = 0
        return {"ok": True, "geometry": _geometry(state)}

    if op == "close":
        if state.sched is not None:
            state.sched.close()
        return {"ok": True, "bye": True}

    raise ValueError(f"unknown op {op!r}")


def _import_handoff(state: _WorkerState, r, record_wire: dict,
                    keys) -> None:
    """Decode-side handoff adoption: import the shipped arena record
    into THIS worker's host tier under its original key (a request
    uid — positive, so it can never collide with the cache's negative
    synthetic keys), register it as a born-swapped prefix, and note
    the pairing so admission resolves it (CRC-verified swap-in on the
    happy path, the counted verified-miss re-prefill otherwise). A
    declined import (arena too small) degrades to the cold handoff —
    the request re-prefills, never faults."""
    eng = state.engine
    tier = getattr(eng, "host_tier", None)
    if tier is None:                        # pragma: no cover
        return
    key = tier.import_record(record_wire)
    if key is None:
        return                              # declined: cold handoff
    cap = ((len(r.prompt) - 1) // eng.chunk_len) * eng.chunk_len
    outcome = eng.prefix_cache.register_handoff(
        key, r.prompt[:cap], n_pages=cap // eng.page_len, keys=keys)
    if outcome == "registered":
        state.sched.note_handoff(r.uid, key)
    else:                                   # pragma: no cover
        tier.discard(key)


def _export_handoffs(state: _WorkerState) -> List[dict]:
    """Prefill-side handoff export: pop every READY hand-over from
    the scheduler, drop the exporter's cache entry (the swapped
    entry's arena bytes stay), and POP the arena record itself into a
    wire form — bytes and swap-out CRCs by value. A record the arena
    evicted (or that never finished its swap-out) exports as None:
    the key-less cold handoff, per the verified-miss contract."""
    from apex_tpu.serving import request_to_wire

    eng = state.engine
    tier = getattr(eng, "host_tier", None)
    out = []
    for r, key, keys in state.sched.take_handoffs():
        record_wire = None
        if key is not None:
            eng.prefix_cache.drop(key)
            if tier is not None:
                record_wire = tier.export_record(key)
        out.append({"request": request_to_wire(r),
                    "record": record_wire, "keys": keys})
    return out


def main(argv: Optional[List[str]] = None) -> int:
    from .fleet import recv_frame, send_frame

    ap = argparse.ArgumentParser(
        description="apex_tpu fleet worker (spawned by "
                    "FleetController — not a user entry point)")
    ap.add_argument("--socket", required=True,
                    help="controller's transport address: an AF_UNIX "
                         "socket path, or tcp:host:port")
    ap.add_argument("--replica", required=True, type=int,
                    help="this worker's fleet index")
    args = ap.parse_args(argv)

    state = _WorkerState(args.replica)
    if args.socket.startswith("tcp:"):
        _, host, port = args.socket.split(":", 2)
        conn = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        conn.connect((host, int(port)))
        # the RPC frames are small and strictly request-response:
        # never let Nagle hold a reply back
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    else:
        conn = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        conn.connect(args.socket)
    import os
    send_frame(conn, {"op": "hello", "replica": state.replica,
                      "pid": os.getpid()})
    try:
        while True:
            try:
                msg = recv_frame(conn)
            except (EOFError, OSError):
                break           # controller went away: exit quietly
            try:
                reply = _handle(state, msg)
            except BaseException as e:      # noqa: BLE001 — reported
                reply = {"error": f"{type(e).__name__}: {e}"}
            if reply is None:
                continue                    # one-way op
            reply["id"] = msg.get("id")
            try:
                send_frame(conn, reply)
            except (EOFError, OSError):
                break
            if msg.get("op") == "close" and "error" not in reply:
                break
    finally:
        conn.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
