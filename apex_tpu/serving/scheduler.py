"""Continuous-batching scheduler: requests in, token streams out.

The naive way to serve N requests is static batching — pad them to one
shape, decode until the LAST one finishes, waste every slot that
finished early. Continuous batching instead treats the engine's decode
step as a steady heartbeat and moves requests through slots between
beats:

1. **expire** — queued or running requests past their deadline finish
   with status ``"timeout"`` (their slot frees immediately);
2. **admit** — while a slot is free and the queue is non-empty, pop the
   oldest request into the slot as *prefilling* (its queue wait ends
   here — the first half of the TTFT decomposition). On a PAGED engine
   admission is gated on the page pool first: the head request's
   worst-case page demand (padded prefill extent or prompt + token
   budget, whichever is larger) must be reservable —
   :meth:`Engine.try_reserve_slot` evicts LRU prefix entries under
   pressure, and when even that cannot cover the demand the request
   simply stays queued (FIFO holds; backpressure surfaces as
   :class:`QueueFull` at submit once the queue itself fills). The
   reservation is what makes mid-decode allocation infallible. With
   ``retain_prefixes=True`` admission then consults the engine's
   :class:`~apex_tpu.serving.PrefixCache`: the longest cached
   block-aligned prefix of the prompt is attached to the slot — on the
   paged path by refcount-bumping the donor's pages into the slot's
   page table (copy-on-write: ZERO data movement, and the matched
   pages are refunded from the reservation), on the contiguous path by
   one compiled KV row-copy with the donor entry refcount-pinned for
   the slot's lifetime — and chunk prefill resumes at the matched
   offset — every matched chunk is attention+MLP compute that never
   runs;
3. **chunk prefill** — at most ``chunk_budget`` (default 1) compiled
   chunk-prefill steps across the prefilling slots, round-robin. A
   prompt of P tokens ingests over ``ceil(P / chunk_len)`` heartbeats;
   the final chunk samples the request's first token (the TTFT mark)
   and flips the slot to decoding. The budget bounds the stall imposed
   on IN-FLIGHT decodes — while nothing is decoding there is nothing
   to stall, so a cold queue bursts chunk-after-chunk (stopping the
   moment a slot flips to decoding) instead of idling between beats;
4. **draft** (``speculative=True``) — for each greedy decoding slot, a
   host-side prompt-lookup drafter (:mod:`~apex_tpu.serving
   .speculative`) proposes up to ``K`` next tokens from n-gram matches
   over ``prompt + generated``;
5. **verify-or-decode** — every slot with a non-empty draft shares ONE
   compiled ``[slots, K+1]`` batched verify call
   (:meth:`Engine.verify_batch`: accept-longest-prefix in-program per
   row, up to ``K + 1`` tokens emitted per slot-step, greedy output
   bitwise identical to plain decode; B verify-eligible slots cost one
   program invocation, not B); everything else — empty drafts, sampled
   requests, requests within ``K`` tokens of their budget — falls back
   to the ordinary fixed-shape decode step over the remaining slots.
   ``speculative=False`` (the default) skips the draft phase entirely
   and keeps today's path as the measurable baseline.

**Async pipelined heartbeat** (``pipeline_depth >= 1``): the sync beat
forces every sampled token to the host (``np.asarray``) before the
next step is dispatched, so the device idles through all the host
think-time in between — drafting, admission, hashing, telemetry.
Dispatch-ahead execution inverts that: decode step t+1 is DISPATCHED
against the speculated schedule (every in-flight slot presumed to
continue — EOS is the only finality the host cannot know in advance;
token-budget and ``max_len`` exhaustion are pure host arithmetic and
are never speculated past) with step t's un-forced device tokens as
its ``last_tokens``, and step t is only then RECONCILED: one batched
readback, per-slot emission through the same finish checks as the
sync path, and rollback of any mispredict — a slot that turned out to
finish (or quarantine, or expire) mid-pipeline simply discards its
speculated successors' tokens (matched by request uid, counted as
``serving.heartbeat.discarded``). Device state needs no undo: the
speculated step's K/V write lands past every reader exactly like
PR 8's rejected verify tail — lengths gate attention, dispatch order
is program order (the cache threads through every call), and the next
occupant's chunk prefill overwrites whole pages before attending them
(write-then-attend). Host bookkeeping rollback is pure length
arithmetic, already performed by ``release_slot``. ``pipeline_depth=0``
(the default) keeps today's fully synchronous beat as the bitwise
oracle; depth ``d`` keeps at most ``d`` decode steps in flight.
A :class:`~apex_tpu.serving.DraftWorker` thread overlaps n-gram
drafting and prefix block-hashing with device execution (pure
closures over snapshots — timing can reorder host work, never change
tokens), and the greedy output stream is BITWISE identical to the
sync path across chunked, speculative, prefix-hit and chaos streams
(pinned by ``tests/L0/test_async_heartbeat.py``).

Step 3 is the head-of-line fix (Orca-style continuous batching +
Sarathi-style chunked prefill): the monolithic alternative — pause the
heartbeat and run a whole ``[1, prefill_len]`` prefill at admit time —
stalls every in-flight decode for the full prompt length. Chunking
bounds that stall at one chunk, and short prompts stop paying full
``prefill_len`` padding compute. The monolithic path is kept behind
``chunked=False`` as the measurable baseline
(``bench_serving.py --mixed-prompts`` prints the two side by side).

Backpressure instead of OOM: the queue is bounded (``max_queue``);
:meth:`submit` raises :class:`QueueFull` when it is at capacity, so a
caller that outruns the engine gets a typed rejection to retry/shed —
never an unbounded host-side pileup. The rejection carries a
``retry_after_s`` hint derived from the measured decode throughput
(an EMA of decode-step wall time × the steps until the nearest running
request can finish), so a well-behaved client backs off by data, not
by guess. (:meth:`run` absorbs the same signal by stepping the engine
until space frees.)

**Fault isolation** (always on; knobs in :class:`~apex_tpu.serving
.FaultPolicy`): every engine call in the heartbeat is containment-
wrapped. A transient exception from a chunk-prefill or decode call —
real, or injected by a :class:`~apex_tpu.serving.FaultPlan` — costs
only its victim request: the slot is freed, its pages and prefix pins
released, and the request requeues with capped exponential backoff up
to ``max_retries`` before the typed ``FAILED`` terminal status. The
engine's in-program non-finite guard quarantines a NaN/Inf slot the
same way while its batchmates keep their exact tokens. A per-heartbeat
wall-clock watchdog (``watchdog_budget_s``) turns stalls into
``serving.watchdog.stall`` events plus an ``on_stall`` callback, and a
:class:`~apex_tpu.serving.PoolAuditor` (sampled via
``audit_every_n``) reconciles page refcounts after finish/eviction
events — leaks and double-frees raise loudly instead of rotting. The
headline guarantee, pinned by ``tests/L0/test_faults.py``: under an
injected fault schedule, un-faulted greedy requests complete bitwise
token-identical to a fault-free run, faulted requests reach a typed
terminal status, and the pool drains with zero leaked pages.

Terminal request states are one typed enum (:class:`RequestStatus`):
``FINISHED`` (served to completion), ``EXPIRED`` (deadline), and
``FAILED`` (fault policy exhausted) — used consistently across the
scheduler, the request records, and telemetry.

Prefix registration is the write half: when a retained-prefix run's
prompt finishes chunk prefill, its block-aligned K/V is copied into a
pool row (capacity-bounded; LRU eviction only at refcount 0; a full,
fully-pinned pool degrades gracefully to the cold path — the request is
served, just without retention). Both halves are chunked-path only:
``retain_prefixes=True`` requires ``chunked=True`` (monolithic prefill
cannot resume mid-prompt) and an engine built with ``prefix_pool > 0``.

Telemetry (through the shared :class:`~apex_tpu.telemetry
.MetricsRegistry`): ``serving.ttft_s`` decomposed into
``serving.queue_wait_s`` (submit → admission) + per-chunk
``serving.prefill_chunk_s`` (the engine observes the latter),
``serving.decode.step_s`` histograms (p50/p95/p99 via the streaming
reservoir), ``serving.slot_occupancy`` / ``serving.padding_waste`` per
step, request outcome counters, one ``serving.request``-tagged
completion record per request (with ``chunks_per_prompt`` and
``reused_tokens``), a final ``serving.tokens_per_s`` gauge from
:meth:`run`, and the prefix-reuse layer: ``serving.prefix.hits`` /
``.misses`` / ``.hit_rate`` (gauge), ``serving.prefix.tokens_reused``,
``serving.prefix.chunks_skipped``, ``serving.prefix.evictions``,
``serving.prefix.registrations`` and ``serving.prefix.pool_full``.
Speculative runs add ``serving.spec.drafted`` / ``serving.spec
.accepted`` counters, the per-verify ``serving.spec.acceptance_rate``
histogram, the per-heartbeat ``serving.spec.tokens_per_step`` gauge
(tokens emitted per SLOT sequence-step — plain decode pins 1.0, the
>1 reading is the whole point), and per-request ``spec_accepted`` in
the completion record. The heartbeat watchdog separately accounts ticks that traced a
new compiled program as ``serving.watchdog.warmup_s`` instead of
breaching (first-contact compile time is not a stall).
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import itertools
import time
import weakref
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from apex_tpu.log_util import get_logger

from .faults import FaultPolicy, PoolAuditor, fault_kind
from .slo import SLOConfig, TenantLedger
from .speculative import DraftWorker, draft_tokens

__all__ = ["Request", "RequestStatus", "QueueFull",
           "DeadlineUnmeetable", "Scheduler",
           "request_from_wire", "request_to_wire",
           "snapshot_from_wire", "snapshot_to_wire"]

_logger = get_logger("serving")

_uid = itertools.count()


class RequestStatus(str, enum.Enum):
    """A request's lifecycle state — the ONE status vocabulary shared
    by the scheduler, the :class:`Request` record, and the telemetry
    completion records. A ``str`` subclass, so legacy comparisons
    against the transient literals (``"queued"``/``"prefilling"``/
    ``"running"``) keep working; the typed terminals are

    - ``FINISHED`` — served to completion (EOS / token budget / cache
      ``max_len``; see ``finish_reason`` for which);
    - ``EXPIRED`` — deadline passed while queued or running;
    - ``FAILED`` — the fault policy's retry budget ran out (transient
      step failures or non-finite quarantines; ``error`` carries the
      last fault).
    """

    NEW = "new"
    QUEUED = "queued"
    PREFILLING = "prefilling"
    RUNNING = "running"
    # transient, SLO scheduling only: evicted from its slot mid-decode
    # to make room for a higher-priority arrival — committed K/V
    # migrated to the host tier (or retained resident), the request
    # waits in the queue and resumes via swap-in + COW prefix share
    PREEMPTED = "preempted"
    FINISHED = "finished"
    EXPIRED = "expired"
    FAILED = "failed"

    def __str__(self) -> str:           # records/logs print the value
        return self.value

    @property
    def terminal(self) -> bool:
        return self in (RequestStatus.FINISHED, RequestStatus.EXPIRED,
                        RequestStatus.FAILED)


class QueueFull(RuntimeError):
    """Raised by :meth:`Scheduler.submit` when the bounded request queue
    is at capacity — the backpressure signal (shed or retry later).
    ``retry_after_s`` (when the scheduler has measured any decode
    throughput yet, else None) estimates how long until a queue
    position frees: decode-step EMA × the fewest steps any running
    request still needs."""

    def __init__(self, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineUnmeetable(QueueFull):
    """Raised by :meth:`Scheduler.submit` under deadline-aware
    admission (``SLOConfig.deadline_admission``) when the request's
    ``deadline_s`` cannot be met at the measured decode-step EMA —
    accepting it would only burn capacity on work destined to miss.
    A :class:`QueueFull` subclass, so every existing backpressure
    handler (the router's spill, ``run()``'s absorb loop) treats it as
    the shed-or-retry signal it is; ``retry_after_s`` is the EMA ×
    queue-position estimate of when the queue ahead will have
    drained."""



@dataclasses.dataclass
class Request:
    """One generation request and, after serving, its outcome.

    Inputs: ``prompt`` (token ids), ``max_new_tokens``, ``temperature``
    (0 = greedy), optional ``timeout_s`` (else the scheduler default).

    Outputs (filled by the scheduler): ``output_tokens``, ``status`` (a
    :class:`RequestStatus`: terminally ``FINISHED`` / ``EXPIRED`` /
    ``FAILED``; transiently ``QUEUED`` / ``PREFILLING`` / ``RUNNING``),
    ``finish_reason`` (``"eos"`` / ``"max_new_tokens"`` / ``"max_len"``
    / ``"timeout"`` / ``"fault"``), ``spec_drafted`` / ``spec_accepted``
    (speculative tokens proposed / accepted for this request —
    cumulative across retries, like the other paid-compute counters;
    0 on non-speculative runs), ``ttft_s`` and its decomposition
    ``queue_wait_s`` (submit → admission) + ``prefill_s`` (summed
    chunk/prefill compute — cumulative across retries: it is compute
    actually paid), ``chunks`` (prefill steps paid, cumulative across
    retries), ``reused_tokens`` (prompt positions restored from the
    prefix cache instead of prefilled; 0 on a miss or with retention
    off), ``latency_s`` (from the ORIGINAL submit — retries don't reset
    the clock), ``retries`` (transient faults absorbed so far) and
    ``error`` (the last fault's description; None when never faulted).
    """

    prompt: Sequence[int]
    max_new_tokens: int = 16
    temperature: float = 0.0
    timeout_s: Optional[float] = None
    uid: int = dataclasses.field(default_factory=lambda: next(_uid))
    # SLO inputs (all inert when the scheduler runs without an
    # SLOConfig — the FIFO path never reads them): ``slo_class`` names
    # a class in SLOConfig.classes (its base priority); ``priority``
    # adds on top (the whole priority for class-less requests);
    # ``deadline_s`` is a completion deadline RELATIVE to submit
    # (deadline-aware admission + the deadline_missed verdict);
    # ``tenant`` joins the weighted-fair ledger and the per-tenant
    # concurrency quota
    priority: int = 0
    slo_class: Optional[str] = None
    deadline_s: Optional[float] = None
    tenant: Optional[str] = None
    # multi-tenant LoRA: the adapter this request decodes under (a
    # name previously registered with the engine's adapter arena), or
    # None for the base model. Admission binds the adapter to the slot
    # (refcount-pinning it resident) before pages are reserved;
    # ``_free_slot`` is the single unbind point. An unknown name fails
    # the request loudly at admission — never a silent base-model
    # fallback
    adapter: Optional[str] = None

    # filled in by the scheduler
    output_tokens: List[int] = dataclasses.field(default_factory=list)
    status: RequestStatus = RequestStatus.NEW
    finish_reason: Optional[str] = None
    ttft_s: Optional[float] = None
    queue_wait_s: Optional[float] = None
    prefill_s: float = 0.0
    chunks: int = 0
    reused_tokens: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    latency_s: Optional[float] = None
    retries: int = 0
    error: Optional[str] = None
    # SLO outputs: times this request was preempted (cumulative —
    # preemption is not a fault, ``retries`` never moves), and the
    # finish-time deadline verdict (latency_s > deadline_s; always
    # False without a deadline)
    preemptions: int = 0
    deadline_missed: bool = False
    _t_submit: Optional[float] = dataclasses.field(default=None,
                                                   repr=False)
    # the CURRENT queueing episode's start (reset when a quarantine
    # requeues): queue_wait_s measures time actually spent waiting for
    # a slot, never prior service time — _t_submit keeps the original
    # clock for latency_s and deadlines
    _t_queued: Optional[float] = dataclasses.field(default=None,
                                                   repr=False)
    _prefill_pos: int = dataclasses.field(default=0, repr=False)
    _not_before: Optional[float] = dataclasses.field(default=None,
                                                     repr=False)
    # preempt/resume state: the token stream the NEXT admission must
    # ingest — prompt + committed outputs for a preempted request
    # (resume re-samples the last committed position, which IS the
    # next token), None otherwise (admission ingests the prompt).
    # Cleared by _reset_transient: a quarantine rolls outputs back, so
    # a stale ingest stream here would replay them as prompt and shift
    # the output stream — the exact wrong-token bug the
    # quarantined-while-preempted chaos test pins
    _ingest_tokens: Optional[List[int]] = dataclasses.field(
        default=None, repr=False)
    # effective priority PINNED at admission (base + the aging boost
    # earned while queued): the victim-selection comparison reads this
    # for running requests, so an aged-up admission keeps its boost
    # and cannot be instantly re-preempted by a fresh arrival of the
    # same base class
    _eff_priority: Optional[int] = dataclasses.field(default=None,
                                                     repr=False)


# --------------------------------------------------------------- wire forms
#
# The process-level fleet ships requests and load snapshots between a
# controller and its worker processes as VERSIONED plain dicts —
# explicit serialize/deserialize pairs, not implicit pickling of live
# objects, so the wire contract is inspectable, testable without a
# socket, and LOUD when a version mismatch crosses the boundary (a
# controller and worker built from different trees must fail with a
# ValueError, never deserialize garbage silently). The private
# ``Request`` clock fields (``_t_submit`` etc.) deliberately do NOT
# cross: ``time.perf_counter`` bases are per-process, so a shipped
# clock would be meaningless on arrival — each side stamps its own.

REQUEST_WIRE_VERSION = 3    # v2: SLO fields (priority/slo_class/
#                             deadline_s/tenant in; preemptions/
#                             deadline_missed out); v3: adapter
SNAPSHOT_WIRE_VERSION = 3   # v2: oldest_deadline_s/preemptible_pages;
#                             v3: resident_adapters

#: The load-snapshot key set — part of the versioned wire contract
#: (routing_policy ranks on these fields, so both fronts must see the
#: same ones; bump SNAPSHOT_WIRE_VERSION when this tuple changes).
#: v2 adds ``oldest_deadline_s`` (tightest remaining deadline across
#: queued+running, RELATIVE seconds — perf_counter bases never cross a
#: process boundary — None when nothing carries one) and
#: ``preemptible_pages`` (pages held by running requests strictly
#: below the SLO config's top class — the headroom a top-priority
#: arrival could reclaim; None when SLO scheduling is off or the
#: engine is not paged). v3 adds ``resident_adapters`` (the adapter
#: names currently resident in the engine's LoRA arena — the
#: adapter-affinity signal, ranked by routing_policy right after the
#: prefix-affinity match; None when LoRA serving is off).
_SNAPSHOT_KEYS = ("queue_depth", "queue_free", "slots", "slots_busy",
                  "slots_free", "inflight_steps", "pages_free",
                  "host_bytes_free", "oldest_deadline_s",
                  "preemptible_pages", "resident_adapters")


def request_to_wire(request: Request) -> dict:
    """``request`` as its versioned dict wire form: every public
    field, plain Python scalars only (token ids coerced through
    ``int`` so numpy scalars never leak into a frame). The private
    per-process clocks stay home (see the wire-forms note above)."""
    return {
        "v": REQUEST_WIRE_VERSION,
        "prompt": [int(t) for t in request.prompt],
        "max_new_tokens": int(request.max_new_tokens),
        "temperature": float(request.temperature),
        "timeout_s": request.timeout_s,
        "uid": int(request.uid),
        "priority": int(request.priority),
        "slo_class": request.slo_class,
        "deadline_s": request.deadline_s,
        "tenant": request.tenant,
        "adapter": request.adapter,
        "output_tokens": [int(t) for t in request.output_tokens],
        "status": request.status.value,
        "finish_reason": request.finish_reason,
        "ttft_s": request.ttft_s,
        "queue_wait_s": request.queue_wait_s,
        "prefill_s": float(request.prefill_s),
        "chunks": int(request.chunks),
        "reused_tokens": int(request.reused_tokens),
        "spec_drafted": int(request.spec_drafted),
        "spec_accepted": int(request.spec_accepted),
        "latency_s": request.latency_s,
        "retries": int(request.retries),
        "error": request.error,
        "preemptions": int(request.preemptions),
        "deadline_missed": bool(request.deadline_missed),
    }


def request_from_wire(wire: dict) -> Request:
    """The :class:`Request` a wire dict describes. Raises
    ``ValueError`` on an unknown wire version (the loud cross-build
    guard) and ``KeyError`` on a missing field — a truncated frame
    must never deserialize into a plausible half-request."""
    v = wire.get("v")
    if v != REQUEST_WIRE_VERSION:
        raise ValueError(
            f"unknown Request wire version {v!r} (this build speaks "
            f"{REQUEST_WIRE_VERSION}) — controller and workers must "
            "run the same tree")
    return Request(
        prompt=list(wire["prompt"]),
        max_new_tokens=wire["max_new_tokens"],
        temperature=wire["temperature"],
        timeout_s=wire["timeout_s"],
        uid=wire["uid"],
        priority=wire["priority"],
        slo_class=wire["slo_class"],
        deadline_s=wire["deadline_s"],
        tenant=wire["tenant"],
        adapter=wire["adapter"],
        output_tokens=list(wire["output_tokens"]),
        status=RequestStatus(wire["status"]),
        finish_reason=wire["finish_reason"],
        ttft_s=wire["ttft_s"],
        queue_wait_s=wire["queue_wait_s"],
        prefill_s=wire["prefill_s"],
        chunks=wire["chunks"],
        reused_tokens=wire["reused_tokens"],
        spec_drafted=wire["spec_drafted"],
        spec_accepted=wire["spec_accepted"],
        latency_s=wire["latency_s"],
        retries=wire["retries"],
        error=wire["error"],
        preemptions=wire["preemptions"],
        deadline_missed=wire["deadline_missed"],
    )


def snapshot_to_wire(snapshot: dict) -> dict:
    """A :meth:`Scheduler.load_snapshot` dict as its versioned wire
    form (the fixed key set, loud on a missing key)."""
    out = {"v": SNAPSHOT_WIRE_VERSION}
    for k in _SNAPSHOT_KEYS:
        out[k] = snapshot[k]
    return out


def snapshot_from_wire(wire: dict) -> dict:
    """The plain load-snapshot dict a wire form describes — exactly
    the shape :meth:`Scheduler.load_snapshot` returns, so
    ``routing_policy.rank_replicas`` consumes local and remote
    snapshots interchangeably. Loud ``ValueError`` on an unknown
    version, ``KeyError`` on a missing load key."""
    v = wire.get("v")
    if v != SNAPSHOT_WIRE_VERSION:
        raise ValueError(
            f"unknown load-snapshot wire version {v!r} (this build "
            f"speaks {SNAPSHOT_WIRE_VERSION}) — controller and "
            "workers must run the same tree")
    return {k: wire[k] for k in _SNAPSHOT_KEYS}


@dataclasses.dataclass
class _InflightStep:
    """Host-side record of one dispatch-ahead decode step: the
    engine's :class:`~apex_tpu.serving.PendingDecode` handle plus the
    ``slot -> request uid`` map it was computed for. Reconcile emits a
    slot's token only while the SAME request still runs there — any
    finality, quarantine or expiry that frees the slot drops its entry
    from every in-flight record on the spot (``_free_slot``), which is
    the whole host-side rollback; the uid+status re-check at reconcile
    is belt-and-braces on top."""

    pending: object
    uids: Dict[int, int]
    tick: int

    # ``uids`` is mutated by Scheduler._free_slot: the moment a slot
    # frees (finish, quarantine, expiry), its entry is DROPPED from
    # every in-flight record and counted as discarded — eager
    # invalidation, because a requeued request keeps its uid, so a
    # reconcile-time uid comparison alone could mistake a stale
    # pre-quarantine step for the retried occupant's.


class Scheduler:
    """Continuous-batching front of an :class:`~apex_tpu.serving.Engine`
    (see module docstring for the step anatomy). ``pipeline_depth=0``
    (default) is the fully synchronous beat; ``>= 1`` enables
    dispatch-ahead decode with deferred token readback (bitwise-greedy
    identical, see the module docstring's async-heartbeat section)."""

    def __init__(self, engine, *, max_queue: int = 64,
                 default_timeout_s: Optional[float] = None,
                 eos_id: Optional[int] = None, registry=None,
                 chunked: bool = True, chunk_budget: int = 1,
                 retain_prefixes: bool = False,
                 speculative: bool = False,
                 pipeline_depth: int = 0,
                 role: str = "both",
                 on_requeue=None,
                 fault_policy: Optional[FaultPolicy] = None,
                 fault_plan=None,
                 auditor: Optional[PoolAuditor] = None,
                 tracer=None,
                 slo: Optional[SLOConfig] = None,
                 tenant_ledger: Optional[TenantLedger] = None):
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        if chunk_budget < 1:
            raise ValueError("chunk_budget must be >= 1")
        if pipeline_depth < 0:
            raise ValueError("pipeline_depth must be >= 0 (0 = the "
                             "synchronous oracle beat)")
        if speculative and getattr(engine, "spec", None) is None:
            raise ValueError(
                "speculative=True requires an engine built with "
                "spec=SpecConfig(...) — the verify program's shape is "
                "fixed at engine construction")
        if retain_prefixes:
            if not chunked:
                raise ValueError(
                    "retain_prefixes requires chunked=True: prefix reuse"
                    " resumes prefill mid-prompt, which the monolithic "
                    "program cannot do")
            if getattr(engine, "prefix_cache", None) is None:
                raise ValueError(
                    "retain_prefixes requires an engine built with "
                    "prefix_pool > 0 (no pool rows to retain into)")
        if slo is not None:
            if not chunked:
                raise ValueError(
                    "slo scheduling requires chunked=True: resume "
                    "re-ingests mid-stream at the committed offset, "
                    "which the monolithic program cannot do")
            if slo.preempt:
                if not retain_prefixes \
                        or not getattr(engine, "paged", False):
                    raise ValueError(
                        "slo.preempt requires a paged engine with "
                        "retain_prefixes=True: a preempted request's "
                        "committed K/V survives as a prefix-cache "
                        "entry (host-tier swap or resident COW share) "
                        "and resume is an ordinary prefix attach")
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got "
                f"{role!r}")
        if role != "both":
            if not retain_prefixes:
                raise ValueError(
                    f"role={role!r} requires retain_prefixes=True: the "
                    "KV handoff travels as an ordinary swapped prefix, "
                    "so both sides need the prefix-cache machinery")
            if not getattr(engine, "paged", False) \
                    or getattr(engine, "host_tier", None) is None:
                raise ValueError(
                    f"role={role!r} requires a paged engine with a "
                    "host_tier: the handoff's KV travels through the "
                    "(shared) host arena's swap programs")
        self.engine = engine
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.eos_id = eos_id
        self.chunked = bool(chunked)
        self.chunk_budget = int(chunk_budget)
        self.retain_prefixes = bool(retain_prefixes)
        self.speculative = bool(speculative)
        # disaggregated serving (role != "both"): "prefill" replicas
        # ingest prompts and export the finished prefix to the shared
        # host arena instead of ever decoding; "decode" replicas accept
        # only router hand-overs (plus their verified-miss re-prefills)
        self.role = str(role)
        # SLO scheduling: None keeps the verbatim FIFO admission path
        # (the baseline every SLO claim is benchmarked against — zero
        # new compiled programs, pinned); a config switches admission
        # to priority order with optional preemption, deadline
        # admission and tenant fairness. The ledger is process-local
        # shared state: the Router passes ONE across its replicas so
        # fairness spans the process; each fleet worker builds its own
        self.slo = slo
        if tenant_ledger is not None:
            self.tenants: Optional[TenantLedger] = tenant_ledger
        elif slo is not None:
            self.tenants = TenantLedger(slo.tenant_weights)
        else:
            self.tenants = None
        # uids preempted since their last admission: the resume marker
        # _consult_prefix_cache reads (and clears) to count/trace the
        # resume rather than a disagg handoff import
        self._preempted_uids: set = set()
        # re-probe-at-requeue seam: when set, a quarantine offers the
        # requeued request back to the router (which re-probes LIVE
        # replicas and the arena) instead of this replica's own queue;
        # returns True when the router took it
        self.on_requeue = on_requeue
        self.registry = registry if registry is not None \
            else getattr(engine, "_registry", None)
        # request tracing (None = off, the zero-cost default: every
        # hook below is an `is not None` guard around pure host-clock
        # reads — no span objects exist, no tokens change, pinned by
        # tests/L0/test_tracing.py). The tracer propagates to the
        # engine so swap-path spans (which never see a Request) attach
        # to the admitting request via the thread-local binding the
        # admission path holds. ``replica_index`` stamps completion
        # records and is rewritten by the Router (replica i).
        self.tracer = tracer
        self.replica_index = 0
        if tracer is not None and hasattr(engine, "set_tracer"):
            engine.set_tracer(tracer)
        # registry wiring: several engine-side metrics (the guard's
        # serving.faults.nonfinite above all) are emitted by the
        # ENGINE's registry — a scheduler-only registry would silently
        # miss them, so propagate ours to a registry-less engine; when
        # BOTH are set and differ, keep them (the split may be
        # deliberate) but say so loudly
        eng_reg = getattr(engine, "_registry", None)
        if self.registry is not None and hasattr(engine, "set_registry"):
            if eng_reg is None:
                engine.set_registry(self.registry)
            elif eng_reg is not self.registry:
                _logger.warning(
                    "scheduler and engine carry DIFFERENT telemetry "
                    "registries: engine-side metrics (e.g. "
                    "serving.faults.nonfinite, serving.prefill.*) land "
                    "in the engine's, scheduler-side in the "
                    "scheduler's — pass one registry to both unless "
                    "the split is deliberate")
        self._queue: collections.deque = collections.deque()
        self._running: List[Optional[Request]] = [None] * engine.slots
        self._last_tokens = np.zeros(engine.slots, np.int32)
        self._temps = np.zeros(engine.slots, np.float32)
        self._pf_rr = 0           # round-robin start for chunk budgeting
        # per-slot pinned prefix match (released when the slot frees)
        self._slot_prefix: List[Optional[object]] = [None] * engine.slots
        self.completed: List[Request] = []
        # fault isolation: containment is ALWAYS on (the policy has
        # production defaults); the plan is the chaos harness's
        # injection schedule (None in production); the auditor
        # reconciles page refcounts after finish/eviction events on
        # paged engines, sampled by the policy's audit_every_n
        self.fault_policy = fault_policy if fault_policy is not None \
            else FaultPolicy()
        self.fault_plan = fault_plan
        if auditor is not None:
            self.auditor = auditor
        elif getattr(engine, "paged", False):
            self.auditor = PoolAuditor(
                every_n=self.fault_policy.audit_every_n,
                registry=self.registry)
        else:
            self.auditor = None
        self._tick = 0            # heartbeat index (the FaultPlan clock)
        self._step_s_ema: Optional[float] = None   # decode-step seconds
        # ---- async pipelined heartbeat state (pipeline_depth >= 1):
        # dispatched-but-unreconciled decode steps, oldest first, and
        # the worker thread that overlaps drafting + prefix hashing
        # with device execution. Depth 0 never touches any of it — the
        # sync beat stays the bitwise oracle path.
        self.pipeline_depth = int(pipeline_depth)
        self._pipeline: collections.deque = collections.deque()
        self._worker: Optional[DraftWorker] = None
        if self.pipeline_depth > 0:
            self._worker = DraftWorker()
            # stop the thread when the scheduler is collected (the
            # finalizer closes over the WORKER, not self — no cycle)
            weakref.finalize(self, self._worker.stop)
        # per-slot precomputed prefix block keys (admission stashes the
        # worker's hash for the registration that follows ingestion)
        self._slot_hash_keys: List[Optional[list]] = \
            [None] * engine.slots
        # uid -> rolling block keys handed in at submit (the router's
        # pre-probed hashes); consumed at admission, dropped at finish
        self._presubmitted_keys: Dict[int, list] = {}
        # prefill-role: finished prompt ingestions awaiting collection
        # by the router as (request, arena key or None, block keys) —
        # ready once the record's async swap-out completes
        self._handoffs: List[tuple] = []
        # decode-role: uid -> arena key for routed handoffs awaiting
        # admission (resolved — imported or verified-miss re-prefilled —
        # by _consult_prefix_cache)
        self._handoff_uids: Dict[int, int] = {}
        # dispatch-ahead chunk prefill (pipeline_depth >= 1): per-slot
        # dispatched-but-unreconciled PendingPrefill handle as
        # (pending, uid, lo, hi, t_dispatch); depth 0 never populates it
        self._pending_prefill: List[Optional[tuple]] = \
            [None] * engine.slots
        # decode-beat isolation accounting: beats taken vs beats that
        # ran any chunk-prefill work (the router aggregates these into
        # the serving.disagg.decode_isolation gauge)
        self.beats_total = 0
        self.beats_with_prefill = 0

    # ------------------------------------------------------------ ingestion
    def submit(self, request: Request,
               prefix_keys: Optional[Sequence[int]] = None,
               count_rejection: bool = True,
               _handoff: bool = False) -> Request:
        """Queue ``request``; raises :class:`QueueFull` at capacity and
        ``ValueError`` for prompts the engine can never serve.

        ``count_rejection=False`` suppresses the
        ``serving.requests.rejected`` tick on a capacity raise — the
        router probes replicas with it so an absorbed SPILL (placed
        and served on the next-best replica) never reads as a
        caller-visible rejection; the router counts one rejection
        itself only when the WHOLE fleet turns the request away.

        ``prefix_keys`` (optional) are the prompt's PRECOMPUTED rolling
        block hashes — the :class:`~apex_tpu.serving.Router` already
        computed them once to probe every replica's prefix cache, so
        the chosen replica takes them here instead of re-hashing (the
        hash is deterministic: precomputed and inline keys are
        interchangeable bit-for-bit). At least ``len(prompt) //
        block_len`` keys, as :meth:`PrefixCache.block_keys` returns.

        A request whose ``_t_submit`` clock is already running (a
        router requeue after a replica death) keeps it — like a
        quarantine requeue, re-submission never resets ``latency_s``
        or the deadline."""
        n = len(request.prompt)
        if not 0 < n <= self.engine.prefill_len:
            raise ValueError(
                f"prompt length {n} not in (0, prefill_len="
                f"{self.engine.prefill_len}] — the fixed-shape prefill "
                "program cannot admit it")
        if request.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if request.adapter is not None \
                and getattr(self.engine, "lora", None) is None:
            raise ValueError(
                f"request names adapter {request.adapter!r} but the "
                "engine was built without lora=LoRAConfig(...) — "
                "LoRA serving is off")
        if self.slo is not None:
            # validates slo_class loudly (unknown names raise here, at
            # the door, instead of silently scheduling as priority 0)
            self.slo.base_priority(request)
        if self.role == "decode" and not _handoff:
            raise ValueError(
                "role='decode' replica serves router hand-overs only — "
                "submit to a prefill-capable replica (the Router's "
                "role policy routes new prompts there)")
        # deadline-aware admission: once any decode throughput has
        # been measured, estimate this request's completion as EMA ×
        # (queue positions ahead + its own chunk count + its token
        # budget) — one heartbeat is at least one EMA'd step. An
        # estimate past the deadline is rejected NOW with an honest
        # retry hint (EMA × queue depth: when the queue ahead has
        # drained, the estimate shrinks below the deadline) instead of
        # admitting work destined to miss. Deliberately conservative
        # in neither direction: no prefix-hit discount (unknowable
        # pre-admission), no slot-parallelism credit.
        if self.slo is not None and self.slo.deadline_admission \
                and request.deadline_s is not None \
                and self._step_s_ema is not None:
            est = self._step_s_ema * (
                len(self._queue) + self.engine.chunks_for(n)
                + request.max_new_tokens)
            if est > request.deadline_s:
                if self.registry is not None:
                    self.registry.counter_inc(
                        "serving.slo.deadline_rejected")
                hint = round(self._step_s_ema
                             * max(1, len(self._queue)), 6)
                raise DeadlineUnmeetable(
                    f"deadline_s={request.deadline_s:.3f} unmeetable: "
                    f"estimated completion ~{est:.3f}s at the current "
                    f"decode rate (retry_after_s~{hint:.3f})",
                    retry_after_s=hint)
        # paged note: no page-demand check is needed here — a request's
        # worst case is capped at ceil(max_len / page_len) pages, which
        # the Engine constructor guarantees every pool can hold, so the
        # queue head always admits eventually as running slots drain
        if len(self._queue) >= self.max_queue:
            if self.registry is not None and count_rejection:
                self.registry.counter_inc("serving.requests.rejected")
            hint = self._retry_after_hint()
            suffix = f" (retry_after_s~{hint:.3f})" if hint else ""
            raise QueueFull(
                f"request queue at capacity ({self.max_queue}); retry "
                f"after a step() or shed load{suffix}",
                retry_after_s=hint)
        request.status = RequestStatus.QUEUED
        now = time.perf_counter()
        if request._t_submit is None:
            request._t_submit = now
        request._t_queued = now
        if self.tracer is not None:
            self.tracer.event(request.uid, "submit", t0=now,
                              prompt_tokens=n,
                              max_new_tokens=request.max_new_tokens,
                              retry=request.retries)
        self._queue.append(request)
        if self.retain_prefixes and prefix_keys is not None:
            # the router's pre-probed hashes: admission consumes them
            # in place of a worker/inline computation
            self._presubmitted_keys[request.uid] = list(prefix_keys)
        elif self._worker is not None and self.retain_prefixes:
            # hash offload: the prompt's rolling block keys start
            # computing NOW on the worker thread, overlapping whatever
            # the device is executing — admission takes the result (or
            # computes inline on a miss; identical bits either way)
            pcache = self.engine.prefix_cache
            prompt = tuple(request.prompt)
            n_blocks = len(prompt) // pcache.block_len
            self._worker.submit(
                ("hash", request.uid),
                lambda: pcache.block_keys(prompt, n_blocks))
        if self.registry is not None:
            self.registry.counter_inc("serving.requests.submitted")
        return request

    # ----------------------------------------------------------- accounting
    def _retry_after_hint(self) -> Optional[float]:
        """The :class:`QueueFull` backoff hint, derived from measured
        decode throughput: a queue position frees when the nearest
        running request finishes, which costs at least (fewest
        remaining tokens across running slots) decode steps at the
        EMA'd step latency. None before the first measured decode step
        (nothing honest to say yet)."""
        if self._step_s_ema is None:
            return None
        remaining = [max(1, r.max_new_tokens - len(r.output_tokens))
                     for r in self._running if r is not None]
        steps = min(remaining) if remaining else 1
        return round(steps * self._step_s_ema, 6)

    def _free_slot(self, slot: int) -> None:
        """Detach whatever occupies ``slot``: clear the running entry,
        unpin its prefix donor, and (paged) return its pages plus any
        unused admission reservation to the pool NOW — on the
        contiguous layout the row is only reclaimed by the next prefill
        overwriting it. Shared by normal finishes and fault
        quarantines."""
        self._running[slot] = None
        self._temps[slot] = 0.0
        self._slot_hash_keys[slot] = None
        if self._pending_prefill[slot] is not None:
            # a dispatched-ahead prefill chunk nobody will read: the
            # same speculated-finality rollback as the decode pipeline
            self._pending_prefill[slot] = None
            if self.registry is not None:
                self.registry.counter_inc("serving.heartbeat.discarded")
        if self._pipeline:
            # invalidate the slot's in-flight dispatch-ahead steps NOW
            # (speculated-finality rollback): a uid check at reconcile
            # is NOT enough on its own — a quarantined request keeps
            # its uid through requeue, so if it re-admits into this
            # same slot before the stale steps retire, their
            # garbage-lineage tokens would pass a uid+status test and
            # be emitted into the retried stream
            dropped = sum(rec.uids.pop(slot, None) is not None
                          for rec in self._pipeline)
            if dropped and self.registry is not None:
                self.registry.counter_inc("serving.heartbeat.discarded",
                                          dropped)
        if self._slot_prefix[slot] is not None:
            # the slot no longer reads from its donor prefix: unpin
            self.engine.prefix_cache.release(self._slot_prefix[slot])
            self._slot_prefix[slot] = None
        if getattr(self.engine, "lora", None) is not None:
            # the single LoRA unbind point: drops the slot's adapter
            # refcount (the adapter STAYS resident for affinity — only
            # arena pressure evicts it). Not in Engine.release_slot,
            # which cold-start prefill calls mid-request
            self.engine.lora_unbind(slot)
        if getattr(self.engine, "paged", False):
            self.engine.release_slot(slot)

    def _finish(self, request: Request, reason: str,
                slot: Optional[int] = None,
                status: Optional[RequestStatus] = None) -> None:
        request.finish_reason = reason
        if status is None:
            status = RequestStatus.EXPIRED if reason == "timeout" \
                else RequestStatus.FINISHED
        request.status = status
        self._presubmitted_keys.pop(request.uid, None)
        self._preempted_uids.discard(request.uid)
        if self._handoff_uids:
            hkey = self._handoff_uids.pop(request.uid, None)
            if hkey is not None:
                # the request died (expired/failed) before admission
                # could import its handoff: release the orphaned cache
                # entry and its arena record
                if self.engine.prefix_cache.drop(hkey):
                    tier = getattr(self.engine, "host_tier", None)
                    if tier is not None:
                        tier.discard(hkey)
        if request._t_submit is not None:
            request.latency_s = time.perf_counter() - request._t_submit
        if request.deadline_s is not None \
                and request.latency_s is not None:
            request.deadline_missed = \
                request.latency_s > request.deadline_s
        if self.tenants is not None and request.tenant is not None:
            # finish-time charge: only work actually delivered moves
            # the weighted-fair ledger
            self.tenants.charge(request.tenant,
                                len(request.output_tokens))
        if self.tracer is not None:
            # the trace's single TERMINAL span, spelled as three
            # explicit literals (the span-name lint reads literals):
            # sealing is first-wins, so a late double-finish is inert
            tr = self.tracer
            if status is RequestStatus.EXPIRED:
                tr.end_trace(request.uid, "expired", reason=reason)
            elif status is RequestStatus.FAILED:
                tr.end_trace(request.uid, "failed", reason=reason,
                             error=request.error)
            else:
                tr.end_trace(request.uid, "finish", reason=reason,
                             output_tokens=len(request.output_tokens))
        if slot is not None:
            self._free_slot(slot)
        self.completed.append(request)
        if self.registry is not None:
            key = {RequestStatus.EXPIRED: "serving.requests.timeout",
                   RequestStatus.FAILED: "serving.requests.failed"}.get(
                       status, "serving.requests.completed")
            self.registry.counter_inc(key)
            # one completion record per request: the TTFT decomposition
            # and chunk count ride the ring/sinks alongside the
            # aggregate histograms (observe=False: uid is not a series
            # and the latencies already live in dedicated serving.*
            # histograms — don't grow junk reservoirs per request)
            self.registry.record_step({
                "uid": request.uid,
                "trace_id": request.uid,
                "replica": self.replica_index,
                "status": request.status.value,
                "finish_reason": reason,
                "prompt_tokens": len(request.prompt),
                "output_tokens": len(request.output_tokens),
                "chunks_per_prompt": request.chunks,
                "reused_tokens": request.reused_tokens,
                "spec_drafted": request.spec_drafted,
                "spec_accepted": request.spec_accepted,
                "retries": request.retries,
                "error": request.error,
                "queue_wait_s": request.queue_wait_s,
                "prefill_s": request.prefill_s,
                "ttft_s": request.ttft_s,
                "latency_s": request.latency_s,
                "slo_class": request.slo_class,
                "priority": request.priority,
                "tenant": request.tenant,
                "preemptions": request.preemptions,
                "deadline_missed": request.deadline_missed,
            }, tag="serving.request", observe=False)
            if self.slo is not None:
                # per-class SLO telemetry: one namespaced family per
                # class (the emitted⇔documented lint reduces the
                # f-string to its serving.slo.class literal)
                cls = request.slo_class if request.slo_class \
                    is not None else "none"
                self.registry.counter_inc(
                    f"serving.slo.class.{cls}.completed")
                if request.ttft_s is not None:
                    self.registry.observe(
                        f"serving.slo.class.{cls}.ttft_s",
                        request.ttft_s)
                if request.deadline_missed:
                    self.registry.counter_inc(
                        "serving.slo.deadline_missed")
                    self.registry.counter_inc(
                        f"serving.slo.class.{cls}.deadline_missed")
                if request.tenant is not None \
                        and self.tenants is not None:
                    self.registry.counter_inc(
                        f"serving.slo.tenant.{request.tenant}.tokens",
                        len(request.output_tokens))
        if self.auditor is not None:
            # finish events move refcounts (page release, reservation
            # return): reconcile on the policy's sampling cadence
            self.auditor.maybe_audit(self.engine)

    def _quarantine(self, request: Request, slot: Optional[int],
                    error: str) -> None:
        """Contain one per-request fault: free the slot (pages,
        reservation, prefix pin), then either requeue the request with
        capped exponential backoff — its transient outputs reset, its
        paid-compute counters (``chunks``, ``prefill_s``) and the
        original submit clock kept — or, past ``max_retries``, finish
        it with the typed ``FAILED`` terminal status. The engine and
        every other slot are untouched: this is the blast-radius
        boundary."""
        request.retries += 1
        request.error = error
        policy = self.fault_policy
        if self.tracer is not None:
            self.tracer.event(
                request.uid, "quarantine", kind=fault_kind(error),
                error=error, retry=request.retries,
                requeued=request.retries <= policy.max_retries)
        if slot is not None:
            self._free_slot(slot)
        if request.retries > policy.max_retries:
            _logger.warning(
                "request %d FAILED after %d retries: %s", request.uid,
                request.retries - 1, error)
            self._finish(request, "fault", status=RequestStatus.FAILED)
            return
        now = self._reset_transient(request)
        request._not_before = now + policy.backoff_s(request.retries)
        # re-probe on requeue: offer the request back to the router
        # first (it re-probes LIVE replicas and the arena at re-route
        # time, so the retry can home onto a prefix or handoff that
        # registered after the original submit); the local queue is the
        # fallback when no router is wired or it declined
        rerouted = self.on_requeue is not None \
            and bool(self.on_requeue(request))
        if not rerouted:
            self._queue.append(request)
        if self.registry is not None:
            self.registry.counter_inc("serving.faults.requeued")
        _logger.info("request %d requeued%s (retry %d/%d): %s",
                     request.uid, " via router" if rerouted else "",
                     request.retries, policy.max_retries,
                     error)

    def _reset_transient(self, request: Request) -> float:
        """Roll ``request`` back to a servable queued state (the shared
        half of a quarantine requeue and a replica-death drain): its
        transient outputs reset, its paid-compute counters (``chunks``,
        ``prefill_s``, the spec counters) and the ORIGINAL submit clock
        kept — retries and drains never reset ``latency_s`` or the
        deadline. Returns ``now`` (the fresh queueing episode's
        start)."""
        request.output_tokens.clear()
        request._prefill_pos = 0
        request.reused_tokens = 0
        request.ttft_s = None
        # BUGFIX guard for the quarantined-while-preempted path: the
        # outputs just rolled back, so the preempt-time ingest stream
        # (prompt + those outputs) is now a lie — replaying it would
        # emit the request's tokens shifted by the replayed outputs, a
        # silent wrong-token stream. Clearing it degrades the resume
        # to the verified-miss contract: the next admission ingests
        # the PROMPT (any surviving prefix entry still prefix-matches
        # it token-verified; a corrupt swap record fails its CRC and
        # re-prefills cold), never a wrong token.
        request._ingest_tokens = None
        request._eff_priority = None
        request.status = RequestStatus.QUEUED
        now = time.perf_counter()
        request._t_queued = now     # a fresh queueing episode begins
        return now

    def _deadline(self, request: Request) -> Optional[float]:
        t = request.timeout_s if request.timeout_s is not None \
            else self.default_timeout_s
        if t is None or request._t_submit is None:
            return None
        return request._t_submit + t

    def _expire(self, now: float) -> None:
        for r in [r for r in self._queue
                  if (d := self._deadline(r)) is not None and now > d]:
            self._queue.remove(r)
            self._finish(r, "timeout")
        for slot, r in enumerate(self._running):
            if r is None:
                continue
            d = self._deadline(r)
            if d is not None and now > d:
                self._finish(r, "timeout", slot)

    # ------------------------------------------------------------ admission
    def _eligible_index(self, now: float) -> Optional[int]:
        """The queue index of the first request whose retry backoff (if
        any) has elapsed — FIFO order among eligible requests; a
        backing-off request never blocks the ones behind it (it already
        had its turn)."""
        for i, r in enumerate(self._queue):
            if r._not_before is None or r._not_before <= now:
                return i
        return None

    def _admit(self) -> None:
        if not self.chunked:
            return self._admit_monolithic()
        if self.slo is not None:
            return self._admit_slo()
        for slot in range(self.engine.slots):
            if self._running[slot] is not None or not self._queue:
                continue
            idx = self._eligible_index(time.perf_counter())
            if idx is None:
                break               # everything queued is backing off
            gate = self._lora_gate(slot, idx)
            if gate == "failed":
                continue            # the queue changed: re-scan
            if gate == "blocked":
                break               # every arena row pinned: FIFO
                #                     holds until a finish unbinds one
            if not self._reserve_pages(slot, self._queue[idx]):
                # pool exhausted for the first eligible request: stop
                # admitting (FIFO — later, smaller requests must not
                # starve it); finishing requests release pages, so the
                # next beat retries
                if getattr(self.engine, "lora", None) is not None:
                    self.engine.lora_unbind(slot)
                break
            self._admit_one(slot, idx)

    def _lora_gate(self, slot: int, idx: int) -> str:
        """Admission-time LoRA bind for queue position ``idx`` into
        ``slot`` — runs BEFORE the page reservation so a blocked bind
        never strands reserved pages. Returns ``"ok"`` (bound, or a
        base-model request — nothing to do), ``"blocked"`` (the
        adapter is absent and every arena row is pinned by a running
        slot: the caller stops admitting; finishes unbind rows and the
        next beat retries) or ``"failed"`` (the adapter is unknown to
        the arena or failed its swap-in checksum: the request fails
        LOUDLY here — removed from the queue, FAILED, error recorded —
        never a silent base-model fallback)."""
        r = self._queue[idx]
        if r.adapter is None \
                or getattr(self.engine, "lora", None) is None:
            return "ok"
        try:
            bound = self.engine.lora_bind(slot, r.adapter)
        except KeyError as e:
            del self._queue[idx]
            r.error = str(e.args[0]) if e.args else str(e)
            self._finish(r, "fault", status=RequestStatus.FAILED)
            return "failed"
        return "ok" if bound else "blocked"

    def _admit_one(self, slot: int, idx: int) -> None:
        """Admit queue position ``idx`` into free ``slot`` (pages
        already reserved): the shared tail of the FIFO and SLO
        admission loops — bitwise the pre-SLO admission body, so the
        ``slo=None`` trace path is verbatim the old one."""
        r = self._queue[idx]
        del self._queue[idx]
        # admission ends the queue wait; prefill compute is paid one
        # chunk per heartbeat from here (_prefill_tick)
        r.queue_wait_s = time.perf_counter() - r._t_queued
        if self.registry is not None:
            self.registry.observe("serving.queue_wait_s",
                                  r.queue_wait_s)
        r.status = RequestStatus.PREFILLING
        r._prefill_pos = 0
        if self.retain_prefixes:
            if self.tracer is not None:
                # bind the trace to this thread so swap-in /
                # swap-out spans the prefix attach triggers inside
                # the engine attribute to the admitting request
                with self.tracer.bind(r.uid):
                    self._consult_prefix_cache(r, slot)
            else:
                self._consult_prefix_cache(r, slot)
        if self.tracer is not None:
            tr = self.tracer
            t_adm = tr.now()
            tr.event(r.uid, "queue_wait",
                     t0=t_adm - r.queue_wait_s, dur=r.queue_wait_s)
            tr.event(r.uid, "admit", t0=t_adm, slot=slot,
                     reused_tokens=r.reused_tokens,
                     pages=(self.engine.pages_required(
                         len(r.prompt), r.max_new_tokens)
                         if getattr(self.engine, "paged", False)
                         else 0))
        self._running[slot] = r
        self._temps[slot] = r.temperature

    # -------------------------------------------- SLO admission + preemption
    def _admit_slo(self) -> None:
        """Priority-order admission (``slo`` set): repeatedly pick the
        most important eligible queued request — highest effective
        priority (base + queue-aging boost), then earliest deadline,
        then the tenant owed the most weighted service, then FIFO —
        and place it in a free slot. When no slot (or no page
        reservation) can be found and ``slo.preempt`` is on, the
        lowest-priority running request STRICTLY below the candidate
        preempts to the host tier instead of the candidate queueing
        behind it. The loop guard bounds pathological ladders (every
        iteration admits, preempts or returns)."""
        guard = 4 * (self.engine.slots + len(self._queue) + 2)
        while self._queue and guard > 0:
            guard -= 1
            now = time.perf_counter()
            idx = self._eligible_index_slo(now)
            if idx is None:
                return          # backing off / quota-blocked across the board
            cand = self._queue[idx]
            slot = next((s for s in range(self.engine.slots)
                         if self._running[s] is None), None)
            if slot is None:
                if not self._try_preempt(cand, now):
                    return
                continue        # a slot just freed: re-scan (the
                #                 candidate set may have re-ranked)
            gate = self._lora_gate(slot, idx)
            if gate == "failed":
                continue        # the queue changed: re-rank
            if gate == "blocked":
                return          # every arena row pinned: admission
                #                 holds until a finish unbinds one
            if not self._reserve_pages(slot, cand):
                # pool exhausted: preempting releases the victim's
                # pages (swap-out frees them at dispatch; a resident
                # retention frees them through try_reserve_slot's LRU
                # valve on the retry)
                if getattr(self.engine, "lora", None) is not None:
                    self.engine.lora_unbind(slot)
                if not self._try_preempt(cand, now):
                    return
                continue
            # pin the admission-time effective priority: the aging
            # boost earned while queued persists while running, so an
            # aged-up admission cannot be instantly re-preempted by
            # the next fresh arrival of a nominally higher class
            cand._eff_priority = self.slo.effective_priority(cand, now)
            self._admit_one(slot, idx)

    def _eligible_index_slo(self, now: float) -> Optional[int]:
        """The SLO analogue of :meth:`_eligible_index`: the queue
        index of the most important request whose retry backoff has
        elapsed and whose tenant is under its concurrency quota.
        Order: effective priority desc, remaining deadline asc
        (deadline-less last), tenant virtual service asc (owed more =
        first), queue position asc (FIFO among true ties)."""
        best = best_key = None
        for i, r in enumerate(self._queue):
            if r._not_before is not None and r._not_before > now:
                continue
            if self._tenant_blocked(r):
                continue
            pri = self.slo.effective_priority(r, now)
            if r.deadline_s is not None and r._t_submit is not None:
                remaining = r._t_submit + r.deadline_s - now
            else:
                remaining = float("inf")
            served = 0.0
            if self.tenants is not None and r.tenant is not None:
                served = self.tenants.virtual_served(r.tenant)
            key = (-pri, remaining, served, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _tenant_blocked(self, r: Request) -> bool:
        """Per-tenant concurrency quota (``slo.tenant_max_share``):
        True while the tenant already occupies its share of slots —
        the request stays QUEUED (not an error) and the block lifts as
        the tenant's running requests finish. At least one slot is
        always allowed, so a quota never starves a tenant outright."""
        share = self.slo.tenant_max_share
        if share is None or r.tenant is None:
            return False
        cap = max(1, int(share * self.engine.slots))
        held = sum(1 for q in self._running
                   if q is not None and q.tenant == r.tenant)
        return held >= cap

    def _try_preempt(self, cand: Request, now: float) -> bool:
        """Preempt the lowest-priority running request strictly below
        ``cand``'s effective priority (ties broken toward the newest
        submit — least sunk wait). False when preemption is off or no
        strictly-lower victim exists (equal priority never preempts:
        that would thrash between peers)."""
        if not self.slo.preempt:
            return False
        pri = self.slo.effective_priority(cand, now)
        victim = None
        victim_key = None
        for slot, r in enumerate(self._running):
            if r is None or r.status != "running":
                # only RUNNING requests preempt: a prefilling slot has
                # no committed output state worth migrating yet, and
                # its chunk loop holds engine state this path must not
                # yank mid-ingest
                continue
            if self.slo.max_preemptions is not None \
                    and r.preemptions >= self.slo.max_preemptions:
                continue
            if len(r.prompt) + len(r.output_tokens) \
                    > self.engine.prefill_len:
                # resume replays prompt + committed outputs through
                # the fixed-shape prefill window — a decode that has
                # grown past prefill_len can no longer be re-ingested
                # exactly, so the slot is not preemptible
                continue
            vpri = r._eff_priority if r._eff_priority is not None \
                else self.slo.base_priority(r)
            if vpri >= pri:
                continue
            key = (vpri, -(r._t_submit or 0.0), -r.uid)
            if victim_key is None or key < victim_key:
                victim, victim_key = slot, key
        if victim is None:
            return False
        self._preempt(victim)
        return True

    def _preempt(self, slot: int) -> None:
        """Preempt-to-host: migrate ``slot``'s committed K/V out and
        requeue its request in the PREEMPTED state. The committed
        stream is ``prompt + outputs`` — its last token is pending
        (decode writes a token's K/V one step after sampling it), so
        the aligned export cap ``aligned(len(seq) - 1)`` is exactly
        the prefix the slot has ingested. With a host tier the pages
        ride :meth:`Engine.export_handoff` (async CRC'd swap-out under
        the request's uid — the disagg machinery, one tier up);
        without one the prefix is retained RESIDENT (COW share, freed
        by LRU pressure if the pool needs it). Either way resume is an
        ordinary admission: prefix match at the committed offset, the
        final chunk re-samples the pending position, and a greedy
        stream continues bitwise. A failed/declined export degrades to
        a cold resume (re-ingest from the prompt) — never a wrong
        token, per the PR 13 verified-miss contract."""
        r = self._running[slot]
        seq = [int(t) for t in r.prompt] + [int(t)
                                            for t in r.output_tokens]
        committed = len(seq) - 1
        cap = (committed // self.engine.chunk_len) \
            * self.engine.chunk_len
        tier = getattr(self.engine, "host_tier", None)
        pcache = self.engine.prefix_cache
        # second-cycle hygiene: a prior resume's import may have left
        # this uid's entry (and arena bytes) behind — drop both before
        # re-exporting under the same single-writer key
        if pcache.drop(r.uid) and tier is not None:
            tier.discard(r.uid)
        t0 = time.perf_counter()
        exported = 0
        try:
            if self.tracer is not None:
                with self.tracer.bind(r.uid):
                    exported = self._preempt_export(slot, r, seq, cap,
                                                    tier)
            else:
                exported = self._preempt_export(slot, r, seq, cap,
                                                tier)
        except Exception as e:  # noqa: BLE001 — containment edge
            self._count_transient()
            _logger.warning(
                "preempt export for request %d failed (%s: %s) — it "
                "will resume cold", r.uid, type(e).__name__, e)
        if exported and tier is not None:
            # resume resolves the record through the handoff seam:
            # _finish/drain release it if the request dies queued, so
            # a preempted request can never leak an arena record
            self._handoff_uids[r.uid] = r.uid
        r.status = RequestStatus.PREEMPTED
        r.preemptions += 1
        r._ingest_tokens = seq
        r._prefill_pos = 0
        r._not_before = None
        self._preempted_uids.add(r.uid)
        if self.tracer is not None:
            self.tracer.event(r.uid, "preempt", t0=t0,
                              dur=time.perf_counter() - t0, slot=slot,
                              committed=committed, exported=exported)
        # free AFTER the export: the entry holds its own page
        # refcounts (or the arena holds the bytes), so the slot's
        # release destroys nothing the resume needs
        self._free_slot(slot)
        self._queue.append(r)
        if self.registry is not None:
            self.registry.counter_inc("serving.preempt.preemptions")
        if self.auditor is not None:
            self.auditor.maybe_audit(self.engine)

    def _preempt_export(self, slot: int, r: Request, seq, cap: int,
                        tier) -> int:
        """The export half of a preemption: through the host arena
        when a tier is wired (the importer-side CRC makes corruption a
        VERIFIED miss), else a resident retention. ``keys=None``
        everywhere — the slot's stashed hash keys cover the PROMPT's
        blocks only, and ``seq`` extends past them."""
        if tier is not None:
            return self.engine.export_handoff(slot, r.uid, seq,
                                              keys=None)
        if cap <= 0:
            return 0
        outcome = self.engine.retain_prefix(slot, seq[:cap], keys=None)
        # "duplicate" is a warm resume too: the exact prefix is
        # already retained (refreshed), so the match will find it
        return cap if outcome in ("registered", "duplicate") else 0

    def _reserve_pages(self, slot: int, r: Request,
                       monolithic: bool = False) -> bool:
        """Paged admission gate: reserve ``r``'s worst-case page demand
        for ``slot`` (True on a contiguous engine — rows are
        preallocated there). Counts ``serving.pool.admit_blocked`` when
        the pool turns an admission away."""
        if not getattr(self.engine, "paged", False):
            return True
        need = self.engine.pages_required(len(r.prompt),
                                          r.max_new_tokens,
                                          monolithic=monolithic)
        ok = self.engine.try_reserve_slot(slot, need)
        if not ok and self.registry is not None:
            self.registry.counter_inc("serving.pool.admit_blocked")
        return ok

    def _ingest(self, r: Request) -> Sequence[int]:
        """The token stream admission ingests for ``r``: its prompt,
        or — resuming a preemption — prompt + committed outputs (the
        final chunk re-samples the last committed position, which IS
        the next output token, so a greedy resume continues
        bitwise)."""
        return r._ingest_tokens if r._ingest_tokens is not None \
            else r.prompt

    def _consult_prefix_cache(self, r: Request, slot: int) -> None:
        """Admission-time read path: attach the longest cached
        block-aligned prefix of ``r``'s ingest stream to ``slot`` —
        paged: share the donor's pages into the slot's table
        (copy-on-write, zero data movement, no pin needed: page
        refcounts outlive the entry); contiguous: one compiled
        row-copy with the donor entry pinned for the slot's lifetime.
        Chunk prefill then resumes at the matched offset. A miss
        changes nothing — the request prefills cold from offset 0.
        For a PREEMPTED request the stream is prompt + committed
        outputs, so the match lands exactly at the preempt-time export
        cap (warm resume) or degrades to the verified-miss cold
        re-ingest — and the resolution is counted and traced as a
        resume, not a disagg import."""
        pcache = self.engine.prefix_cache
        resume = r.uid in self._preempted_uids
        if resume:
            self._preempted_uids.discard(r.uid)
        keys = self._presubmitted_keys.pop(r.uid, None)
        if resume:
            # any presubmitted/worker hash covers the PROMPT's blocks
            # only — stale for the resumed stream; recompute inline
            keys = None
        elif keys is None and self._worker is not None:
            prompt = tuple(r.prompt)
            n_blocks = len(prompt) // pcache.block_len
            keys = self._worker.take(
                ("hash", r.uid),
                lambda: pcache.block_keys(prompt, n_blocks))
        if keys is not None:
            # registration after ingestion reuses the same keys
            self._slot_hash_keys[slot] = keys
        seq = self._ingest(r)
        m = pcache.match(seq, keys=keys)
        if m is not None:
            if getattr(self.engine, "paged", False):
                if not self.engine.attach_prefix(slot, m):
                    # hierarchical KV: the hit's host-tier bytes were
                    # missing/corrupt (the engine dropped the entry and
                    # counted serving.swap.verify_failed) or the pool
                    # was too tight to restore them — degrade to a
                    # VERIFIED MISS: nothing attached, the request
                    # prefills cold from offset 0, and the hit/miss
                    # accounting is reversed so hit_rate stays honest
                    pcache.unrecord_hit(m)
                    m = None
            else:
                self.engine.restore_prefix(slot, m.row, m.length)
                pcache.acquire(m)
                self._slot_prefix[slot] = m
        if m is not None:
            r._prefill_pos = m.length
            r.reused_tokens = m.length
        if self.registry is not None:
            if m is None:
                self.registry.counter_inc("serving.prefix.misses")
            else:
                self.registry.counter_inc("serving.prefix.hits")
                self.registry.counter_inc("serving.prefix.tokens_reused",
                                          m.length)
                self.registry.counter_inc(
                    "serving.prefix.chunks_skipped",
                    m.length // self.engine.chunk_len)
            self.registry.gauge_set("serving.prefix.hit_rate",
                                    pcache.hit_rate)
        hkey = self._handoff_uids.pop(r.uid, None) \
            if self._handoff_uids else None
        if hkey is not None:
            imported = m is not None \
                and getattr(m, "row", None) == hkey
            if not imported:
                # the handoff record went missing, corrupt or evicted
                # (or the swap-in failed its CRC — the engine dropped
                # that entry itself): VERIFIED MISS. Release any
                # dangling entry plus its arena record, then
                # re-prefill — nothing was attached, so never a wrong
                # token. When an ordinary local prefix matched instead
                # (m covers the same tokens), the unused handoff
                # record is released the same way but no re-prefill is
                # charged.
                if pcache.drop(hkey):
                    tier = getattr(self.engine, "host_tier", None)
                    if tier is not None:
                        tier.discard(hkey)
                if m is None and not resume \
                        and self.registry is not None:
                    self.registry.counter_inc(
                        "serving.disagg.reprefills")
            if self.tracer is not None and not resume:
                self.tracer.event(r.uid, "handoff_import",
                                  imported=imported,
                                  reused_tokens=0 if m is None
                                  else m.length)
        if resume:
            # the resume resolution, whichever path backed it: warm
            # (swap-in + COW at the committed offset — m.length
            # tokens) or the verified-miss cold re-ingest
            if self.registry is not None:
                self.registry.counter_inc("serving.preempt.resumes")
                if m is None:
                    self.registry.counter_inc(
                        "serving.preempt.resume_reprefills")
            if self.tracer is not None:
                self.tracer.event(r.uid, "resume", slot=slot,
                                  resumed_tokens=0 if m is None
                                  else m.length, cold=m is None)

    def _admit_monolithic(self) -> None:
        """Legacy admit (``chunked=False``): whole-prompt prefill at
        admission — the head-of-line-blocking baseline the chunked path
        is benchmarked against."""
        for slot in range(self.engine.slots):
            if self._running[slot] is not None:
                continue
            # keep filling THIS slot: a request that finishes right at
            # prefill (instant EOS / budget 1) leaves it free for the next
            while self._queue and self._running[slot] is None:
                idx = self._eligible_index(time.perf_counter())
                if idx is None:
                    return          # everything queued is backing off
                gate = self._lora_gate(slot, idx)
                if gate == "failed":
                    continue        # the queue changed: re-scan
                if gate == "blocked":
                    return          # arena rows all pinned: keep FIFO
                if not self._reserve_pages(slot, self._queue[idx],
                                           monolithic=True):
                    if getattr(self.engine, "lora", None) is not None:
                        self.engine.lora_unbind(slot)
                    return          # pool exhausted: keep FIFO, retry later
                r = self._queue[idx]
                del self._queue[idx]
                r.queue_wait_s = time.perf_counter() - r._t_queued
                if self.registry is not None:
                    self.registry.observe("serving.queue_wait_s",
                                          r.queue_wait_s)
                if self.tracer is not None:
                    tr = self.tracer
                    t_adm = tr.now()
                    tr.event(r.uid, "queue_wait",
                             t0=t_adm - r.queue_wait_s,
                             dur=r.queue_wait_s)
                    tr.event(r.uid, "admit", t0=t_adm, slot=slot,
                             reused_tokens=0,
                             pages=(self.engine.pages_required(
                                 len(r.prompt), r.max_new_tokens,
                                 monolithic=True)
                                 if getattr(self.engine, "paged",
                                            False) else 0))
                t0 = time.perf_counter()
                try:
                    token = self.engine.prefill(
                        slot, list(r.prompt), temperature=r.temperature)
                except Exception as e:  # noqa: BLE001 — containment edge
                    r.prefill_s += time.perf_counter() - t0
                    self._count_transient()
                    self._quarantine(r, slot,
                                     f"{type(e).__name__}: {e}")
                    continue
                r.prefill_s += time.perf_counter() - t0
                r.chunks += 1
                if self.tracer is not None:
                    self.tracer.event(r.uid, "prefill_chunk", t0=t0,
                                      dur=time.perf_counter() - t0,
                                      lo=0, hi=len(r.prompt),
                                      final=True)
                if not self.engine.last_prefill_finite:
                    # non-finite prompt logits: the sampled token is
                    # garbage — quarantine instead of emitting it
                    self._quarantine(r, slot,
                                     "non-finite prefill logits")
                    continue
                r.ttft_s = time.perf_counter() - r._t_submit
                if self.registry is not None:
                    self.registry.observe("serving.ttft_s", r.ttft_s)
                r.output_tokens.append(token)
                r.status = RequestStatus.RUNNING
                if self.eos_id is not None and token == self.eos_id:
                    self._finish(r, "eos")
                elif r.max_new_tokens <= 1:
                    self._finish(r, "max_new_tokens")
                elif len(r.prompt) >= self.engine.max_len:
                    # cache already full: a decode step would overwrite
                    # the last prompt position's K/V (the engine clamps
                    # its write to max_len-1) and emit a corrupted token
                    self._finish(r, "max_len")
                else:
                    self._running[slot] = r
                    self._last_tokens[slot] = token
                    self._temps[slot] = r.temperature
                if self._running[slot] is None \
                        and getattr(self.engine, "paged", False):
                    # finished right at prefill (_finish saw no slot):
                    # free the pages + leftover reservation now
                    self.engine.release_slot(slot)

    def _count_transient(self) -> None:
        if self.registry is not None:
            self.registry.counter_inc("serving.faults.transient")

    def _prefill_tick(self, tick: Optional[int] = None) -> int:
        """Run at most ``chunk_budget`` chunk-prefill steps across the
        prefilling slots, round-robin from a rotating start so no slot
        starves. Returns the number of chunks run. Each engine call is
        containment-wrapped: a transient failure (real or
        plan-injected) or a non-finite sampled row quarantines ONLY the
        slot's request — the other prefilling/decoding slots never see
        it. ``tick`` is the heartbeat index faults are keyed by (the
        same clock every injection site reads)."""
        if tick is None:
            tick = self._tick
        ran = 0
        slots = self.engine.slots
        start = self._pf_rr
        for i in range(slots):
            if ran >= self.chunk_budget:
                break
            slot = (start + i) % slots
            if self._pending_prefill[slot] is not None:
                # dispatch-ahead prefill: retire the slot's in-flight
                # chunk FIRST (its readback was deferred one visit so
                # the device executed it under this beat's host work),
                # then dispatch the next — reconcile-then-dispatch
                # keeps at most one chunk per slot in flight
                self._reconcile_prefill(slot)
            r = self._running[slot]
            if r is None or r.status != "prefilling":
                continue
            if self.role == "prefill":
                cap = ((len(r.prompt) - 1) // self.engine.chunk_len) \
                    * self.engine.chunk_len
                if r._prefill_pos >= cap:
                    # ingestion complete (every full chunk; the final
                    # partial chunk belongs to the importer, whose
                    # chunk-prefill program samples the first token):
                    # export to the arena and free the slot
                    self._export_handoff(r, slot)
                    ran += 1
                    self._pf_rr = (slot + 1) % slots
                    continue
            seq = self._ingest(r)
            lo = r._prefill_pos
            hi = min(lo + self.engine.chunk_len, len(seq))
            final = hi == len(seq)
            if self.pipeline_depth > 0:
                self._dispatch_prefill(slot, r, lo, hi, final, tick)
                ran += 1
                self._pf_rr = (slot + 1) % slots
                continue
            t0 = time.perf_counter()
            try:
                if self.fault_plan is not None:
                    self.fault_plan.maybe_raise("chunk", tick)
                token = self.engine.prefill_chunk(
                    slot, list(seq[lo:hi]), lo, r.temperature,
                    final=final)
            except Exception as e:  # noqa: BLE001 — containment edge
                r.prefill_s += time.perf_counter() - t0
                ran += 1            # the heartbeat spent its budget here
                self._pf_rr = (slot + 1) % slots
                self._count_transient()
                self._quarantine(r, slot, f"{type(e).__name__}: {e}")
                continue
            r.prefill_s += time.perf_counter() - t0
            r._prefill_pos = hi
            r.chunks += 1
            ran += 1
            if self.tracer is not None:
                self.tracer.event(r.uid, "prefill_chunk", t0=t0,
                                  dur=time.perf_counter() - t0,
                                  lo=lo, hi=hi, final=final)
            # next tick resumes AFTER the last slot served, so slots
            # separated by gaps still ingest at the same rate (a +1
            # bump would serve the slot after a gap twice as often)
            self._pf_rr = (slot + 1) % slots
            if not self.engine.last_chunk_finite:
                # non-finite logits at the sampled row: the slot's K/V
                # is suspect end-to-end — quarantine the request (the
                # mid-prompt sampled token is discarded anyway; a final
                # chunk's token would have been the request's first
                # real output, which we must not emit from NaN logits)
                self._quarantine(r, slot,
                                 "non-finite chunk-prefill logits")
                continue
            if not final:
                continue
            self._complete_prompt(r, slot, token)
        return ran

    def _complete_prompt(self, r: Request, slot: int,
                         token: int) -> None:
        """Ingestion completion (shared by the sync and dispatch-ahead
        prefill paths): register the prefix, mark the TTFT, and emit
        the sampled token through the same finish checks as every
        other token. For a fresh request the token is the FIRST output
        (the checks below reduce verbatim to the pre-SLO forms); for a
        resumed one it is the next output after the committed stream —
        TTFT was already paid and is never overwritten."""
        if self.retain_prefixes:
            if self.tracer is not None:
                # registration can evict a prefix entry, which on a
                # hierarchical-KV engine dispatches a swap-out — bind
                # so those spans attribute to this request
                with self.tracer.bind(r.uid):
                    self._register_prefix(r, slot)
            else:
                self._register_prefix(r, slot)
        if r.ttft_s is None:
            r.ttft_s = time.perf_counter() - r._t_submit
            if self.registry is not None:
                self.registry.observe("serving.ttft_s", r.ttft_s)
        r.output_tokens.append(token)
        if self.eos_id is not None and token == self.eos_id:
            self._finish(r, "eos", slot)
        elif len(r.output_tokens) >= r.max_new_tokens:
            self._finish(r, "max_new_tokens", slot)
        elif len(self._ingest(r)) >= self.engine.max_len:
            # cache already full: a decode step would overwrite the
            # last ingested position's K/V and emit a corrupted token
            self._finish(r, "max_len", slot)
        else:
            r.status = RequestStatus.RUNNING
            self._last_tokens[slot] = token

    def _dispatch_prefill(self, slot: int, r: Request, lo: int,
                          hi: int, final: bool, tick: int) -> None:
        """DISPATCH-AHEAD REGION (prefill path): issue chunk
        ``[lo, hi)`` for ``slot`` without forcing its sampled token to
        host — the chunk executes on the device while the beat's
        remaining host work runs; :meth:`_reconcile_prefill` retires it
        at the slot's next visit. Nothing in this function may force a
        device value (no ``int()`` / ``np.asarray`` /
        ``jax.device_get`` — statically linted BY NAME in
        ``tests/L0/test_serving_metrics_lint.py``)."""
        t0 = time.perf_counter()
        try:
            if self.fault_plan is not None:
                self.fault_plan.maybe_raise("chunk", tick)
            pending = self.engine.prefill_chunk_dispatch(
                slot, list(self._ingest(r)[lo:hi]), lo, r.temperature,
                final=final)
        except Exception as e:  # noqa: BLE001 — containment edge
            r.prefill_s += time.perf_counter() - t0
            self._count_transient()
            self._quarantine(r, slot, f"{type(e).__name__}: {e}")
            return
        r.prefill_s += time.perf_counter() - t0
        r._prefill_pos = hi
        self._pending_prefill[slot] = (pending, r.uid, lo, hi, t0)

    def _reconcile_prefill(self, slot: int) -> None:
        """Retire ``slot``'s dispatched-ahead prefill chunk: force its
        token, finish the chunk's accounting, and — when it was the
        prompt's final chunk — run the same completion path as the
        sync beat. A slot that churned while the chunk was in flight
        had its handle dropped by ``_free_slot`` already; the uid
        re-check here is belt-and-braces."""
        entry = self._pending_prefill[slot]
        if entry is None:
            return
        self._pending_prefill[slot] = None
        pending, uid, lo, hi, t0 = entry
        r = self._running[slot]
        if r is None or r.uid != uid or r.status != "prefilling":
            if self.registry is not None:
                self.registry.counter_inc("serving.heartbeat.discarded")
            return
        tr0 = time.perf_counter()
        try:
            token = self.engine.prefill_chunk_reconcile(pending)
        except Exception as e:  # noqa: BLE001 — containment edge
            # async backends can surface a dispatched chunk's failure
            # at its deferred force rather than at dispatch
            r.prefill_s += time.perf_counter() - tr0
            self._count_transient()
            self._quarantine(r, slot, f"{type(e).__name__}: {e}")
            return
        r.prefill_s += time.perf_counter() - tr0
        r.chunks += 1
        final = hi == len(self._ingest(r))
        if self.tracer is not None:
            self.tracer.event(r.uid, "prefill_chunk", t0=t0,
                              dur=time.perf_counter() - t0,
                              lo=lo, hi=hi, final=final)
        if not self.engine.last_chunk_finite:
            # same contract as the sync beat: non-finite logits at the
            # sampled row make the slot's K/V suspect end-to-end
            self._quarantine(r, slot, "non-finite chunk-prefill logits")
            return
        if final:
            self._complete_prompt(r, slot, token)

    # ------------------------------------------------- disaggregation
    def _export_handoff(self, r: Request, slot: int) -> None:
        """Prefill-role hand-over, at prompt-ingestion completion: land
        the slot's finished prefix in the (shared) host arena under the
        request's uid via the async CRC'd swap-out
        (:meth:`Engine.export_handoff`), roll the request back to a
        servable queued state and free the slot. The router collects
        ``(request, key, block keys)`` from :meth:`take_handoffs` once
        the record's swap-out completes and re-routes to a
        decode-capable replica. A failed export degrades to a key-less
        handoff — the decode side re-prefills cold, never a fault of
        the request (the PR 13 verified-miss contract)."""
        keys = self._slot_hash_keys[slot]
        t0 = time.perf_counter()
        exported = 0
        try:
            if self.tracer is not None:
                with self.tracer.bind(r.uid):
                    exported = self.engine.export_handoff(
                        slot, r.uid, r.prompt, keys=keys)
            else:
                exported = self.engine.export_handoff(
                    slot, r.uid, r.prompt, keys=keys)
        except Exception as e:  # noqa: BLE001 — containment edge
            self._count_transient()
            _logger.warning(
                "handoff export for request %d failed (%s: %s) — the "
                "decode side will re-prefill", r.uid,
                type(e).__name__, e)
        if self.tracer is not None:
            self.tracer.event(r.uid, "handoff_export", t0=t0,
                              dur=time.perf_counter() - t0, slot=slot,
                              exported_tokens=exported)
        self._reset_transient(r)
        r._not_before = None
        self._free_slot(slot)
        self._handoffs.append((r, r.uid if exported else None, keys))
        if self.registry is not None:
            self.registry.counter_inc("serving.disagg.handoffs")

    def take_handoffs(self) -> List[tuple]:
        """Pop every ``(request, arena_key_or_None, block_keys)``
        hand-over whose arena record is READY — its async swap-out has
        left the worker's pending set, so an importer's ``take`` can
        never race the CRC completion — or which never got a record
        (the cold handoff: short prompt, declined arena, failed
        export). Still-in-flight records stay for a later call."""
        if not self._handoffs:
            return []
        tier = getattr(self.engine, "host_tier", None)
        pending = set(tier.pending_keys()) if tier is not None \
            else set()
        ready = [h for h in self._handoffs
                 if h[1] is None or h[1] not in pending]
        if ready:
            self._handoffs = [h for h in self._handoffs
                              if h[1] is not None and h[1] in pending]
        return ready

    def note_handoff(self, uid: int, key: int) -> None:
        """Router seam (decode side): record that ``uid`` arrives with
        an arena handoff record under ``key``. Admission resolves it —
        zero re-prefill on the happy path, the VERIFIED-MISS re-prefill
        otherwise — and the resolution is counted and traced there."""
        self._handoff_uids[int(uid)] = int(key)

    def _register_prefix(self, r: Request, slot: int) -> None:
        """Write path, at prompt-ingestion completion: retain the
        prompt's block-aligned K/V prefix (now fully resident in
        ``slot``). Paged: share the slot's pages into a cache entry —
        zero copies, zero new pages (capacity pressure is the admission
        gate's job). Contiguous: one compiled row-copy into a pool row;
        a full pool evicts its LRU refcount-0 entry and a fully-pinned
        pool skips retention (graceful degradation — the request is
        unaffected)."""
        pcache = self.engine.prefix_cache
        before = pcache.evictions
        keys = self._slot_hash_keys[slot]
        # the ingest stream, not r.prompt: a resumed request ingested
        # prompt+committed-outputs, and that is the prefix now resident
        # in the slot (keys are None on resume — stored hashes covered
        # the prompt only — so the cache re-hashes inline)
        seq = self._ingest(r)
        if getattr(self.engine, "paged", False):
            outcome = self.engine.retain_prefix(slot, seq,
                                                keys=keys)
        else:
            outcome = pcache.register(
                seq,
                lambda row, length: self.engine.store_prefix(row, slot,
                                                             length),
                keys=keys)
        if self.registry is not None:
            evicted = pcache.evictions - before
            if evicted:
                self.registry.counter_inc("serving.prefix.evictions",
                                          evicted)
            if outcome == "registered":
                self.registry.counter_inc("serving.prefix.registrations")
            elif outcome == "pool_full":
                self.registry.counter_inc("serving.prefix.pool_full")
        if self.auditor is not None and pcache.evictions != before:
            # evictions release entry page refcounts: reconcile on the
            # policy's sampling cadence
            self.auditor.maybe_audit(self.engine)

    # ---------------------------------------------------------- speculative
    def _spec_tick(self, tick: int):
        """The draft → verify half of a speculative heartbeat: for each
        greedy decoding slot, prompt-lookup a draft over ``prompt +
        generated``; every slot that drafted something (and is within
        budget) then shares ONE compiled ``[slots, K+1]`` batched
        verify call (:meth:`Engine.verify_batch` — B verify-eligible
        slots per program invocation instead of B sequential calls),
        each emitting its accepted prefix plus the bonus token. Returns
        ``(verified_slots, slot_steps, emitted)``: slots that took a
        verify step this tick (excluded from the decode batch — they
        already advanced), per-SLOT verify sequence-steps run, and
        tokens emitted. Containment-wrapped exactly like chunk/decode:
        a transient failure during the shared call quarantines the
        slots that were IN it (the decode batch and prefilling slots
        never see it); a per-row non-finite verdict quarantines only
        that row's request. Slots that draft nothing, sampled requests,
        and requests within ``draft_len`` tokens of their budget (the
        padded verify window must stay inside the admission page
        reservation and ``max_len``) fall through to plain decode."""
        eng = self.engine
        cfg = eng.spec
        verified: set = set()
        calls = emitted = 0
        pending = []            # (slot, request, draft, offset)
        for slot, r in enumerate(self._running):
            if r is None or r.status != "running":
                continue
            if r.temperature != 0.0:
                continue    # acceptance verifies against argmax only
            owed = r.max_new_tokens - len(r.output_tokens)
            # the slot's committed length: everything but the pending
            # last token (which the verify step writes, like decode)
            offset = len(r.prompt) + len(r.output_tokens) - 1
            # endgame gate: require draft_len < owed, so a fully
            # accepted verify's n_accepted + 1 <= K + 1 <= owed tokens
            # ALL emit — emission never truncates, which keeps the
            # engine's tokens_generated, the bench's per-slot-step
            # arithmetic, and the padded window's page reservation all
            # exact. The last <= K tokens take plain decode.
            if cfg.draft_len >= owed \
                    or offset + cfg.draft_len + 1 > eng.max_len:
                continue
            draft = self._take_draft(r)
            if not draft:
                continue    # nothing to verify: plain-decode fallback
            pending.append((slot, r, draft, offset))
        if not pending:
            return verified, calls, emitted
        t0v = self.tracer.now() if self.tracer is not None else 0.0
        try:
            if self.fault_plan is not None:
                # the exception site raises INSTEAD of the call, so it
                # must fire before the nonfinite spec is consumed — a
                # co-scheduled nonfinite stays live for the retry
                # instead of being counted as delivered to a call that
                # never ran
                self.fault_plan.maybe_raise("verify", tick)
            bias = np.zeros(eng.slots, np.float32)
            if self.fault_plan is not None:
                for slot, _r, _d, _o in pending:
                    taken = self.fault_plan.take_nonfinite(tick, slot)
                    if taken is not None:
                        bias[slot] = taken
            # offsets= cross-checks our bookkeeping against the
            # engine's committed lengths — drift raises loudly instead
            # of silently diverging tokens (the old per-slot path's
            # guarantee, kept through the batching)
            toks, n_acc = eng.verify_batch(
                {slot: (int(self._last_tokens[slot]), draft)
                 for slot, _r, draft, _o in pending},
                fault_bias=bias,
                offsets={slot: off for slot, _r, _d, off in pending})
        except ValueError:
            # verify_batch's ValueErrors are all pre-mutation
            # validation (slot range, draft length, the offsets
            # cross-check): deterministic scheduler-vs-engine contract
            # bugs, not runtime faults — propagate loudly instead of
            # quarantining N-1 healthy batchmates over untouched
            # engine state
            raise
        except Exception as e:  # noqa: BLE001 — containment edge
            # the shared call produced no tokens: every slot that was
            # in it absorbs one retry (they share the blast radius the
            # way the decode batch shares a decode-site fault); the
            # decode batch and prefilling slots keep their progress
            self._count_transient()
            desc = f"{type(e).__name__}: {e}"
            for slot, r, _d, _o in pending:
                self._quarantine(r, slot, desc)
            return verified, calls, emitted
        # ONE batched readback per verify dispatch (the engine already
        # forces exactly once; these are host views) — the emission
        # loop below walks python ints, never per-element device reads
        toks = np.asarray(toks)
        n_acc = np.asarray(n_acc, np.int32)
        finite = eng.last_verify_finite_slots
        durv = self.tracer.now() - t0v if self.tracer is not None \
            else 0.0
        for slot, r, draft, offset in pending:
            if not finite[slot]:
                # the in-program guard flagged this row's logits: every
                # returned token is garbage — quarantine the request
                # (slot, pages, reservation freed); batchmates and the
                # decode batch never see it. Acceptance stats are NOT
                # recorded: n_accepted was argmaxed over NaN/Inf rows
                # and would pollute the acceptance histograms the
                # bench's p50/p99 read
                self._quarantine(r, slot, "non-finite verify logits")
                continue
            m = int(n_acc[slot])
            calls += 1
            r.spec_drafted += len(draft)
            r.spec_accepted += m
            if self.registry is not None:
                self.registry.counter_inc("serving.spec.drafted",
                                          len(draft))
                self.registry.counter_inc("serving.spec.accepted", m)
                self.registry.observe("serving.spec.acceptance_rate",
                                      m / len(draft))
            if self.tracer is not None:
                # one shared compiled call: every surviving row's span
                # covers the same interval, annotated per-slot
                self.tracer.event(r.uid, "verify", t0=t0v, dur=durv,
                                  slot=slot, drafted=len(draft),
                                  accepted=m)
            verified.add(slot)
            # emit the accepted prefix + bonus token through the SAME
            # per-token finish checks plain decode applies (EOS first,
            # then budget, then cache exhaustion) — the emitted stream
            # is the greedy stream, discovered several tokens per step
            # (m + 1 <= owed by the endgame gate: nothing truncates)
            for i, tok in enumerate(toks[slot, :m + 1].tolist()):
                r.output_tokens.append(tok)
                self._last_tokens[slot] = tok
                emitted += 1
                if self.eos_id is not None and tok == self.eos_id:
                    self._finish(r, "eos", slot)
                    break
                if len(r.output_tokens) >= r.max_new_tokens:
                    self._finish(r, "max_new_tokens", slot)
                    break
                if offset + i + 2 > eng.max_len:
                    # the cache position this token's successor would
                    # write at is past max_len — same check, same
                    # reason string as the decode loop
                    self._finish(r, "max_len", slot)
                    break
            else:
                # slot still running: its outputs are settled until the
                # next reconcile, so start the NEXT draft on the worker
                # now — it computes while this beat's decode dispatch
                # executes on the device
                self._presubmit_draft(r)
        return verified, calls, emitted

    def _draft_key(self, r: Request):
        """A draft job's identity: the request AND its settled output
        length — a stale precomputed draft (the slot emitted again, or
        a quarantine requeued the request) can never be taken, only
        aged out."""
        return ("draft", r.uid, len(r.output_tokens))

    def _take_draft(self, r: Request) -> list:
        """The slot's n-gram draft: the worker's precomputed result
        when one is ready (pipelined mode), else computed inline —
        byte-identical either way (``draft_tokens`` is pure)."""
        cfg = self.engine.spec
        toks = list(r.prompt) + list(r.output_tokens)
        fn = self._draft_fn(r.uid, toks, cfg)
        if self._worker is None:
            return fn()
        return self._worker.take(self._draft_key(r), fn)

    def _draft_fn(self, uid, toks, cfg):
        """The draft job closure. With a tracer attached it self-times
        and emits a ``draft`` span FROM INSIDE the closure, so the span
        lands on whichever thread actually ran the computation (the
        ``serving-draft-worker`` daemon in pipelined mode, the
        heartbeat thread inline) — honest cross-thread attribution."""
        tr = self.tracer
        if tr is None:
            return lambda: draft_tokens(toks, cfg)

        def job():
            t0 = tr.now()
            d = draft_tokens(toks, cfg)
            tr.event(uid, "draft", t0=t0, dur=tr.now() - t0,
                     drafted=len(d))
            return d
        return job

    def _presubmit_draft(self, r: Request) -> None:
        """Queue the request's next draft on the worker thread (no-op
        without one). Closes over a SNAPSHOT of prompt + outputs, so a
        concurrent host append cannot skew the computation — the key
        pins the length the snapshot was taken at."""
        if self._worker is None or r.temperature != 0.0:
            return
        cfg = self.engine.spec
        if cfg is None:
            return
        toks = list(r.prompt) + list(r.output_tokens)
        self._worker.submit(self._draft_key(r),
                            self._draft_fn(r.uid, toks, cfg))

    # ------------------------------------------------------------- stepping
    def step(self) -> bool:
        """One scheduler beat: expire → admit → chunk prefill → decode
        (``pipeline_depth >= 1``: dispatch-ahead decode with deferred
        readback — see the module docstring), every engine call
        containment-wrapped (see the fault-isolation contract), timed
        against the fault policy's watchdog budget. Returns True if any
        forward progress was made (a decode step ran or reconciled, a
        verify emitted, or a prefill chunk was ingested).

        Every beat's wall time is split into HOST-THINK vs DEVICE-WAIT
        (differencing the engine's :attr:`~apex_tpu.serving.Engine
        .device_wait_s` around the body): the ``serving.heartbeat
        .host_s`` / ``device_wait_s`` histograms and the
        ``serving.heartbeat.duty_cycle`` gauge (device-wait fraction of
        the beat). The watchdog budgets the HOST portion — a beat that
        spends its wall blocked on healthy device execution is the
        steady state, not a stall; a beat whose host think-time blows
        the budget is (under pipelining the whole point is that
        device-wait stops inflating beat wall, so budgeting wall would
        re-conflate the two)."""
        t_tick = time.perf_counter()
        tick = self._tick
        self._tick += 1
        if self.fault_plan is not None:
            # injected heartbeat stall (the watchdog-breach probe)
            self.fault_plan.maybe_stall(tick)
            tier = getattr(self.engine, "host_tier", None)
            if tier is not None:
                # injected host-arena bit rot (the swap_corruption
                # kind): the NEXT swap-in of the victim entry must
                # fail its checksum and degrade to a verified miss
                self.fault_plan.maybe_corrupt_swap(tick, tier)
                # injected handoff bit rot (the handoff_corruption
                # kind): victimizes uid-keyed handoff records only, so
                # the next IMPORT's CRC fails and degrades to the
                # verified-miss re-prefill on the decode side — never
                # a wrong token
                self.fault_plan.maybe_corrupt_handoff(tick, tier)
        compiled0 = getattr(self.engine, "compiled_programs", 0)
        dw0 = getattr(self.engine, "device_wait_s", 0.0)
        # requests riding this beat, snapshotted BEFORE the body so
        # finish/quarantine churn inside it cannot drop participants
        # (None when tracing is off — no allocation on the hot path)
        uids0 = [r.uid for r in self._running if r is not None] \
            if self.tracer is not None else None
        try:
            if self.pipeline_depth > 0:
                return self._step_body_pipelined(tick)
            return self._step_body(tick)
        finally:
            elapsed = time.perf_counter() - t_tick
            dwait = max(0.0, getattr(self.engine, "device_wait_s", 0.0)
                        - dw0)
            host_s = max(elapsed - dwait, 0.0)
            if self.tracer is not None and uids0:
                # one heartbeat span per request that rode this beat,
                # carrying the PR 11 host-think vs device-wait split —
                # attribution rides the EXISTING accounting, no new
                # forced reads
                for uid in uids0:
                    self.tracer.event(uid, "heartbeat", t0=t_tick,
                                      dur=elapsed, tick=tick,
                                      host_s=host_s,
                                      device_wait_s=dwait)
            if self.registry is not None:
                self.registry.observe("serving.heartbeat.host_s",
                                      host_s)
                self.registry.observe("serving.heartbeat.device_wait_s",
                                      dwait)
                if elapsed > 0:
                    self.registry.gauge_set(
                        "serving.heartbeat.duty_cycle", dwait / elapsed)
            if self.fault_policy.watchdog_budget_s is not None:
                if getattr(self.engine, "compiled_programs", 0) \
                        > compiled0:
                    # warm-start exemption: this heartbeat TRACED a
                    # compiled program, so its wall time is dominated
                    # by one-off compile latency, not a stall — tiny
                    # watchdog budgets must not false-trip on first
                    # contact (a dispatch-ahead beat traces at DISPATCH
                    # time, so the exemption lands on the right beat
                    # under pipelining too). Accounted separately so
                    # the compile cost stays visible instead of
                    # vanishing.
                    if self.registry is not None:
                        self.registry.observe(
                            "serving.watchdog.warmup_s", elapsed)
                elif host_s > self.fault_policy.watchdog_budget_s:
                    self._on_watchdog_breach(tick, host_s)

    def _on_watchdog_breach(self, tick: int, host_s: float) -> None:
        """A heartbeat blew its HOST-portion budget (beat wall minus
        time blocked on device results — injected stalls, runaway
        drafting and slow bookkeeping all land here; healthy device
        execution does not): count the ``serving.watchdog.stall``
        event, record the breach duration, and hand it to the policy's
        ``on_stall`` callback (alerting / shedding is the caller's
        choice — the scheduler itself keeps beating)."""
        if self.registry is not None:
            self.registry.counter_inc("serving.watchdog.stall")
            self.registry.observe("serving.watchdog.stall_s", host_s)
        _logger.warning("heartbeat %d stalled: %.3fs of host time "
                        "against a %.3fs watchdog budget", tick, host_s,
                        self.fault_policy.watchdog_budget_s)
        if self.fault_policy.on_stall is not None:
            self.fault_policy.on_stall(host_s)

    def _step_body(self, tick: int) -> bool:
        self._expire(time.perf_counter())
        self._admit()
        chunks = self._prefill_tick(tick) if self.chunked else 0
        # the chunk budget bounds the stall imposed ON in-flight
        # decodes; while nothing is decoding there is nothing to stall,
        # so keep ingesting back-to-back (cold-start/queue-drain bursts
        # reach full slot occupancy without idle heartbeats)
        while chunks and not any(r is not None and r.status == "running"
                                 for r in self._running):
            more = self._prefill_tick(tick)
            if not more:
                break
            chunks += more
        self.beats_total += 1
        if chunks:
            self.beats_with_prefill += 1
        if self.role == "prefill":
            # prefill replicas never decode: the beat is expire →
            # admit → ingest → export; finished ingestions sit in
            # _handoffs until the router collects them
            return chunks > 0
        spec_slots: set = set()
        spec_calls = spec_emitted = 0
        if self.speculative:
            # draft → verify-or-decode: verified slots already advanced
            # (possibly by several tokens) and sit out this tick's
            # decode batch; empty drafts fall through to plain decode
            spec_slots, spec_calls, spec_emitted = self._spec_tick(tick)
        active = np.array([r is not None and r.status == "running"
                           and slot not in spec_slots
                           for slot, r in enumerate(self._running)])
        self._emit_beat_gauges(active)
        if not active.any():
            self._set_spec_gauge(spec_calls, spec_emitted, 0, 0)
            return chunks > 0 or spec_calls > 0
        bias = None
        if self.fault_plan is not None:
            bias = self.fault_plan.decode_bias(tick, self.engine.slots)
        t0 = time.perf_counter()
        try:
            if self.fault_plan is not None:
                self.fault_plan.maybe_raise("decode", tick)
            tokens = self.engine.decode_step(self._last_tokens, active,
                                             self._temps,
                                             fault_bias=bias)
        except Exception as e:  # noqa: BLE001 — containment edge
            # a failed decode call produced no tokens (injected faults
            # raise INSTEAD of the call; a real mid-call failure left
            # the host token state unconsumed either way): quarantine
            # the attributed victim when the exception names one, else
            # every running request absorbs one retry — the engine
            # survives and the next beat retries the survivors
            self._count_transient()
            victim = getattr(e, "slot", -1)
            desc = f"{type(e).__name__}: {e}"
            # honor the attribution only if the victim was actually in
            # the decode batch; otherwise charge the decoding requests
            # — prefilling slots (and slots that already took a verify
            # step this tick) were not in the failed call and keep
            # their progress either way
            if 0 <= victim < self.engine.slots \
                    and victim not in spec_slots \
                    and self._running[victim] is not None \
                    and self._running[victim].status == "running":
                self._quarantine(self._running[victim], victim, desc)
            else:
                for slot, r in enumerate(self._running):
                    if r is not None and r.status == "running" \
                            and slot not in spec_slots:
                        self._quarantine(r, slot, desc)
            return True
        dt = time.perf_counter() - t0
        self._step_s_ema = dt if self._step_s_ema is None \
            else 0.8 * self._step_s_ema + 0.2 * dt
        finite = self.engine.last_decode_finite
        lengths = self.engine.lengths()
        decode_emitted = 0
        for slot, r in enumerate(self._running):
            if r is None or r.status != "running" or slot in spec_slots:
                continue
            if not finite[slot]:
                # the in-program guard flagged this slot's logits:
                # its sampled token is garbage — quarantine the slot's
                # request; batchmates' tokens are untouched (the guard
                # and the bias are per-slot, the program is shared)
                self._quarantine(r, slot, "non-finite decode logits")
                continue
            token = int(tokens[slot])
            r.output_tokens.append(token)
            self._last_tokens[slot] = token
            decode_emitted += 1
            if self.eos_id is not None and token == self.eos_id:
                self._finish(r, "eos", slot)
            elif len(r.output_tokens) >= r.max_new_tokens:
                self._finish(r, "max_new_tokens", slot)
            elif int(lengths[slot]) >= self.engine.max_len:
                # cache exhausted: the NEXT token would have nowhere to
                # attend from
                self._finish(r, "max_len", slot)
        self._set_spec_gauge(spec_calls, spec_emitted, 1, decode_emitted)
        return True

    def _emit_beat_gauges(self, active: np.ndarray) -> None:
        """Per-beat occupancy / padding-waste / paged-pool gauges over
        the decode batch's dispatch mask (shared by the sync and
        pipelined beats)."""
        if self.registry is None:
            return
        occ = float(active.mean())
        self.registry.gauge_set("serving.slot_occupancy", occ)
        self.registry.observe("serving.slot_occupancy", occ)
        self.registry.observe("serving.padding_waste", 1.0 - occ)
        if getattr(self.engine, "paged", False):
            # the paged pool's per-step health: HBM pressure
            # (pages_in_use/free), sharing efficiency (cow_shares —
            # pages serving >1 reader for one page of HBM) and
            # internal fragmentation (allocated-but-invalid slack)
            ps = self.engine.pool_stats()
            self.registry.gauge_set("serving.pool.pages_in_use",
                                    float(ps["pages_in_use"]))
            self.registry.gauge_set("serving.pool.pages_free",
                                    float(ps["pages_free"]))
            self.registry.gauge_set("serving.pool.cow_shares",
                                    float(ps["cow_shares"]))
            self.registry.gauge_set("serving.pool.fragmentation",
                                    float(ps["fragmentation"]))

    # ------------------------------------------- the pipelined heartbeat
    def _step_body_pipelined(self, tick: int) -> bool:
        """One dispatch-ahead beat (``pipeline_depth >= 1``): expire →
        admit → chunk prefill → [speculative: reconcile-all → draft →
        verify] → DISPATCH decode t+1 → RECONCILE step t (keeping at
        most ``pipeline_depth`` steps in flight). The decode dispatched
        here executes on the device while the NEXT beat's host work —
        expiry, admission, chunk bookkeeping, worker-thread drafting,
        telemetry — runs; the emitted greedy stream is bitwise the sync
        path's because every token still flows through the same
        compiled programs and the same per-token finish checks, just
        read back one batched transfer later."""
        self._expire(time.perf_counter())
        self._admit()
        chunks = self._prefill_tick(tick) if self.chunked else 0
        # cold-queue burst (same contract as the sync beat): only while
        # nothing is decoding AND nothing is in flight
        while chunks and not self._pipeline \
                and not any(r is not None and r.status == "running"
                            for r in self._running):
            more = self._prefill_tick(tick)
            if not more:
                break
            chunks += more
        self.beats_total += 1
        if chunks:
            self.beats_with_prefill += 1
        if self.role == "prefill":
            # prefill replicas never decode (dispatch-ahead applies to
            # their CHUNKS instead — _prefill_tick's reconcile-then-
            # dispatch split keeps one chunk per slot in flight)
            return chunks > 0
        spec_slots: set = set()
        spec_calls = spec_emitted = 0
        reconciled = 0
        if self.speculative:
            # drafting and the verify program need settled outputs:
            # retire everything in flight first (those flights already
            # overlapped this beat's expire/admit/chunk work), then
            # draft → verify-or-decode exactly like the sync beat
            reconciled += self._reconcile_all()
            spec_slots, spec_calls, spec_emitted = self._spec_tick(tick)
        active = self._dispatch_decode(tick, spec_slots)
        self._emit_beat_gauges(active if active is not None
                               else np.zeros(self.engine.slots, bool))
        while len(self._pipeline) > self.pipeline_depth:
            reconciled += self._reconcile_oldest()
        drained = False
        if active is None and self._pipeline:
            # nothing newly dispatched: drain the pipeline rather than
            # strand finished device work (endgame/idle beats) — and
            # count the drain as progress even when every retired step
            # was a discard (an all-discard drain still moved state)
            drained = True
            reconciled += self._reconcile_all()
        self._set_spec_gauge(spec_calls, spec_emitted, 1, reconciled)
        return (chunks > 0 or spec_calls > 0 or active is not None
                or reconciled > 0 or drained)

    def _dispatch_decode(self, tick: int,
                         spec_slots) -> Optional[np.ndarray]:
        """DISPATCH-AHEAD REGION: issue one decode step against the
        speculated schedule — every running slot presumed to continue,
        EXCEPT past host-known finality (token budget / ``max_len``
        exhaustion counting the tokens already in flight — pure
        arithmetic, so only EOS is ever mispredicted). Returns the
        dispatch mask when a step went in flight (or a contained
        dispatch fault quarantined its batch), None when there was
        nothing to dispatch.

        Nothing between here and :meth:`_reconcile_oldest` may force a
        device value to host: no ``int()`` / ``float()`` /
        ``np.asarray`` on engine results (the foot-gun this refactor
        exists to remove — statically linted by
        ``tests/L0/test_serving_metrics_lint.py``)."""
        eng = self.engine
        inflight: collections.Counter = collections.Counter()
        for rec in self._pipeline:
            for slot, uid in rec.uids.items():
                r = self._running[slot]
                if r is not None and r.uid == uid:
                    inflight[slot] += 1
        uids: Dict[int, int] = {}
        active = np.zeros(eng.slots, bool)
        for slot, r in enumerate(self._running):
            if r is None or r.status != "running" or slot in spec_slots:
                continue
            n_have = len(r.output_tokens) + inflight[slot]
            if n_have >= r.max_new_tokens:
                continue    # host-known finality: never dispatch past it
            if len(r.prompt) + n_have - 1 >= eng.max_len:
                continue    # cache exhausted once the flights land
            active[slot] = True
            uids[slot] = r.uid
        if not uids:
            return None
        bias = None
        if self.fault_plan is not None:
            bias = self.fault_plan.decode_bias(tick, eng.slots)
        try:
            if self.fault_plan is not None:
                self.fault_plan.maybe_raise("decode", tick)
            pending = eng.decode_dispatch(
                self._pipeline_last_tokens(active), active, self._temps,
                fault_bias=bias)
        except Exception as e:  # noqa: BLE001 — containment edge
            # the dispatch produced no step (injected faults raise
            # INSTEAD of the call): same blast radius as the sync
            # decode site — the attributed victim, else every request
            # in the would-be batch; in-flight steps for quarantined
            # slots discard at their reconcile by uid mismatch
            self._count_transient()
            victim = getattr(e, "slot", -1)
            desc = f"{type(e).__name__}: {e}"
            if victim in uids:
                self._quarantine(self._running[victim], victim, desc)
            else:
                for slot in sorted(uids):
                    r = self._running[slot]
                    if r is not None and r.uid == uids[slot]:
                        self._quarantine(r, slot, desc)
            return active
        self._pipeline.append(_InflightStep(pending=pending, uids=uids,
                                            tick=tick))
        return active

    def _pipeline_last_tokens(self, active: np.ndarray):
        """The dispatch's ``last_tokens`` operand: host values for
        settled slots, the NEWEST in-flight step's un-forced device
        tokens for slots whose latest token is still on the device —
        merged by one tiny device ``where`` so the data dependency
        chains decode t+1 onto t without the host ever reading a token
        (dispatch-ahead region: linted force-free)."""
        host = self._last_tokens
        if not self._pipeline:
            return host
        newest = self._pipeline[-1]
        mask = np.zeros(host.shape[0], bool)
        for slot, uid in newest.uids.items():
            r = self._running[slot]
            if r is not None and r.uid == uid and active[slot]:
                mask[slot] = True
        if not mask.any():
            return host
        return jnp.where(jnp.asarray(mask), newest.pending.tokens,
                         jnp.asarray(host))

    def _reconcile_oldest(self) -> int:
        """RECONCILE the oldest in-flight decode step: ONE batched
        token readback (never per-slot ``int()`` against device
        arrays), emission through the same per-token finish checks as
        the sync path, and the speculated-finality rollback — a slot
        whose request finished, quarantined or expired while the step
        was in flight had its entry dropped by ``_free_slot`` already
        (counted as ``serving.heartbeat.discarded``); the uid+status
        check here is belt-and-braces. Returns tokens emitted."""
        rec = self._pipeline.popleft()
        eng = self.engine
        valid = np.zeros(eng.slots, bool)
        for slot, uid in rec.uids.items():
            r = self._running[slot]
            if r is not None and r.uid == uid \
                    and r.status == "running":
                valid[slot] = True
        try:
            tokens, finite, dt = eng.decode_reconcile(rec.pending,
                                                      valid=valid)
        except Exception as e:  # noqa: BLE001 — containment edge
            # a dispatched-ahead step can fail at its DEFERRED force:
            # async backends surface runtime errors at the first read,
            # not at dispatch (the CPU backend's donated-call
            # synchronous execution hides this — errors land at the
            # wrapped dispatch site there). Same blast radius as a
            # sync decode-site fault: the attributed victim, else
            # every request the step computed for; quarantining frees
            # their slots, which drops their entries from any younger
            # in-flight records (_free_slot's eager invalidation)
            self._count_transient()
            victim = getattr(e, "slot", -1)
            desc = f"{type(e).__name__}: {e}"
            if 0 <= victim < eng.slots and valid[victim]:
                self._quarantine(self._running[victim], victim, desc)
            else:
                for slot in sorted(rec.uids):
                    if valid[slot]:
                        self._quarantine(self._running[slot], slot,
                                         desc)
            return 0
        self._step_s_ema = dt if self._step_s_ema is None \
            else 0.8 * self._step_s_ema + 0.2 * dt
        emitted = discarded = 0
        for slot in sorted(rec.uids):
            if not valid[slot]:
                discarded += 1
                continue
            r = self._running[slot]
            if not finite[slot]:
                # the in-program guard flagged this slot's logits (same
                # quarantine as the sync beat); any younger in-flight
                # step for it discards at ITS reconcile by uid mismatch
                self._quarantine(r, slot, "non-finite decode logits")
                continue
            token = int(tokens[slot])
            r.output_tokens.append(token)
            self._last_tokens[slot] = token
            emitted += 1
            if self.eos_id is not None and token == self.eos_id:
                self._finish(r, "eos", slot)
            elif len(r.output_tokens) >= r.max_new_tokens:
                self._finish(r, "max_new_tokens", slot)
            elif len(r.prompt) + len(r.output_tokens) - 1 \
                    >= eng.max_len:
                # committed length (prompt + outputs - 1) reached the
                # cache — the same condition the sync beat reads back
                # from engine.lengths(), computed host-side here so
                # reconcile forces nothing beyond the token readback
                self._finish(r, "max_len", slot)
            elif self.speculative:
                # outputs settled until the next reconcile: start the
                # next draft on the worker now, overlapping the device
                self._presubmit_draft(r)
        if discarded and self.registry is not None:
            self.registry.counter_inc("serving.heartbeat.discarded",
                                      discarded)
        return emitted

    def _reconcile_all(self) -> int:
        """Retire every in-flight step, oldest first (the speculative
        beat's settle point and the endgame drain)."""
        emitted = 0
        while self._pipeline:
            emitted += self._reconcile_oldest()
        return emitted

    def _set_spec_gauge(self, spec_calls: int, spec_emitted: int,
                        decode_steps: int, decode_emitted: int) -> None:
        """The headline speculative gauge: tokens emitted this
        heartbeat per SLOT sequence-step run — a decode step advances
        each participating slot by exactly one (so plain decode pins
        the gauge at 1.0), a verify call is one slot-step that emits
        ``n_accepted + 1``; acceptance is the only thing that pushes
        the reading above 1. Only emitted on speculative runs."""
        del decode_steps            # a slot-step count, not a dispatch count
        if not self.speculative or self.registry is None:
            return
        steps = spec_calls + decode_emitted
        if steps:
            self.registry.gauge_set(
                "serving.spec.tokens_per_step",
                (spec_emitted + decode_emitted) / steps)

    @property
    def pending(self) -> int:
        """Queued + running request count, plus one while any
        dispatched-ahead decode step is still awaiting reconcile (the
        drain target: ``step()`` until 0 leaves nothing behind — not
        even in-flight device work, so the LAST request's EOS cannot
        strand its speculated successors un-discarded)."""
        n = len(self._queue) + sum(r is not None
                                   for r in self._running) \
            + len(self._handoffs)
        if self._pipeline:
            n += 1
        return n

    # ----------------------------------------------------- router seams
    def load_snapshot(self) -> dict:
        """One HOST-ONLY load reading for this scheduler+engine pair —
        the :class:`~apex_tpu.serving.Router`'s least-loaded admission
        signal, taken per routed request. Everything here is host
        bookkeeping (queue/slot walks, the paged allocator's free
        count, the host arena's byte ledger); nothing forces a device
        value, so probing N replicas per submit costs microseconds,
        not syncs. ``pages_free`` is None on a contiguous engine (rows
        are preallocated — slot occupancy is the whole capacity story
        there); ``host_bytes_free`` is None without a hierarchical-KV
        host tier — when present it is the swap arena's remaining
        headroom, so the router's least-loaded tie-break sees arena
        pressure (a replica about to shed swapped prefixes), not just
        device pages.

        Two SLO-aware fields (both None when ``slo`` is off, so the
        pre-SLO snapshot shape is a strict subset):

        - ``oldest_deadline_s``: seconds until the TIGHTEST live
          deadline (queued or running), negative once blown, None when
          no live request carries one. Reported RELATIVE because
          ``perf_counter`` bases do not cross processes — the fleet
          controller compares urgency, not wall clocks.
        - ``preemptible_pages``: pages held by RUNNING requests whose
          effective priority is strictly below the config's top class
          AND whose committed stream still fits the prefill re-ingest
          window (a decode past ``prefill_len`` is no longer exactly
          resumable, so it is never a victim) — the headroom a
          top-class arrival could reclaim by preemption. None on a
          contiguous engine (no pages to count).
        """
        busy = sum(r is not None for r in self._running)
        tier = getattr(self.engine, "host_tier", None)
        oldest = None
        preemptible = None
        if self.slo is not None:
            now = time.perf_counter()
            live = [r for r in self._running if r is not None]
            live.extend(self._queue)
            for r in live:
                if r.deadline_s is None or r._t_submit is None:
                    continue
                rem = r._t_submit + r.deadline_s - now
                if oldest is None or rem < oldest:
                    oldest = rem
            if getattr(self.engine, "paged", False):
                top = self.slo.top_priority
                preemptible = 0
                for slot, r in enumerate(self._running):
                    if r is None or r.status != RequestStatus.RUNNING:
                        continue
                    if len(r.prompt) + len(r.output_tokens) \
                            > self.engine.prefill_len:
                        # mirrors _try_preempt: past the re-ingest
                        # window the slot is not exactly resumable
                        continue
                    pri = r._eff_priority if r._eff_priority is not None \
                        else self.slo.base_priority(r)
                    if pri < top:
                        preemptible += self.engine.slot_pages(slot)
        return {
            "queue_depth": len(self._queue),
            "queue_free": self.max_queue - len(self._queue),
            "slots": self.engine.slots,
            "slots_busy": busy,
            "slots_free": self.engine.slots - busy,
            "inflight_steps": len(self._pipeline),
            "pages_free": self.engine.pages_free
            if getattr(self.engine, "paged", False) else None,
            "host_bytes_free": None if tier is None
            else tier.capacity_bytes - tier.bytes_used,
            "oldest_deadline_s": oldest,
            "preemptible_pages": preemptible,
            # adapter affinity: the names resident in the device
            # arena (a bind is a hit, not a swap-in), None when LoRA
            # serving is off
            "resident_adapters": self.engine.resident_adapters()
            if getattr(self.engine, "lora", None) is not None else None,
        }

    def drain_requests(self) -> List[Request]:
        """Export every live request — running slots first (admission
        order), then the queue FIFO — rolled back to a servable queued
        state (:meth:`_reset_transient`: outputs cleared, paid-compute
        counters and the original submit clock kept, retry backoff
        cleared so survivors re-admit immediately), with every slot
        freed through the normal quarantine path: pages, reservations
        and prefix pins go back to the pool NOW and any dispatched-
        ahead steps are discarded, so a drained engine audits with
        zero leaked pages. This is the replica-death seam: the router
        calls it on a dead replica and requeues the result on
        survivors — a drain is NOT a fault of the requests, so
        ``retries`` is untouched. The scheduler itself stays
        constructed (its ``completed`` history and telemetry survive);
        pair with :meth:`close` to stop the worker thread."""
        drained: List[Request] = []
        for slot, r in enumerate(self._running):
            if r is None:
                continue
            self._free_slot(slot)   # pages + reservation + prefix pin
            self._reset_transient(r)
            r._not_before = None
            drained.append(r)
        # any in-flight dispatch-ahead steps lost their uids to
        # _free_slot above; drop the empty records (their device work
        # is never reconciled — the dead engine's results are garbage)
        self._pipeline.clear()
        # uncollected handoffs: nobody will ever import them — release
        # each one's cache entry and arena record (complete() tolerates
        # a record discarded mid-flight) and requeue the request
        tier = getattr(self.engine, "host_tier", None)
        for r, key, _keys in self._handoffs:
            if key is not None:
                if self.engine.prefix_cache.drop(key) \
                        and tier is not None:
                    tier.discard(key)
            self._reset_transient(r)
            r._not_before = None
            drained.append(r)
        self._handoffs = []
        # decode-side mirror: noted-but-not-yet-admitted imports also
        # orphan their entry + record when this replica drains (the
        # router re-routes the request through a fresh prefill)
        for key in self._handoff_uids.values():
            if self.engine.prefix_cache.drop(key) \
                    and tier is not None:
                tier.discard(key)
        self._handoff_uids.clear()
        while self._queue:
            r = self._queue.popleft()
            self._reset_transient(r)
            r._not_before = None
            drained.append(r)
        for r in drained:
            # the router re-routes (and re-probes) on a survivor: this
            # scheduler's stashed hash keys are dead weight
            self._presubmitted_keys.pop(r.uid, None)
        return drained

    def close(self) -> None:
        """Stop the scheduler's :class:`~apex_tpu.serving.DraftWorker`
        thread (no-op at ``pipeline_depth=0``; idempotent — the
        weakref finalizer registered at construction runs the same
        stop)."""
        if self._worker is not None:
            self._worker.stop()

    def _sleep_toward_backoff(self) -> None:
        """When nothing occupies a slot and everything queued is inside
        a retry-backoff window, sleep toward the earliest horizon
        (capped at 50 ms per wait) instead of burning CPU — and the
        caller's step budget — on no-op heartbeats."""
        if any(r is not None for r in self._running):
            return
        now = time.perf_counter()
        horizon = min((r._not_before for r in self._queue
                       if r._not_before is not None
                       and r._not_before > now), default=None)
        if horizon is not None:
            time.sleep(min(horizon - now, 0.05))

    # ---------------------------------------------------------------- runs
    def run(self, requests: Sequence[Request] = (),
            max_steps: int = 100000) -> List[Request]:
        """Submit ``requests`` (stepping through :class:`QueueFull`
        backpressure rather than surfacing it) and drain until every
        request finishes. Returns them in completion order and records
        the run's ``serving.tokens_per_s`` gauge."""
        t0 = time.perf_counter()
        tok0 = self.engine.tokens_generated
        done0 = len(self.completed)
        for r in requests:
            while True:
                try:
                    self.submit(r)
                    break
                except QueueFull:
                    # a step admits queued work into slots (and decodes),
                    # freeing queue capacity — backpressure absorbed here
                    if not self.step():
                        if not self._queue:
                            raise    # nothing active yet queue full
                        self._sleep_toward_backoff()
        steps = 0
        while self.pending and steps < max_steps:
            if not self.step():
                self._sleep_toward_backoff()
            steps += 1
        dt = time.perf_counter() - t0
        toks = self.engine.tokens_generated - tok0
        if self.registry is not None and dt > 0:
            self.registry.gauge_set("serving.tokens_per_s", toks / dt)
        _logger.info("served %d request(s): %d tokens in %.3fs "
                     "(%.1f tok/s)", len(self.completed) - done0, toks,
                     dt, toks / dt if dt > 0 else float("inf"))
        return self.completed[done0:]
