"""apex_tpu.serving — compiled KV-cache inference with continuous batching.

The training stack (amp cast policies, Pallas attention, telemetry)
stops at the optimizer step; this subsystem opens the inference
workload the north star calls for — serving a stream of variable-length
generation requests from a fixed set of compiled programs:

- :class:`PagedKVCache` + :class:`PagePool` (:mod:`.kv_cache`) — the
  DEFAULT cache layout: a dense ``[layers, num_pages, heads, page_len,
  head_dim]`` page pool plus a host-side allocator (free list, page
  refcounts, admission reservations). Requests own page lists, not
  rows: short prompts stop paying ``max_len`` HBM, freed pages return
  to the pool immediately, and prefix hits are copy-on-write page
  shares (refcount bump — zero data movement). :class:`KVCache` keeps
  the original contiguous per-slot-row layout as the parity oracle and
  measurable baseline (``Engine(paged=False)``).
- :class:`Engine` (:mod:`.engine`) — exactly THREE XLA executables on
  the paged path (jitted chunk-prefill + decode step + the legacy
  monolithic prefill baseline, each gathering K/V through a
  ``[slots, max_pages]`` page-table operand; traced offset/length/
  temperature scalars), four on the contiguous path (+ the prefix KV
  row-copy, retired from the paged hit path); greedy / temperature /
  top-k sampling compiled in; attention through the ``decode.*``-tuned
  kernels of :mod:`apex_tpu.kernels.decode_attention` /
  :mod:`apex_tpu.kernels.prefill_attention` and their ``paged_*``
  page-table variants.
- :class:`PrefixCache` (:mod:`.prefix_cache`) — content-addressed
  prompt-prefix reuse: retained prefixes keyed by a rolling hash over
  ``chunk_len``-aligned token blocks. Paged: entries record the page
  ids already holding the prefix (registration and hits are refcount
  bumps; LRU eviction under pool pressure only). Contiguous: entries
  own ``prefix_pool`` cache rows with refcount pinning + LRU eviction,
  hits restored by one row-copy. Both skip ``matched_len / chunk_len``
  chunks of prefill compute, token-exact vs. the cold path.
- :class:`Scheduler` (:mod:`.scheduler`) — continuous batching with
  chunked prefill fused into the decode heartbeat: admit-into-free-slots,
  at most ``chunk_budget`` compiled chunk-prefill steps per tick (so
  in-flight decodes never wait more than one chunk for a new admit),
  EOS/max-token/timeout eviction, bounded-queue :class:`QueueFull`
  backpressure, opt-in prefix retention (``retain_prefixes=True``:
  consult-on-admit, register-on-prefill-completion), and slot-occupancy
  / padding-waste / decomposed-TTFT / chunks-per-prompt /
  ``serving.prefix.*`` / tokens-per-sec telemetry through the shared
  :class:`~apex_tpu.telemetry.MetricsRegistry`.

- :class:`SpecConfig` / :func:`draft_tokens` (:mod:`.speculative`) —
  speculative decoding fused into the heartbeat: a host-side
  prompt-lookup / n-gram drafter proposes up to K next tokens per
  greedy slot, ONE compiled ``[slots, K+1]`` BATCHED verify program
  (:meth:`Engine.verify_batch` — the chunk-append machinery at the
  draft shape; every verify-eligible slot shares one invocation per
  heartbeat) scores them all in a single step, and in-program
  accept-longest-prefix keeps greedy output bitwise identical to
  plain decode while lifting tokens-per-step above 1
  (``Scheduler(speculative=True)``; rejected-tail K/V never becomes
  visible — rollback is a host/length decrement).

- :mod:`.sharding` — tensor-parallel serving (``Engine(mesh=...)``,
  paged only): a ``match_partition_rules``-style rule table over the
  TransformerLM pytree plus shard_map-wrapped engine programs. The KV
  pool shards along the heads axis so attention never crosses ICI;
  the only collectives are two psums per transformer block plus one
  all-gather of the sampled logits rows (the tied head runs
  vocab-parallel). ``mesh=None`` stays the verbatim single-chip
  baseline, pinned bitwise against a ``tp=1`` mesh.

- :class:`KVQuantConfig` (:mod:`.kv_quant`) / :class:`WeightQuantConfig`
  (:mod:`.weight_quant`) — the int8 storage tiers over the two dominant
  HBM-resident populations, sharing one symmetric-quant core
  (:mod:`.quant_common`): the KV pool stores int8 with per-``[layer,
  head]`` scales dequantized inside the attention kernels (~2x
  concurrency at the same pool bytes), and the serving weights store
  int8 with per-output-channel scales dequantized in each GEMM's
  epilogue (~2x model-size headroom vs bf16). Both are params/cache
  properties, not programs — zero new executables, token-match-rate
  contracts vs the bf16 oracle, and the ``None`` defaults stay the
  bitwise baselines.

- :class:`FaultPlan` / :class:`FaultPolicy` / :class:`PoolAuditor`
  (:mod:`.faults`) — fault isolation: a seeded deterministic
  chaos-injection harness (non-finite logits into chosen decode slots,
  transient call-boundary exceptions, heartbeat stalls, replica deaths
  at the router tier, debug-copy page-table corruption), the
  scheduler's always-on containment policy (per-slot non-finite
  quarantine, requeue with capped exponential backoff → typed
  ``FAILED``, heartbeat watchdog), and an O(pages) page-pool invariant
  auditor that raises loudly on leaked or double-freed pages.
  Un-faulted greedy requests stay bitwise identical to a fault-free
  run; containment adds ZERO compiled programs.

- :class:`HostTier` / :class:`SwapWorker` (:mod:`.host_tier`) —
  hierarchical KV (``Engine(host_tier=<bytes>)``, paged +
  ``prefix_pool > 0``; composes with ``mesh=``): a bounded host-DRAM
  arena behind the page pool. A prefix entry evicted under pool
  pressure has its page bytes migrated device→host (int8 under
  ``kv_quant`` — half the transfer) instead of being destroyed — by
  default ASYNCHRONOUSLY: the admission path only dispatches a
  fixed-shape compiled gather (the snapshot rides program order) and
  a worker thread forces/checksums/stores the bytes off the hot path,
  the entry staying matchable in the *swapping* → *swapped* states (a
  hit racing its own swap JOINS the copy; ``sync_swap=True`` is the
  measurable inline baseline). A later hit migrates the bytes back
  through the other fixed-shape compiled program (a page-block
  scatter) before copy-on-write sharing as usual; under a mesh both
  swap programs shard over the pool's heads axis with ZERO
  collectives and arena records carry per-shard CRCs. CRC-verified:
  a corrupt/missing swap-in degrades to a verified miss (re-prefill),
  never a wrong token — hit-after-swap greedy streams are bitwise
  identical to never-swapped ones, async or sync, and prefix capacity
  is bounded by host RAM, not HBM.

- :class:`Router` (:mod:`.router`) — replica-parallel serving (tp × dp
  scale-out): N ``Scheduler``+``Engine`` replicas behind one
  host-side ``submit()`` that routes by PREFIX AFFINITY (one set of
  rolling block hashes probes every replica's cache read-only; the
  request lands where its K/V already lives) with least-loaded
  admission as the fallback (free slots / queue depth / free pool
  pages from :meth:`Scheduler.load_snapshot`), cross-replica
  backpressure (a full replica is a spill to the next-best; QueueFull
  only when the whole fleet is saturated, ``retry_after_s`` = max of
  replica hints), and replica-death containment: a dead replica's
  requests drain (:meth:`Scheduler.drain_requests`) and re-route onto
  survivors with zero leaked pages — un-faulted requests stay bitwise.
  Zero compiled programs added; ``serving.router.*`` telemetry.

- :class:`FleetController` (:mod:`.fleet` / :mod:`.fleet_worker`) —
  the Router's fleet, OUT-OF-PROCESS: each replica is a separate OS
  process (``python -m apex_tpu.serving.fleet_worker``) owning its
  own JAX runtime, engine and telemetry registry, behind a
  length-prefixed stdlib AF_UNIX transport. The controller reuses the
  Router's exact decision core (:mod:`.routing_policy` — shared pure
  functions, so in-process and process fleets route identically and
  the parity pin is bitwise) over serialized probes and
  :func:`snapshot_to_wire` load snapshots; requests and disagg arena
  records cross as versioned wire forms (:func:`request_to_wire`,
  :func:`record_to_wire` — handoffs travel BY VALUE and re-verify by
  CRC on the importing arena). Health heartbeats with a missed-beat
  death detector (the ``worker_hang`` fault kind), ROLLING restart
  (drain → respawn → rejoin warm), and elastic
  ``add_replica``/``remove_replica``/``set_role`` under live traffic.
  ``serving.fleet.*`` telemetry; per-worker registries merge into one
  fleet view.

- :class:`LoRAConfig` / :class:`LoRAManager` (:mod:`.lora`) —
  multi-tenant LoRA serving (``Engine(lora=LoRAConfig(...))``):
  thousands of fine-tunes batched on ONE base engine. Each adapter is
  a per-site low-rank pair folded into the four serving GEMMs as an
  epilogue term (``acc + (x @ A) @ B · α``) gathered from a stacked
  device arena by a TRACED per-slot adapter-index operand — adapter
  identity is data, not a trace key, so a heterogeneous-adapter batch
  decodes in one compiled invocation and the program-count pins do
  not move. Adapters hot-load/evict through a bounded HostTier-style
  host store (LRU, refcount pinning while any slot is bound, CRC
  verification on swap-in — a corrupt record fails LOUDLY, never
  decodes wrong tokens); ``Request.adapter`` routes with
  resident-adapter affinity next to prefix affinity on both routing
  fronts; under a mesh the arena shards on the PR-9 rule table's
  axes (A column-split, B row-split) so the existing per-block psums
  restore the sum — zero new collectives. ``lora=None`` (and a
  LoRA engine with no adapter bound) stays the BITWISE base engine
  on the same executables.

- :class:`SLOConfig` / :class:`TenantLedger` (:mod:`.slo`) —
  SLO-aware preemptive scheduling (``Scheduler(slo=SLOConfig(...))``):
  priority classes (``Request.slo_class`` / ``priority``), preempt-
  lowest under admission pressure — the victim's committed pages
  migrate device→host through the existing async swap path (or stay
  resident as a retained prefix) and the request resumes later via
  swap-in + COW prefix-share at the committed offset, BITWISE
  identical to its uninterrupted greedy run; queue-aging starvation
  bounds, per-tenant slot quotas + weighted-fair token accounting
  (one shared ledger across the in-process Router's replicas),
  deadline-aware admission (:class:`DeadlineUnmeetable` with an
  honest EMA-derived ``retry_after_s``), and SLO-aware fleet routing
  (``preemptible_pages`` headroom in :mod:`.routing_policy`, ranked
  identically by Router and FleetController). ``slo=None`` stays the
  verbatim FIFO baseline — zero new compiled programs either way.

Quick start::

    from apex_tpu import serving
    from apex_tpu.models.transformer_lm import create_lm

    model = create_lm("small", vocab_size=32768, max_seq_len=512)
    engine = serving.Engine(model, params, slots=8, max_len=512,
                            prefill_len=128)
    sched = serving.Scheduler(engine, eos_id=0)
    done = sched.run([serving.Request(prompt=[17, 23, 5],
                                      max_new_tokens=64)])
    generated = done[0].output_tokens

Exercised end-to-end by ``bench_serving.py`` and
``examples/lm/main_amp.py --generate``.
"""

from . import routing_policy, sharding
from .engine import Engine, PendingDecode, sample_tokens
from .faults import (FaultPlan, FaultPolicy, FaultSpec, InjectedFault,
                     PoolAuditor, PoolInvariantError, fault_kind)
from .fleet import FleetController, WorkerDied
from .host_tier import (HostTier, SwapWorker, record_from_wire,
                        record_to_wire)
from .kv_cache import KVCache, PagedKVCache, PagePool
from .kv_quant import KVQuantConfig
from .lora import LoRAConfig, LoRAManager
from .prefix_cache import PrefixCache, PrefixMatch
from .router import Router
from .scheduler import (DeadlineUnmeetable, QueueFull, Request,
                        RequestStatus, Scheduler,
                        request_from_wire, request_to_wire,
                        snapshot_from_wire, snapshot_to_wire)
from .slo import SLOConfig, TenantLedger
from .speculative import DraftWorker, SpecConfig, draft_tokens
from .weight_quant import WeightQuantConfig

__all__ = ["DeadlineUnmeetable", "DraftWorker", "Engine", "FaultPlan",
           "FaultPolicy",
           "FaultSpec", "FleetController", "HostTier", "InjectedFault",
           "KVCache", "KVQuantConfig", "LoRAConfig", "LoRAManager",
           "PagedKVCache", "PagePool",
           "PendingDecode", "PoolAuditor", "PoolInvariantError",
           "PrefixCache", "PrefixMatch", "QueueFull", "Request",
           "RequestStatus", "Router", "SLOConfig", "Scheduler",
           "SpecConfig",
           "SwapWorker", "TenantLedger", "WeightQuantConfig",
           "WorkerDied",
           "draft_tokens", "fault_kind", "record_from_wire",
           "record_to_wire", "request_from_wire", "request_to_wire",
           "routing_policy", "sample_tokens", "sharding",
           "snapshot_from_wire", "snapshot_to_wire"]
