"""apex_tpu.serving — compiled KV-cache inference with continuous batching.

The training stack (amp cast policies, Pallas attention, telemetry)
stops at the optimizer step; this subsystem opens the inference
workload the north star calls for — serving a stream of variable-length
generation requests from a fixed set of compiled programs:

- :class:`KVCache` (:mod:`.kv_cache`) — preallocated
  ``[layers, slots, heads, max_len, head_dim]`` slot cache with
  per-slot lengths, stored in the amp half dtype.
- :class:`Engine` (:mod:`.engine`) — exactly four XLA executables
  (jitted chunk-prefill + jitted decode step + the legacy monolithic
  prefill baseline + the prefix-reuse KV row-copy, fixed shapes, traced
  slot/offset/length/temperature scalars), greedy / temperature / top-k
  sampling compiled in; decode attention through
  :func:`apex_tpu.kernels.decode_attention.decode_attention` and chunk
  attention through
  :func:`apex_tpu.kernels.prefill_attention.prefill_attention`
  (length-masked, ``decode.*`` tuned-block keys).
- :class:`PrefixCache` (:mod:`.prefix_cache`) — content-addressed
  prompt-prefix reuse: retained prefixes keyed by a rolling hash over
  ``chunk_len``-aligned token blocks, held in ``prefix_pool`` cache
  rows with refcount pinning + LRU eviction; an admission hit restores
  the longest cached prefix by one row-copy and skips
  ``matched_len / chunk_len`` chunks of prefill compute, bitwise
  token-exact vs. the cold path.
- :class:`Scheduler` (:mod:`.scheduler`) — continuous batching with
  chunked prefill fused into the decode heartbeat: admit-into-free-slots,
  at most ``chunk_budget`` compiled chunk-prefill steps per tick (so
  in-flight decodes never wait more than one chunk for a new admit),
  EOS/max-token/timeout eviction, bounded-queue :class:`QueueFull`
  backpressure, opt-in prefix retention (``retain_prefixes=True``:
  consult-on-admit, register-on-prefill-completion), and slot-occupancy
  / padding-waste / decomposed-TTFT / chunks-per-prompt /
  ``serving.prefix.*`` / tokens-per-sec telemetry through the shared
  :class:`~apex_tpu.telemetry.MetricsRegistry`.

Quick start::

    from apex_tpu import serving
    from apex_tpu.models.transformer_lm import create_lm

    model = create_lm("small", vocab_size=32768, max_seq_len=512)
    engine = serving.Engine(model, params, slots=8, max_len=512,
                            prefill_len=128)
    sched = serving.Scheduler(engine, eos_id=0)
    done = sched.run([serving.Request(prompt=[17, 23, 5],
                                      max_new_tokens=64)])
    generated = done[0].output_tokens

Exercised end-to-end by ``bench_serving.py`` and
``examples/lm/main_amp.py --generate``.
"""

from .engine import Engine, sample_tokens
from .kv_cache import KVCache
from .prefix_cache import PrefixCache, PrefixMatch
from .scheduler import QueueFull, Request, Scheduler

__all__ = ["Engine", "KVCache", "PrefixCache", "PrefixMatch", "QueueFull",
           "Request", "Scheduler", "sample_tokens"]
