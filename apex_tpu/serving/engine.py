"""Compiled inference engine: one prefill program, one decode program.

The engine owns the two — and exactly two — XLA executables a serving
process needs, both traced once at fixed shapes:

- **prefill**: ``[1, prefill_len]`` tokens (prompt right-padded) → the
  model's full causal forward (``return_kv=True``), prompt K/V written
  into one cache slot, first token sampled from the logits at the true
  prompt's last position. Slot index, prompt length, temperature and the
  PRNG key are *traced* scalars, so requests of any length or slot land
  in the same executable — no per-request recompiles.
- **decode step**: ``[slots, 1]`` tokens (every slot's latest token) →
  single-token cached forward, one new token per slot. Inactive slots
  compute too (their output is discarded and their length frozen) —
  that padding waste is the price of a fixed-shape program, and the
  scheduler reports it.

Sampling runs inside the compiled programs: greedy when a slot's
temperature is 0, else temperature softmax over logits optionally
truncated to the engine's static ``top_k``. Temperatures are per-slot
traced values; ``top_k`` is static (a different ``top_k`` is a new
engine).

Weights are cast ONCE at construction through the amp cast-policy
machinery (default: pure-half O3 — bf16 storage, no fp32 masters, the
cache in the same dtype); pass ``policy=amp.resolve_policy("O0")`` for
an exact-fp32 engine (the decode-parity tests' configuration).

Trace accounting: the python bodies of both programs run only when jax
traces them, so ``prefill_traces``/``decode_traces`` count compiles —
the serving test tier pins both to exactly 1 across a multi-request,
variable-length run.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.kernels import vmem
from apex_tpu.log_util import get_logger

from .kv_cache import KVCache

__all__ = ["Engine", "sample_tokens"]

_logger = get_logger("serving")


def sample_tokens(logits, temperature, key, top_k: int = 0):
    """Sample one token per row of ``logits`` [N, V] (inside jit).

    ``temperature`` [N]: 0 → greedy (argmax), > 0 → softmax sampling at
    that temperature. ``top_k`` (static): when > 0, logits outside each
    row's top-k are masked before sampling. Greedy rows ignore top_k
    (argmax is already top-1)."""
    logits = jnp.asarray(logits, jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


class Engine:
    """KV-cache inference engine over a ``TransformerLM``-shaped model.

    Parameters
    ----------
    model:
        A flax module with the cache-threading contract of
        :class:`apex_tpu.models.transformer_lm.TransformerLM`
        (``return_kv`` prefill, ``cache``/``positions`` decode) and the
        geometry attributes ``num_layers``/``num_heads``/``hidden``/
        ``max_seq_len``.
    params:
        The model's parameter pytree (e.g. a train state's params).
        Cast once through ``policy.cast_params`` — by default to the
        pure-half O3 shape.
    slots:
        Concurrent sequences per decode step (the continuous-batching
        width).
    max_len:
        Cache positions per slot (prompt + generation budget); must not
        exceed the model's ``max_seq_len``.
    prefill_len:
        Fixed padded prompt capacity of the prefill program
        (``<= max_len``). Longer prompts are rejected at submit time.
    policy:
        An :class:`apex_tpu.amp.Policy` governing weight/cache storage;
        default ``resolve_policy("O3", verbose=False)`` (pure bf16).
    top_k:
        Static top-k truncation for sampled (non-greedy) slots; 0 = off.
    registry:
        Optional :class:`apex_tpu.telemetry.MetricsRegistry`; when set,
        the engine observes ``serving.decode.step_s`` and
        ``serving.prefill.s`` latencies and counts generated tokens.

    Prefill attention geometry honours the tuned-override registry keys
    ``decode.prefill_block_q``/``decode.prefill_block_k`` (0/absent →
    the flash kernel's own ``flash.*`` resolution).
    """

    def __init__(self, model, params, *, slots: int, max_len: int,
                 prefill_len: Optional[int] = None, policy=None,
                 top_k: int = 0, seed: int = 0, registry=None):
        from apex_tpu.amp.policy import resolve_policy

        if policy is None:
            policy = resolve_policy("O3", verbose=False)
        self.policy = policy
        half = policy.compute_dtype
        max_seq = int(getattr(model, "max_seq_len", max_len))
        if max_len > max_seq:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"max_seq_len {max_seq}")
        if prefill_len is None:
            prefill_len = max_len
        if not 0 < prefill_len <= max_len:
            raise ValueError(f"prefill_len {prefill_len} must be in "
                             f"(0, max_len={max_len}]")
        if slots < 1:
            raise ValueError("slots must be >= 1")
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.top_k = int(top_k)
        # pin the eval dtype on the module itself so decode GEMMs and
        # the cache agree (pure-half: no fp32 masters anywhere)
        try:
            self._model = model.clone(inference_dtype=half)
        except TypeError:  # model without the inference_dtype field
            self._model = model
        self.params = policy.cast_params(params)
        hidden = int(model.hidden)
        heads = int(model.num_heads)
        self.cache = KVCache.create(
            layers=int(model.num_layers), slots=self.slots, heads=heads,
            max_len=self.max_len, head_dim=hidden // heads, dtype=half)
        self._registry = registry
        self._key = jax.random.PRNGKey(seed)
        self.prefill_traces = 0
        self.decode_traces = 0
        self.tokens_generated = 0
        # prefill flash-attention geometry: decode.* tuned keys beat the
        # training sweep's flash.* defaults when present
        self._pf_bq = vmem.get_override("decode.prefill_block_q", 0,
                                        multiple=8) or None
        self._pf_bk = vmem.get_override("decode.prefill_block_k", 0,
                                        multiple=128) or None
        self._jit_prefill = jax.jit(self._prefill_impl,
                                    donate_argnums=(1,))
        self._jit_decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        _logger.info(
            "serving engine: %d slots x %d positions, prefill_len=%d, "
            "cache %s (%.1f MiB), top_k=%d", self.slots, self.max_len,
            self.prefill_len, np.dtype(half).name,
            self.cache.nbytes() / 2**20, self.top_k)

    # ------------------------------------------------------ compiled bodies
    def _prefill_impl(self, params, cache, tokens, length, slot,
                      temperature, key):
        self.prefill_traces += 1    # python body runs at trace time only
        logits, (k_new, v_new) = self._model.apply(
            {"params": params}, tokens, train=False, return_kv=True)
        cache = cache.insert(slot, k_new, v_new, length)
        last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                            keepdims=False)        # [V]
        token = sample_tokens(last[None], temperature[None], key,
                              self.top_k)[0]
        return cache, token

    def _decode_impl(self, params, cache, last_tokens, active,
                     temperature, key):
        self.decode_traces += 1     # python body runs at trace time only
        positions = jnp.minimum(cache.lengths, self.max_len - 1)
        logits, (k2, v2) = self._model.apply(
            {"params": params}, last_tokens[:, None], train=False,
            cache=cache.model_view(), positions=positions)
        tokens = sample_tokens(logits[:, 0, :], temperature, key,
                               self.top_k)
        return cache.advance(k2, v2, active), tokens

    # ------------------------------------------------------------- host API
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill(self, slot: int, prompt: Sequence[int],
                temperature: float = 0.0) -> int:
        """Prefill ``prompt`` into ``slot`` and return the first sampled
        token (host int). Blocks until the token is on the host — the
        time-to-first-token boundary."""
        n = len(prompt)
        if not 0 < n <= self.prefill_len:
            raise ValueError(f"prompt length {n} not in (0, "
                             f"prefill_len={self.prefill_len}]")
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} not in [0, {self.slots})")
        tokens = np.zeros((1, self.prefill_len), np.int32)
        tokens[0, :n] = np.asarray(prompt, np.int32)
        t0 = time.perf_counter()
        self.cache, token = self._with_prefill_blocks(
            lambda: self._jit_prefill(
                self.params, self.cache, jnp.asarray(tokens), np.int32(n),
                np.int32(slot), np.float32(temperature), self._next_key()))
        token = int(token)
        if self._registry is not None:
            self._registry.observe("serving.prefill.s",
                                   time.perf_counter() - t0)
            self._registry.counter_inc("serving.prefill.calls")
            self._registry.counter_inc("serving.tokens_generated")
        self.tokens_generated += 1
        return token

    def _with_prefill_blocks(self, fn):
        """Run ``fn`` with the ``decode.prefill_block_q``/``_k`` tuned
        keys temporarily installed as the flash-attention geometry.
        Blocks resolve at TRACE time, so this bites exactly once — on
        the call that traces the prefill program — and the training
        ``flash.*`` values are restored before anything else traces."""
        if self._pf_bq is None and self._pf_bk is None:
            return fn()
        keys = ("flash.block_q", "flash.block_k")
        saved = {k: vmem.overrides().get(k) for k in keys}
        for k, v in zip(keys, (self._pf_bq, self._pf_bk)):
            if v:
                vmem.set_override(k, v)
        try:
            return fn()
        finally:
            for k in keys:
                if saved[k] is None:
                    vmem.remove_override(k)
                else:
                    vmem.set_override(k, saved[k])

    def decode_step(self, last_tokens, active, temperatures) -> np.ndarray:
        """One decode step over every slot: ``last_tokens`` [slots] int
        (each slot's most recent token), ``active`` [slots] bool,
        ``temperatures`` [slots] float. Returns the next token per slot
        (host int32 array; inactive rows are noise to discard)."""
        t0 = time.perf_counter()
        self.cache, tokens = self._jit_decode(
            self.params, self.cache,
            jnp.asarray(last_tokens, jnp.int32),
            jnp.asarray(active, bool),
            jnp.asarray(temperatures, jnp.float32), self._next_key())
        out = np.asarray(tokens)            # device sync: step latency
        n_active = int(np.sum(np.asarray(active, bool)))
        self.tokens_generated += n_active
        if self._registry is not None:
            dt = time.perf_counter() - t0
            self._registry.observe("serving.decode.step_s", dt)
            self._registry.counter_inc("serving.decode.steps")
            self._registry.counter_inc("serving.tokens_generated",
                                       n_active)
        return out

    def lengths(self) -> np.ndarray:
        """Host view of per-slot cache lengths."""
        return np.asarray(self.cache.lengths)

    def set_registry(self, registry) -> None:
        """Swap the telemetry registry (e.g. after a compile-warmup pass,
        so first-trace latency never poisons the serving histograms)."""
        self._registry = registry

    def reset(self) -> None:
        """Zero the cache lengths (slot table wipe; K/V left in place —
        length masking makes stale data unreachable)."""
        self.cache = self.cache.replace(
            lengths=jnp.zeros((self.slots,), jnp.int32))
