"""Compiled inference engine: chunk-prefill, decode, prefill (+ KV copy).

Two cache layouts share this one class:

- **paged** (the default): a dense pool of fixed-size pages
  (:class:`~apex_tpu.serving.PagedKVCache`) addressed through per-slot
  page tables (:class:`~apex_tpu.serving.PagePool` host allocator).
  THREE compiled programs — chunk prefill, decode, monolithic prefill —
  each taking a ``[.., max_pages]`` int32 page-table operand next to
  the tokens; lengths live host-side. Prefix reuse is copy-on-write:
  a hit SHARES the donor's pages (refcount bump, zero data movement),
  so the fourth program of the contiguous layout — the KV row copy —
  is retired from the hit path and never compiles here.
- **contiguous** (``paged=False``): the original per-slot-row layout,
  kept verbatim as the paged path's parity oracle and the measurable
  baseline — exactly as the monolithic prefill is kept inside the
  chunked scheduler. Its program set is the original four.

The contiguous engine owns the four — and exactly four — XLA
executables a serving process needs, each traced once at fixed shapes:

- **chunk prefill** (the scheduler's ingestion path): ``[1, chunk_len]``
  tokens (one chunk of a prompt, right-padded on the final partial
  chunk) → the model's chunked-prefill forward against ONE cache slot
  (:meth:`KVCache.slot_view`), K/V written at ``[offset, offset +
  chunk_len)``, shifted-causal attention over the slot's existing
  prefix, a token sampled from the last *valid* row (the request's
  first token when the chunk is final; discarded otherwise). Slot,
  offset, valid-count, temperature and the PRNG key are *traced*
  scalars — every chunk of every prompt lands in this one executable,
  and the scheduler runs at most one between decode steps, so in-flight
  decodes never wait more than one chunk for a new admit.
- **decode step**: ``[slots, 1]`` tokens (every slot's latest token) →
  single-token cached forward, one new token per slot. Inactive slots
  compute too (their output is discarded and their length frozen) —
  that padding waste is the price of a fixed-shape program, and the
  scheduler reports it.
- **monolithic prefill** (legacy/baseline): ``[1, prefill_len]`` tokens
  → full causal forward (``return_kv=True``), whole prompt in one call.
  Kept as the chunked path's bitwise-parity oracle and the
  head-of-line-blocking baseline (``Scheduler(chunked=False)``,
  ``bench_serving.py --mixed-prompts``); it stalls every active decode
  slot for the full prompt, which is exactly what chunking removes.
- **KV row copy** (prefix reuse): donor slot → destination slot via
  dynamic slices (the :meth:`KVCache.slot_view`/:meth:`KVCache
  .write_slot` pattern), traced source/destination/length scalars. One
  program serves both directions of content-addressed prompt caching —
  registering a completed prefix into a pool row and restoring a
  matched prefix into a freshly admitted slot — after which the
  remaining suffix flows through the *existing* chunk-prefill program
  starting at the matched offset, skipping ``matched_len / chunk_len``
  chunks of attention+MLP compute outright.

Sampling runs inside the compiled programs: greedy when a slot's
temperature is 0, else temperature softmax over logits optionally
truncated to the engine's static ``top_k``. Temperatures are per-slot
traced values; ``top_k`` is static (a different ``top_k`` is a new
engine).

Every sampling program also carries the **non-finite guard**: an
``all(isfinite)`` reduction over the fp32 logits row(s) it samples
from, returned per slot so the host (the scheduler's fault policy) can
quarantine a NaN/Inf slot while its batchmates keep their exact tokens
— fused into the existing executables, zero new programs. The decode
and chunk programs additionally take a ``fault_bias`` logit-offset
operand (all-zero in production — adding +0.0 to an fp32 row is
value-identical — NaN/Inf under a
:class:`~apex_tpu.serving.FaultPlan`, which makes the guard fire on
real non-finite logits). Verdicts land in
:attr:`Engine.last_decode_finite` / :attr:`Engine.last_chunk_finite` /
:attr:`Engine.last_prefill_finite` and count
``serving.faults.nonfinite``.

**Speculative verify** (``spec=SpecConfig(...)``): one more compiled
program — a BATCHED ``[slots, K+1]`` draft-and-verify step built on the
chunk-append machinery, the same fixed-shape discipline as decode:
every verify-eligible slot shares ONE program invocation per heartbeat
(instead of B sequential single-slot calls), and slots not verifying
ride along as padding whose cache bytes are provably untouched (paged:
their table-row operand is zeroed so writes land on the sentinel page;
contiguous: their rows are masked back to their prior bytes
in-program). The host drafts K tokens per slot (prompt-lookup n-gram —
see :mod:`apex_tpu.serving.speculative`), the program embeds each
row's ``[last_token, d_1 .. d_K]`` at that slot's current offset,
writes their K/V (paged: per-position scatters — ``unaligned_append``;
contiguous: the ordinary offset chunk write), runs shifted-causal
attention, and computes ACCEPT-LONGEST-PREFIX *in-program* per row:
greedy target ``g_s``, ``n_accepted`` = the longest run with
``d_i == g_{i-1}``. The emitted tokens ``g_0 .. g_m`` are the
program's own greedy targets, so greedy output is token-identical to
plain decode by construction. The rejected tail's K/V is written but
NEVER visible: lengths are what gate attention, and the contiguous
program sets each verifying slot's length to ``offset + n_accepted +
1`` itself (the paged host does the same to its host-side lengths) —
rollback is a length decrement, no cache mutation to undo; the stale
positions are overwritten write-then-attend before anything can attend
them (the same contract inactive-slot decode writes already live by).
One executable serves every draft/offset/slot combination AND the
single-slot :meth:`Engine.verify_step` wrapper (``verify_traces`` pins
it); a fused per-row isfinite guard + per-slot ``fault_bias`` operand
give chaos the same grip it has on every other program
(:attr:`Engine.last_verify_finite_slots`).

**Async dispatch** (the pipelined heartbeat's engine half): the decode
step is split into :meth:`Engine.decode_dispatch` — enqueue the
compiled call and return a :class:`PendingDecode` whose sampled tokens
stay ON DEVICE — and :meth:`Engine.decode_reconcile` — one batched
readback per step, where emission accounting and the finiteness
verdict land. ``decode_dispatch`` accepts a previous pending step's
un-forced token array as its ``last_tokens``, so decode step t+1
chains onto step t entirely on the device; :meth:`Engine.decode_step`
is the two halves back-to-back (the depth-0 sync oracle — same
program, same operands, same bytes). Every site that blocks on the
runtime — forced reads (token readback, finite flags), the
:meth:`Engine.sync` barrier, and the compiled calls themselves
(:meth:`Engine._runtime_call`: the CPU backend executes
donated-buffer programs synchronously inside dispatch, so the call's
block time IS device execution there; on silicon async dispatch makes
it ~µs) — charges its block time to :attr:`Engine.device_wait_s`,
which the scheduler differences per heartbeat into the
``serving.heartbeat.*`` host-think / device-wait split.

**Tensor parallelism** (``mesh=...``, paged only): the same programs,
shard_map'd over a 1-D tensor-parallel mesh axis
(:mod:`apex_tpu.serving.sharding`). Params split per a
``match_partition_rules`` table (qkv/MLP-up column-parallel, proj/
MLP-down row-parallel, embeddings replicated), the KV pool shards
along the HEADS axis (``[layers, num_pages, heads/tp, page_len,
head_dim]`` per shard) so attention never crosses ICI, and the only
collectives are the two canonical TP all-reduces per block
(post-attention, post-MLP) plus ONE all-gather of the sampled logits
rows (the tied head computes vocab/tp slices per shard) — 2 psums per
block + 1 gather per program, pinned from compiled HLO. ``mesh=None``
(the default) is the verbatim single-chip baseline — none of the
sharding code is on its trace path — and a ``tp=1`` mesh is pinned
bitwise against it on a greedy stream.

Weights are cast ONCE at construction through the amp cast-policy
machinery (default: pure-half O3 — bf16 storage, no fp32 masters, the
cache in the same dtype); pass ``policy=amp.resolve_policy("O0")`` for
an exact-fp32 engine (the decode-parity tests' configuration).

Trace accounting: the python bodies of the programs run only when jax
traces them, so ``chunk_traces``/``decode_traces``/``prefill_traces``/
``copy_traces`` count compiles — the serving test tier pins the
contiguous engine to exactly four compiled programs across a
multi-request, variable-length, hit/miss/evict run that exercises all
four paths, and the paged engine to exactly THREE across the same
stream (copy-on-write sharing is host bookkeeping, not a program).

Paged-mode host bookkeeping (all numpy, no device work):

- ``page_len`` positions per page (``decode.page_len`` tuned key,
  degraded to divide ``chunk_len`` — chunk writes must cover whole
  pages so shared pages are never written);
- a ``[slots, max_pages]`` page table mirrored to the device as an
  operand of every call; page 0 is the sentinel the fixed-shape decode
  program's inactive-slot writes land on;
- worst-case page **reservation** at admission
  (:meth:`Engine.try_reserve_slot` — the scheduler's admit gate), so an
  admitted request can always grow to its token budget: pool pressure
  queues requests, evicts LRU prefix entries, and ultimately surfaces
  as submit-side ``QueueFull`` — never a mid-decode failure;
- prefix retention/hits as page sharing (:meth:`Engine.retain_prefix` /
  :meth:`Engine.attach_prefix`) with refcounts in the
  :class:`~apex_tpu.serving.PagePool`.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
import weakref
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.kernels import vmem
from apex_tpu.log_util import get_logger

from .host_tier import HostTier, SwapWorker
from .kv_cache import KVCache, PagedKVCache, PagePool
from .kv_quant import KVQuantConfig, quantize
from .prefix_cache import PrefixCache
from .speculative import SpecConfig
from .weight_quant import WeightQuantConfig

__all__ = ["Engine", "PendingDecode", "resolve_page_len",
           "sample_tokens"]

_logger = get_logger("serving")


def resolve_page_len(chunk_len: int, page_len: Optional[int] = None) -> int:
    """The paged engine's page-size resolution, exposed so external
    sizers (``bench_serving.paged_capacity_stats``) compute pool
    geometry with the SAME value the constructor will: an explicit
    ``page_len`` must divide ``chunk_len`` (chunk writes must cover
    whole pages — the copy-on-write invariant); the default is the
    ``decode.page_len`` tuned key, else ``min(chunk_len, 128)``,
    degraded to the largest common divisor of ``chunk_len``."""
    chunk_len = int(chunk_len)
    if page_len is None:
        page_len = vmem.get_override("decode.page_len", 0) \
            or min(chunk_len, 128)
        if chunk_len % page_len:
            page_len = math.gcd(page_len, chunk_len)
    page_len = int(page_len)
    if page_len < 1 or chunk_len % page_len:
        raise ValueError(
            f"page_len {page_len} must divide chunk_len {chunk_len} "
            f"(chunk writes must cover whole pages — a partially-"
            f"written shared page would break copy-on-write)")
    return page_len


def sample_tokens(logits, temperature, key, top_k: int = 0):
    """Sample one token per row of ``logits`` [N, V] (inside jit).

    ``temperature`` [N]: 0 → greedy (argmax), > 0 → softmax sampling at
    that temperature. ``top_k`` (static): when > 0, logits outside each
    row's top-k are masked before sampling. Greedy rows ignore top_k
    (argmax is already top-1)."""
    logits = jnp.asarray(logits, jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if top_k and top_k > 0 and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


@dataclasses.dataclass
class PendingDecode:
    """One dispatched-but-unread decode step — the handle the async
    pipelined heartbeat holds between :meth:`Engine.decode_dispatch`
    and :meth:`Engine.decode_reconcile`.

    ``tokens`` / ``finite`` are DEVICE arrays: touching them with
    ``int()`` / ``float()`` / ``np.asarray`` forces the host to wait
    for the step — exactly the stall dispatch-ahead execution exists to
    remove — so nothing reads them until reconcile (the scheduler lint
    in ``tests/L0/test_serving_metrics_lint.py`` enforces this on the
    dispatch region). ``active`` is the host-side dispatch mask (who
    the step computed for) and ``t_dispatch`` the dispatch timestamp,
    so reconcile can observe the full dispatch→retire latency as
    ``serving.decode.step_s`` (in sync mode reconcile follows dispatch
    immediately and the reading degenerates to today's measurement)."""

    tokens: Any                 # [slots] int32, ON DEVICE until reconcile
    finite: Any                 # [slots] bool, ON DEVICE until reconcile
    active: np.ndarray          # [slots] bool, host dispatch mask
    t_dispatch: float
    reconciled: bool = False


@dataclasses.dataclass
class PendingPrefill:
    """One dispatched-but-unread chunk-prefill step — the prefill-path
    twin of :class:`PendingDecode`, held by the scheduler between
    :meth:`Engine.prefill_chunk_dispatch` and
    :meth:`Engine.prefill_chunk_reconcile` so chunk ``t+1`` can be
    issued before chunk ``t``'s sampled token is forced to host.

    ``token`` / ``finite`` are DEVICE scalars until reconcile; the
    force-early lint covers the dispatch half by name, exactly like the
    decode region. ``final`` and the timestamps are host bookkeeping so
    reconcile can finish the chunk's counters identically to the
    synchronous path."""

    token: Any                  # scalar int32, ON DEVICE until reconcile
    finite: Any                 # scalar bool, ON DEVICE until reconcile
    slot: int
    final: bool
    t_dispatch: float
    dispatch_s: float
    reconciled: bool = False


class Engine:
    """KV-cache inference engine over a ``TransformerLM``-shaped model.

    Parameters
    ----------
    model:
        A flax module with the cache-threading contract of
        :class:`apex_tpu.models.transformer_lm.TransformerLM`
        (``return_kv`` prefill, ``cache``/``positions`` decode) and the
        geometry attributes ``num_layers``/``num_heads``/``hidden``/
        ``max_seq_len``.
    params:
        The model's parameter pytree (e.g. a train state's params).
        Cast once through ``policy.cast_params`` — by default to the
        pure-half O3 shape.
    slots:
        Concurrent sequences per decode step (the continuous-batching
        width).
    max_len:
        Cache positions per slot (prompt + generation budget); must not
        exceed the model's ``max_seq_len``.
    prefill_len:
        Fixed padded prompt capacity of the prefill programs
        (``<= max_len``). Longer prompts are rejected at submit time.
    chunk_len:
        Tokens per chunk-prefill step (default ``min(prefill_len,
        256)``). Smaller chunks bound the stall a prefill imposes on
        in-flight decodes more tightly but pay more per-chunk overhead;
        lane-aligned values (multiples of 128) keep the chunk kernel on
        its Pallas path.
    policy:
        An :class:`apex_tpu.amp.Policy` governing weight/cache storage;
        default ``resolve_policy("O3", verbose=False)`` (pure bf16).
    prefix_pool:
        Cache rows reserved past the serving slots for content-addressed
        prompt-prefix reuse (0 = off). When > 0 the engine allocates
        ``slots + prefix_pool`` rows, compiles the fourth (KV row-copy)
        program lazily on first use, and exposes a
        :class:`~apex_tpu.serving.PrefixCache` as ``prefix_cache``
        (consulted by ``Scheduler(retain_prefixes=True)``). The decode
        batch stays ``[slots, 1]`` — pool rows are never computed over.
    paged:
        True (default) = paged pool + page-table indirection (three
        compiled programs, copy-on-write prefix sharing); False = the
        original contiguous per-slot-row layout (four programs, prefix
        reuse by compiled row copy) — kept as the parity oracle and
        measurable baseline.
    page_len:
        Positions per page (paged only). Default: the ``decode.page_len``
        tuned key, else ``min(chunk_len, 128)``, degraded to the largest
        common divisor of ``chunk_len`` — a page is the unit of sharing
        and must be covered whole by every chunk write. An explicit
        value that does not divide ``chunk_len`` is rejected.
    num_pages:
        Physical pool pages INCLUDING the page-0 sentinel (paged only).
        Default: ``(slots + prefix_pool) * ceil(max_len / page_len) + 1``
        — the same HBM the contiguous layout would spend on full-length
        rows; size it down for denser sharing or up for more retained
        prefixes.
    spec:
        A :class:`~apex_tpu.serving.SpecConfig` enabling the
        speculative-verify program (``draft_len`` fixes its
        ``[slots, K+1]`` compiled shape — one batched invocation serves
        every verify-eligible slot per heartbeat). None (the default)
        compiles nothing extra and leaves today's program set
        untouched; the program itself traces lazily on the first
        :meth:`verify_batch` / :meth:`verify_step`.
    mesh:
        A 1-D :class:`jax.sharding.Mesh` enabling tensor-parallel
        serving (paged only): every compiled program runs shard_map'd
        over the mesh axis with params split per the
        :mod:`~apex_tpu.serving.sharding` rule table and the KV pool
        sharded along heads (``heads % tp == 0`` enforced, as are the
        MLP-inner and vocab splits). ``mesh=None`` (the default) is
        the verbatim single-chip engine.
    kv_quant:
        A :class:`~apex_tpu.serving.KVQuantConfig` turning on the
        quantized cache STORAGE tier (works on both layouts, composes
        with prefix sharing, speculative verify and ``mesh=``): K/V are
        stored as int8 with per-``[layer, head]`` fp32 scales carried
        in the cache pytree — halving pool HBM, so the same bytes hold
        ~2x the slots/pages — cache writes quantize in-program and the
        attention kernels dequantize in-kernel. Scales are calibrated
        (or given) at construction; degenerate calibration (absmax 0 /
        non-finite) raises HERE. Greedy output becomes a
        token-match-rate claim vs the bf16 oracle
        (``bench_serving.py --quantized-kv``); ``kv_quant=None`` (the
        default) is the bitwise bf16 baseline — none of the quant code
        is on its trace path. The program set is unchanged either way
        (dequant is fused, never a new executable).
    weight_quant:
        A :class:`~apex_tpu.serving.WeightQuantConfig` turning on the
        quantized WEIGHT storage tier (both layouts; composes with
        ``kv_quant``, prefix sharing, speculative verify, the async
        heartbeat, ``host_tier`` and ``mesh=``): the big serving GEMM
        kernels — qkv, proj, MLP in/out, and the tied vocab head — are
        stored int8 with per-output-channel fp32 scales, and dequant
        is the scale multiply folded onto each GEMM's accumulator in
        the epilogue (:mod:`~apex_tpu.serving.weight_quant`). Roughly
        halves weight HBM vs bf16; together with ``kv_quant`` the two
        dominant resident allocations both shrink. A params property,
        not a program — the compiled-program set and every trace-count
        pin are unchanged. Calibration is the per-channel absmax of
        the (policy-cast) weights themselves, resolved HERE with the
        loud degenerate-channel failure; under a mesh the scales shard
        with their kernels per the partition-rule table. Greedy output
        becomes a token-match-rate claim vs the bf16 oracle
        (``bench_serving.py --quantized-weights``);
        ``weight_quant=None`` (the default) is the bitwise baseline —
        none of the quant code is on its trace path.
    host_tier:
        Hierarchical-KV host-DRAM prefix tier (paged only, requires
        ``prefix_pool > 0``; composes with ``mesh=``): an int capacity
        in BYTES, or a pre-built :class:`~apex_tpu.serving.HostTier`.
        When set, a prefix entry evicted under pool pressure has its
        page bytes migrated device→host into the bounded arena instead
        of being destroyed (int8 under ``kv_quant`` — half the
        transfer bytes). Swap-out is ASYNCHRONOUS by default: the
        admission path only DISPATCHES a fixed-shape compiled gather
        (``swap_out`` — the pool-byte snapshot is taken at dispatch,
        before the freed pages can be reused) and hands the un-forced
        device blocks to a :class:`~apex_tpu.serving.SwapWorker`
        thread, which forces, checksums and stores them off the hot
        path; the entry sits matchable in the *swapping* state
        meanwhile, and a hit racing its own swap-out JOINS the
        in-flight copy (never reads partial bytes). A later hit
        migrates the bytes back through the other compiled program
        (``swap_in``: a fixed-shape page-block scatter, one dispatch
        per swap-in — no attention, no sampling, no PRNG) before
        copy-on-write sharing as usual. Restored pages are byte-exact
        (per-shard CRC-verified; a corrupt/missing swap-in degrades to
        a verified miss and a re-prefill, never a wrong token), so a
        hit-after-swap greedy stream is bitwise identical to a
        never-swapped one — asynchronously or not — and prefix
        capacity is bounded by host RAM instead of device HBM. Under
        a ``mesh`` both swap programs run shard_map'd with the pool's
        heads-axis sharding — each shard gathers/scatters its own
        ``heads/tp`` slice, ZERO collectives (pure data movement) —
        and arena records carry one CRC per shard. ``None`` (default)
        keeps today's destroy-on-evict behaviour and traces nothing
        extra.
    sync_swap:
        Escape hatch (``host_tier`` only): True forces the PRE-ASYNC
        behaviour — swap-out forces the gathered bytes to host and
        stores them inline on the admission path (no worker thread).
        The emitted token streams are bitwise identical either way
        (pinned); the hatch exists for debugging and as the bench's
        measurable baseline (``serving.swap.admit_stall_s`` sync vs
        async is the admission-stall claim).
    top_k:
        Static top-k truncation for sampled (non-greedy) slots; 0 = off.
    registry:
        Optional :class:`apex_tpu.telemetry.MetricsRegistry`; when set,
        the engine observes ``serving.decode.step_s`` and
        ``serving.prefill.s`` latencies and counts generated tokens.

    Prefill attention geometry honours the tuned-override registry keys
    ``decode.prefill_block_q``/``decode.prefill_block_k`` (0/absent →
    the flash kernel's own ``flash.*`` resolution).
    """

    def __init__(self, model, params, *, slots: int, max_len: int,
                 prefill_len: Optional[int] = None,
                 chunk_len: Optional[int] = None, policy=None,
                 prefix_pool: int = 0, top_k: int = 0, seed: int = 0,
                 registry=None, paged: bool = True,
                 page_len: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 spec: Optional[SpecConfig] = None, mesh=None,
                 kv_quant: Optional[KVQuantConfig] = None,
                 weight_quant: Optional[WeightQuantConfig] = None,
                 host_tier=None, sync_swap: bool = False, lora=None):
        from apex_tpu.amp.policy import resolve_policy

        if policy is None:
            policy = resolve_policy("O3", verbose=False)
        self.policy = policy
        half = policy.compute_dtype
        max_seq = int(getattr(model, "max_seq_len", max_len))
        if max_len > max_seq:
            raise ValueError(f"max_len {max_len} exceeds the model's "
                             f"max_seq_len {max_seq}")
        if prefill_len is None:
            prefill_len = max_len
        if not 0 < prefill_len <= max_len:
            raise ValueError(f"prefill_len {prefill_len} must be in "
                             f"(0, max_len={max_len}]")
        if slots < 1:
            raise ValueError("slots must be >= 1")
        if chunk_len is None:
            chunk_len = min(int(prefill_len), 256)
            if -(-int(prefill_len) // chunk_len) * chunk_len > max_len:
                # the defaulted geometry must always be servable: when
                # the rounded-up window would spill past the cache
                # (prefill_len just over a chunk multiple with little
                # decode headroom), degrade to single-chunk ingestion
                chunk_len = int(prefill_len)
        if not 0 < chunk_len <= prefill_len:
            raise ValueError(f"chunk_len {chunk_len} must be in "
                             f"(0, prefill_len={prefill_len}]")
        # every chunk writes a full chunk_len-wide K/V slice (the final
        # partial chunk is padded), so the LAST chunk's window must fit
        # the cache: otherwise the model's position clip would silently
        # relocate the write over earlier prompt K/V (cache corruption,
        # not an error). Reject the geometry loudly at construction.
        n_chunks = -(-int(prefill_len) // int(chunk_len))
        if n_chunks * int(chunk_len) > max_len:
            raise ValueError(
                f"chunk_len {chunk_len}: the final chunk window "
                f"[{(n_chunks - 1) * chunk_len}, {n_chunks * chunk_len})"
                f" of a prefill_len={prefill_len} prompt exceeds "
                f"max_len={max_len}; pick a chunk_len with "
                f"ceil(prefill_len/chunk_len)*chunk_len <= max_len")
        if prefix_pool < 0:
            raise ValueError("prefix_pool must be >= 0")
        if spec is not None:
            if not isinstance(spec, SpecConfig):
                raise TypeError(f"spec must be a SpecConfig, got "
                                f"{type(spec).__name__}")
            if spec.draft_len + 1 > max_len:
                raise ValueError(
                    f"spec.draft_len {spec.draft_len}: a verify step "
                    f"writes draft_len + 1 = {spec.draft_len + 1} "
                    f"positions, which cannot fit max_len={max_len}")
        self.spec = spec
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.prefill_len = int(prefill_len)
        self.chunk_len = int(chunk_len)
        self.prefix_pool = int(prefix_pool)
        self.top_k = int(top_k)
        hidden = int(model.hidden)
        heads = int(model.num_heads)
        layers = int(model.num_layers)
        head_dim = hidden // heads
        # quantized-cache storage tier (independent of the COMPUTE half
        # dtype the policy picks): int8 K/V with per-[layer, head] fp32
        # scales, resolved HERE so a degenerate calibration (absmax 0 /
        # non-finite) is a loud construction error, never NaN output.
        # Calibration runs on the caller's uncast model/params — absmax
        # estimation does not need the serving dtype's rounding.
        self.kv_quant = kv_quant
        if kv_quant is not None:
            if not isinstance(kv_quant, KVQuantConfig):
                raise TypeError(f"kv_quant must be a KVQuantConfig, "
                                f"got {type(kv_quant).__name__}")
            k_scale, v_scale = kv_quant.resolve_scales(
                model, params, layers=layers, heads=heads)
            cache_dtype = jnp.dtype(kv_quant.dtype)
        else:
            k_scale = v_scale = None
            cache_dtype = half
        # quantized WEIGHT storage tier (independent of both the
        # compute half dtype and the cache tier): int8 GEMM kernels
        # with per-output-channel fp32 scales, dequantized in the
        # matmul epilogue. A params property, not a program — the
        # compiled-program set and every trace-count pin are unchanged.
        self.weight_quant = weight_quant
        if weight_quant is not None \
                and not isinstance(weight_quant, WeightQuantConfig):
            raise TypeError(f"weight_quant must be a WeightQuantConfig, "
                            f"got {type(weight_quant).__name__}")
        self.mesh = mesh
        if mesh is not None:
            from . import sharding as _sharding

            if not paged:
                raise ValueError(
                    "Engine(mesh=...) requires paged=True: the sharded "
                    "programs gather K/V through the heads-sharded page "
                    "pool; the contiguous layout stays the single-chip "
                    "parity oracle/baseline")
            self._tp_axis = _sharding.tp_axis_of(mesh)
            self.tp = int(np.prod(mesh.devices.shape))
            _sharding.validate_tp_geometry(
                self.tp, num_heads=heads, hidden=hidden,
                mlp_ratio=int(getattr(model, "mlp_ratio", 4)),
                vocab_size=int(model.vocab_size))
        else:
            self._tp_axis = None
            self.tp = 1
        # pin the eval dtype on the module itself so decode GEMMs and
        # the cache agree (pure-half: no fp32 masters anywhere); under a
        # mesh also pin the tensor-parallel shard geometry (the module
        # becomes one Megatron-style shard inside shard_map)
        clone_kw = {"inference_dtype": half}
        if mesh is not None:
            clone_kw.update(tp_axis=self._tp_axis, tp_size=self.tp)
        if weight_quant is not None:
            clone_kw["weight_quant"] = True
        try:
            self._model = model.clone(**clone_kw)
        except TypeError:  # model without the inference_dtype field
            # diagnose by the field actually missing — a tp-capable
            # model lacking only weight_quant (or vice versa) must be
            # told about ITS gap, not the other feature's
            fields = set(getattr(type(model), "__dataclass_fields__",
                                 ()))
            if mesh is not None \
                    and not {"tp_axis", "tp_size"} <= fields:
                raise TypeError(
                    "Engine(mesh=...) needs a model with tp_axis/"
                    "tp_size fields (the TransformerLM tensor-parallel "
                    "contract)")
            if weight_quant is not None \
                    and "weight_quant" not in fields:
                raise TypeError(
                    "Engine(weight_quant=...) needs a model with the "
                    "weight_quant field (the TransformerLM "
                    "quantized-serving contract)")
            if mesh is not None or weight_quant is not None:
                # the fields exist, so the clone failed for some other
                # reason — degrading to the un-cloned model would
                # silently drop the requested tier
                raise
            self._model = model
        self.params = policy.cast_params(params)
        if weight_quant is not None:
            # quantize AFTER the policy cast (the absmax measured is
            # the serving dtype's, so codes reproduce exactly the
            # values the bf16 GEMM would have loaded) and BEFORE the
            # mesh placement, so the scale leaves shard with their
            # kernels under the rule table below
            self.params = weight_quant.quantize_params(self.params)
        if mesh is not None:
            # permute/scale + place per the partition-rule table; the
            # spec tree below is what the shard_map wrappers split by
            self.params = _sharding.shard_params(
                self.params, mesh, num_heads=heads, axis=self._tp_axis)
            self._pspec = _sharding.match_partition_rules(
                _sharding.partition_rules(self._tp_axis), self.params)
        self.paged = bool(paged)
        if self.paged:
            self.page_len = page_len = resolve_page_len(self.chunk_len,
                                                        page_len)
            self.max_pages = -(-self.max_len // page_len)
            if num_pages is None:
                # same budget the contiguous layout would spend on
                # (slots + prefix_pool) full-length rows, plus the
                # sentinel — the win is that short requests no longer
                # CONSUME their row's worth
                num_pages = (self.slots + self.prefix_pool) \
                    * self.max_pages + 1
            num_pages = int(num_pages)
            if num_pages < self.max_pages + 1:
                raise ValueError(
                    f"num_pages {num_pages} cannot hold even one "
                    f"max_len request ({self.max_pages} pages) plus "
                    f"the sentinel page")
            self.num_pages = num_pages
            if mesh is None:
                self.cache = PagedKVCache.create(
                    layers=layers, num_pages=num_pages, heads=heads,
                    page_len=page_len, head_dim=head_dim,
                    dtype=cache_dtype, k_scale=k_scale, v_scale=v_scale)
            else:
                # heads-axis pool sharding: each shard holds
                # [layers, num_pages, heads/tp, page_len, head_dim] —
                # attention never crosses ICI; page tables, lengths and
                # the allocator stay replicated host state. Allocated
                # DIRECTLY into the sharded layout (zeros_sharded): a
                # pool sized to aggregate HBM — the point of sharding
                # it — must never transit one chip whole. Quantization
                # scales shard ALONG the pool's heads axis
                # ([layers, heads/tp] per shard), so each shard
                # de/quantizes its own heads collective-free.
                shape = (layers, num_pages, heads, page_len, head_dim)
                pspec = _sharding.cache_pspec(self._tp_axis)
                if k_scale is not None:
                    sspec = _sharding.scale_pspec(self._tp_axis)
                    from jax.sharding import NamedSharding
                    k_scale = jax.device_put(
                        k_scale, NamedSharding(mesh, sspec))
                    v_scale = jax.device_put(
                        v_scale, NamedSharding(mesh, sspec))
                self.cache = PagedKVCache(
                    k=_sharding.zeros_sharded(shape, cache_dtype, mesh,
                                              pspec),
                    v=_sharding.zeros_sharded(shape, cache_dtype, mesh,
                                              pspec),
                    k_scale=k_scale, v_scale=v_scale)
            self.pool = PagePool(num_pages, page_len)
            self._page_table = np.zeros((self.slots, self.max_pages),
                                        np.int32)
            self._n_pages = np.zeros(self.slots, np.int32)
            self._host_len = np.zeros(self.slots, np.int32)
            self._slot_reserved = np.zeros(self.slots, np.int32)
            # paged prefix reuse needs no reserved rows — retained
            # prefixes share the one pool; prefix_pool sizes the EXTRA
            # capacity set aside for them in the num_pages default and
            # gates the feature on, exactly as before
            self.prefix_cache = None if self.prefix_pool == 0 else \
                PrefixCache(block_len=self.chunk_len, pool_rows=(),
                            on_evict=self.pool.release)
        else:
            self.pool = None
            # pool rows ride the same arrays as the serving slots so
            # ONE copy program (traced src/dst rows, same shapes)
            # serves both directions of prefix reuse; decode slices
            # them back out
            self.cache = KVCache.create(
                layers=layers, slots=self.slots + self.prefix_pool,
                heads=heads, max_len=self.max_len, head_dim=head_dim,
                dtype=cache_dtype, k_scale=k_scale, v_scale=v_scale)
            self.prefix_cache = None if self.prefix_pool == 0 else \
                PrefixCache(
                    block_len=self.chunk_len,
                    pool_rows=range(self.slots,
                                    self.slots + self.prefix_pool))
        # hierarchical KV: the host-DRAM prefix tier behind the paged
        # pool. Wired AFTER the prefix cache exists — eviction becomes
        # swap-out (a dispatched device→host migration; the entry
        # stays matchable as "swapping" then "swapped"), a swapped hit
        # swaps back in through _jit_swap_in. Both swap programs are
        # mesh-aware: under a tp mesh they run shard_map'd over the
        # pool's heads axis (each shard moves its own heads/tp slice —
        # zero collectives, pinned from compiled HLO).
        self.host_tier: Optional[HostTier] = None
        self.host_tier_shared = False
        self.sync_swap = bool(sync_swap)
        self._swap_worker: Optional[SwapWorker] = None
        self.swap_verify_failed = 0
        if host_tier is not None:
            if not self.paged:
                raise ValueError(
                    "Engine(host_tier=...) requires paged=True: the "
                    "tier swaps pool pages, and the contiguous layout "
                    "has none")
            if self.prefix_cache is None:
                raise ValueError(
                    "Engine(host_tier=...) requires prefix_pool > 0 — "
                    "the tier is a second level behind the prefix "
                    "cache, not a standalone store")
            self.host_tier = host_tier if isinstance(host_tier, HostTier) \
                else HostTier(int(host_tier))
            # externally-owned-arena mode (disaggregated serving): a
            # pre-built HostTier(shared=True) is co-owned by N engines
            # — register as ONE of its eviction listeners instead of
            # claiming the exclusive hook, and scope the cross-tier
            # audit to keys this engine's prefix index owns (the
            # PoolAuditor consults host_tier_shared)
            self.host_tier_shared = bool(
                getattr(self.host_tier, "shared", False))
            if self.host_tier_shared:
                self.host_tier.add_on_evict(self._on_host_tier_evict)
            else:
                self.host_tier.on_evict = self._on_host_tier_evict
            self.prefix_cache.set_swap_hooks(
                swap_out=self._dispatch_swap_out,
                contains=self.host_tier.contains)
            self._jit_swap_in = jax.jit(
                self._wrap_swap(self._swap_in_impl,
                                extra_in=(self._swap_block_pspec(),) * 2
                                + (None,), block_out=0),
                donate_argnums=(0,))
            # the swap-out gather is deliberately UNDONATED: its output
            # is a fresh snapshot buffer (the worker forces it later)
            # and an undonated call dispatches asynchronously even on
            # the CPU backend — which is exactly what takes the
            # device→host migration off the admission path
            self._jit_swap_out = jax.jit(
                self._wrap_swap(self._swap_out_impl, extra_in=(None,),
                                block_out=2))
            if not self.sync_swap:
                self._swap_worker = SwapWorker()
                # stop the thread when the engine is collected (the
                # finalizer closes over the WORKER, not self — no cycle)
                weakref.finalize(self, self._swap_worker.stop)
        # multi-tenant LoRA tier (:mod:`apex_tpu.serving.lora`): a
        # stacked per-site adapter arena gathered in the GEMM epilogue
        # by a TRACED per-slot adapter-index operand — heterogeneous
        # adapters decode in one batch, the adapter id is data (never
        # a trace key), so the program-count pins above cannot move.
        # _slot_adapter[slot] names the arena row each slot gathers;
        # row 0 is the all-zero adapter (+0.0 epilogue — the
        # fault_bias value-identity pin), so base requests on a
        # LoRA-enabled engine stay bitwise the base engine.
        self.lora = None
        self._slot_adapter = np.zeros(self.slots, np.int32)
        if lora is not None:
            from .lora import LoRAConfig, LoRAManager
            if not isinstance(lora, LoRAConfig):
                raise TypeError(f"lora must be a LoRAConfig, got "
                                f"{type(lora).__name__}")
            self.lora = LoRAManager(
                lora, hidden=hidden, num_heads=heads,
                num_layers=layers,
                mlp_ratio=int(getattr(model, "mlp_ratio", 4)),
                tp=self.tp, mesh=mesh,
                tp_axis=self._tp_axis or "tp", registry=registry)
        self._registry = registry
        # request tracer (None = off): installed by the scheduler via
        # set_tracer. The engine's only spans are the hierarchical-KV
        # migrations (swap_out / swap_out_store / swap_in) — emitted
        # through event_current against the thread-local trace binding
        # the scheduler's admission path holds, since the engine never
        # sees a Request
        self._tracer = None
        self._key = jax.random.PRNGKey(seed)
        self.prefill_traces = 0
        self.decode_traces = 0
        self.chunk_traces = 0
        self.copy_traces = 0
        self.verify_traces = 0
        self.swap_in_traces = 0
        self.swap_out_traces = 0
        self.tokens_generated = 0
        # cumulative seconds the HOST spent blocked waiting for device
        # results (every forcing site — token readback, finiteness
        # verdicts, the sync() barrier — is timed into this). The
        # scheduler differences it around each heartbeat to split beat
        # wall time into host-think vs device-wait: the basis of the
        # serving.heartbeat.* gauges and the pipelined watchdog's
        # host-portion budget.
        self.device_wait_s = 0.0
        # the non-finite guard's host-side view, refreshed by every
        # sampling call: per-slot flags for the last decode step, one
        # flag each for the last chunk/monolithic prefill. True means
        # the sampled logits row was entirely finite (the token is
        # trustworthy); False is the quarantine signal the scheduler's
        # fault policy consumes.
        self.last_decode_finite = np.ones(self.slots, bool)
        self.last_chunk_finite = True
        self.last_prefill_finite = True
        self.last_verify_finite = True
        self.last_verify_finite_slots = np.ones(self.slots, bool)
        self.nonfinite_events = 0
        # prefill flash-attention geometry: decode.* tuned keys beat the
        # training sweep's flash.* defaults when present
        self._pf_bq = vmem.get_override("decode.prefill_block_q", 0,
                                        multiple=8) or None
        self._pf_bk = vmem.get_override("decode.prefill_block_k", 0,
                                        multiple=128) or None
        if self.paged:
            # under a mesh each program body runs shard_map'd over the
            # tensor-parallel axis (params split per the rule table, the
            # pool on heads, every host operand replicated); mesh=None
            # wraps nothing — the verbatim single-chip programs
            self._jit_prefill = jax.jit(
                self._tp_wrap(self._paged_prefill_impl, 2),
                donate_argnums=(1,))
            self._jit_decode = jax.jit(
                self._tp_wrap(self._paged_decode_impl, 2),
                donate_argnums=(1,))
            self._jit_chunk = jax.jit(
                self._tp_wrap(self._paged_chunk_impl, 2),
                donate_argnums=(1,))
            self._jit_verify = jax.jit(
                self._tp_wrap(self._paged_verify_impl, 3),
                donate_argnums=(1,))
            self._jit_copy = None      # retired: hits share pages
            _logger.info(
                "serving engine (paged%s): %d slots x %d positions, "
                "prefill_len=%d, chunk_len=%d, page_len=%d, %d pages "
                "(+1 sentinel in count), prefix_pool=%d, cache %s "
                "(%.1f MiB%s), top_k=%d",
                f", tp={self.tp}" if mesh is not None else "",
                self.slots, self.max_len, self.prefill_len,
                self.chunk_len, self.page_len, self.num_pages,
                self.prefix_pool, np.dtype(cache_dtype).name,
                self.cache.nbytes() / 2**20,
                f", {self.cache.nbytes() / self.tp / 2**20:.1f}/shard"
                if mesh is not None else "", self.top_k)
        else:
            self._jit_prefill = jax.jit(self._prefill_impl,
                                        donate_argnums=(1,))
            self._jit_decode = jax.jit(self._decode_impl,
                                       donate_argnums=(1,))
            self._jit_chunk = jax.jit(self._chunk_impl,
                                      donate_argnums=(1,))
            self._jit_verify = jax.jit(self._verify_impl,
                                       donate_argnums=(1,))
            self._jit_copy = jax.jit(self._copy_impl, donate_argnums=(0,))
            _logger.info(
                "serving engine: %d slots x %d positions, prefill_len=%d,"
                " chunk_len=%d, prefix_pool=%d, cache %s (%.1f MiB), "
                "top_k=%d",
                self.slots, self.max_len, self.prefill_len,
                self.chunk_len, self.prefix_pool, np.dtype(cache_dtype).name,
                self.cache.nbytes() / 2**20, self.top_k)

        self._emit_tp_gauges()
        self._emit_kv_gauges()
        self._emit_wq_gauges()
        self._emit_lora_gauges()

    # --------------------------------------------------- tensor parallelism
    def _tp_wrap(self, fn, n_extra_out: int):
        """Wrap a paged program body in ``shard_map`` over the engine's
        tensor-parallel mesh: params split per the partition-rule table,
        the KV pool on its heads axis, every other operand (tokens, page
        tables, lengths, scalars, PRNG key) replicated, outputs
        replicated except the pool. ``mesh=None`` returns ``fn``
        untouched — the single-chip baseline is the verbatim program,
        not a degenerate wrap."""
        if self.mesh is None:
            return fn
        from jax.sharding import PartitionSpec as P

        from apex_tpu.utils.compat import shard_map

        cspec = self._cache_spec_tree()

        def wrapped(params, cache, *rest):
            extra = (P(),) * len(rest)
            if self.lora is not None:
                # the two trailing LoRA operands: the stacked arena
                # (split per its own spec tree — the PR 9 rule-table
                # split restated per stacked array) and the adapter-id
                # vector (replicated host data)
                extra = (P(),) * (len(rest) - 2) \
                    + (self.lora.spec_tree(), P())
            return shard_map(
                fn, mesh=self.mesh,
                in_specs=(self._pspec, cspec) + extra,
                out_specs=(cspec,) + (P(),) * n_extra_out,
                check_vma=False)(params, cache, *rest)

        return wrapped

    def _cache_spec_tree(self):
        """The cache pytree's partition-spec tree (mesh engines only):
        pool arrays on the heads axis, quantization scales (when
        present) on THEIR heads axis, None fields stay None — shared
        by every shard_map wrap (model programs and the two swap
        programs alike)."""
        from .sharding import cache_pspec, scale_pspec

        quant = self.kv_quant is not None
        return PagedKVCache(
            k=cache_pspec(self._tp_axis), v=cache_pspec(self._tp_axis),
            k_scale=scale_pspec(self._tp_axis) if quant else None,
            v_scale=scale_pspec(self._tp_axis) if quant else None)

    def _swap_block_pspec(self):
        """A swapped page block's partition spec: ``[layers,
        max_pages, heads/tp, page_len, head_dim]`` per shard — the
        SAME heads-axis split as the pool itself, so each shard's swap
        gather/scatter moves exactly its own slice and the programs
        need no collective at all. None on a single-chip engine."""
        if self.mesh is None:
            return None
        from jax.sharding import PartitionSpec as P

        return P(None, None, self._tp_axis, None, None)

    def _wrap_swap(self, fn, *, extra_in, block_out: int):
        """Wrap a swap program body (``fn(cache, *rest)``) in
        shard_map over the tensor-parallel mesh: the cache per its
        spec tree, ``extra_in`` the per-operand specs for ``rest``
        (None = replicated), and the outputs — ``block_out`` page
        blocks (heads-sharded) for the gather, else the cache tree for
        the scatter. ``mesh=None`` returns ``fn`` untouched, exactly
        like :meth:`_tp_wrap`: the single-chip swap programs are the
        verbatim bodies. The wrapped programs are the collective-free
        pin's subject: swap is pure data movement, each shard moves
        its own heads — compiled HLO must contain ZERO collectives
        (``tests/L0/test_host_tier.py``)."""
        if self.mesh is None:
            return fn
        from jax.sharding import PartitionSpec as P

        from apex_tpu.utils.compat import shard_map

        cspec = self._cache_spec_tree()
        bspec = self._swap_block_pspec()
        in_rest = tuple(P() if s is None else s for s in extra_in)
        out_specs = (bspec,) * block_out if block_out else cspec

        def wrapped(cache, *rest):
            return shard_map(
                fn, mesh=self.mesh, in_specs=(cspec,) + in_rest,
                out_specs=out_specs, check_vma=False)(cache, *rest)

        return wrapped

    def _gather_logits(self, rows):
        """Rejoin vocab-parallel logits: under a mesh the model's tied
        head returns ``[..., vocab/tp]`` local slices (see
        :class:`~apex_tpu.models.transformer_lm.TransformerLM`) and this
        one all-gather — the sharded programs' ONLY gather, applied to
        the rows actually being sampled — restores the full vocabulary
        so sampling and the fused non-finite guard run exactly as on one
        chip. Identity on a single-chip engine."""
        if self.mesh is None:
            return rows
        return jax.lax.all_gather(rows, self._tp_axis,
                                  axis=rows.ndim - 1, tiled=True)

    def _emit_tp_gauges(self) -> None:
        """The ``serving.tp.*`` telemetry snapshot of a sharded engine:
        shard count, the per-program collective inventory (2 psums per
        block + 1 logits all-gather — the numbers the HLO pin asserts),
        and the per-shard pool view (each shard holds every page at
        ``heads/tp`` width, so HBM per chip is the pool bytes over tp).
        Single-chip engines emit nothing."""
        if self._registry is None or self.mesh is None:
            return
        from .sharding import expected_collectives

        coll = expected_collectives(self.cache.layers)
        self._registry.gauge_set("serving.tp.shards", float(self.tp))
        self._registry.gauge_set("serving.tp.psums_per_program",
                                 float(coll["all_reduce"]))
        self._registry.gauge_set("serving.tp.all_gathers_per_program",
                                 float(coll["all_gather"]))
        self._registry.gauge_set("serving.tp.hbm_bytes_per_shard",
                                 self.cache.nbytes() / self.tp)
        self._registry.gauge_set("serving.tp.pool_pages_per_shard",
                                 float(self.num_pages))

    def _emit_kv_gauges(self) -> None:
        """The ``serving.kv.*`` telemetry snapshot: per-token cache
        bytes (``layers * heads * head_dim * itemsize * 2`` — the
        number the quantized tier halves, and the basis of the bench's
        bytes-per-token reduction claim) and, on a quantized engine,
        the largest absolute value the calibrated scales can represent
        (``max(scale) * 127`` — a drifting workload whose true absmax
        exceeds this is CLIPPING, the dashboard signal to recalibrate).
        """
        if self._registry is None:
            return
        c = self.cache
        per_token = c.layers * c.heads * c.head_dim \
            * np.dtype(c.dtype).itemsize * 2
        self._registry.gauge_set("serving.kv.bytes_per_token",
                                 float(per_token))
        if c.k_scale is not None:
            from .kv_quant import QMAX
            absmax = max(float(jnp.max(c.k_scale)),
                         float(jnp.max(c.v_scale))) * QMAX
            self._registry.gauge_set("serving.kv.quant_scale_absmax",
                                     absmax)

    def _emit_wq_gauges(self) -> None:
        """The ``serving.wq.*`` telemetry snapshot of a weight-quantized
        engine: mean bytes per WEIGHT parameter (total param-tree bytes
        over weight elements, scale overhead charged in — the basis of
        the bench's weight-bytes reduction claim; ~2.0 on the bf16
        default, ~1.0+scales quantized) and the largest absolute weight
        the calibrated scales can represent (``max(scale) * 127`` — a
        provenance number: it moves only when the checkpoint or margin
        does, so a dashboard step flags a silent weight swap).
        Unquantized engines emit nothing — the family is the tier's
        liveness signal."""
        if self._registry is None or self.weight_quant is None:
            return
        from .weight_quant import (param_bytes, param_count,
                                   quant_scale_absmax)

        self._registry.gauge_set(
            "serving.wq.bytes_per_param",
            param_bytes(self.params) / param_count(self.params))
        self._registry.gauge_set("serving.wq.quant_scale_absmax",
                                 quant_scale_absmax(self.params))

    def _emit_lora_gauges(self) -> None:
        """The ``serving.lora.*`` gauge snapshot of a LoRA-enabled
        engine (host-store bytes at rest + device-resident adapter
        count — the :class:`~apex_tpu.serving.lora.LoRAManager` owns
        the names and the counters). LoRA-less engines emit nothing —
        the family is the tier's liveness signal, like ``serving.wq``.
        """
        if self._registry is None or self.lora is None:
            return
        self.lora.set_registry(self._registry)

    # ------------------------------------------------------- multi-tenant LoRA
    def _lora_args(self, slot: Optional[int] = None):
        """The two trailing operands every compiled program takes on a
        LoRA-enabled engine: the stacked device arena (a pytree of
        traced arrays) and the per-row adapter-index vector — the full
        ``[slots]`` binding for decode/verify, the one ``[1]`` slot's
        for chunk/prefill. Empty on a LoRA-less engine, which keeps
        today's traces verbatim."""
        if self.lora is None:
            return ()
        ids = self._slot_adapter if slot is None \
            else self._slot_adapter[slot:slot + 1]
        return (self.lora.arena, jnp.asarray(ids))

    def lora_register(self, name: str, sites, *,
                      alpha: float = 1.0) -> None:
        """Admit adapter ``name`` (per-site stacked A/B matrices) into
        the LoRA host store — see :meth:`~apex_tpu.serving.lora
        .LoRAManager.register`. Loud on a LoRA-less engine."""
        if self.lora is None:
            raise ValueError("engine has no LoRA tier — construct "
                             "with Engine(lora=LoRAConfig(...))")
        self.lora.register(name, sites, alpha=alpha)

    def lora_bind(self, slot: int, name: str) -> bool:
        """Bind serving slot ``slot`` to adapter ``name``: acquire a
        (refcount-pinned) arena row — a hit when resident, a
        CRC-verified swap-in when cold — and point the slot's traced
        adapter index at it. False when the arena is full of bound
        adapters (graceful degradation: the caller holds the request
        queued); ``KeyError`` when the adapter is unknown or its
        record failed the swap-in checksum (the loud-reload path)."""
        if self.lora is None:
            raise ValueError("engine has no LoRA tier")
        row = self.lora.acquire(name)
        if row is None:
            return False
        self._slot_adapter[slot] = row
        return True

    def lora_unbind(self, slot: int) -> None:
        """Release slot ``slot``'s adapter binding (no-op when the
        slot holds the zero adapter, or the tier is off). The adapter
        stays arena-resident at refcount 0 — the next bind is a hit."""
        if self.lora is None:
            return
        row = int(self._slot_adapter[slot])
        if row:
            self._slot_adapter[slot] = 0
            self.lora.release(row)

    def lora_audit(self) -> dict:
        """Cross-check the LoRA tier's refcounts against the LIVE slot
        bindings (every bound arena row's refcount must equal the
        number of slots pointing at it) plus the manager's own byte
        ledger and row<->record reconciliation. Raises on any drift;
        returns the reconciled stats."""
        if self.lora is None:
            raise ValueError("engine has no LoRA tier")
        bound: dict = {}
        for slot in range(self.slots):
            row = int(self._slot_adapter[slot])
            if row:
                bound[row] = bound.get(row, 0) + 1
        return self.lora.audit(bound)

    def resident_adapters(self):
        """Device-resident adapter names (the scheduler's snapshot
        column — adapter affinity routes on membership here); None on
        a LoRA-less engine."""
        return None if self.lora is None \
            else self.lora.resident_names()

    @property
    def compiled_programs(self) -> int:
        """Distinct XLA executables traced so far (the compile-count
        discipline the serving tests pin: exactly three across a run
        that exercises chunk prefill, decode, and the monolithic
        baseline; exactly four once prefix reuse exercises the KV
        row-copy too — and one more, on either layout, once speculative
        decoding exercises the verify program: 4 paged, 5 contiguous.
        The hierarchical-KV tier adds AT MOST one more PER DIRECTION
        on the paged path: the fixed-shape ``swap_out`` block gather
        (traced lazily on the first pressure eviction) and the
        fixed-shape ``swap_in`` block scatter (traced lazily on the
        first hit-after-swap) — both shape-padded to ``max_pages``, so
        no entry size can ever trace a second copy)."""
        return (self.chunk_traces + self.decode_traces
                + self.prefill_traces + self.copy_traces
                + self.verify_traces + self.swap_in_traces
                + self.swap_out_traces)

    # ------------------------------------------------------ compiled bodies
    # Every sampling program also returns a per-slot FINITENESS flag —
    # all(isfinite) over the fp32 logits row it samples from — so the
    # host can quarantine a NaN/Inf slot without touching its batchmates
    # (the non-finite guard is FUSED into the existing programs: zero
    # new executables, pinned by the trace-count tests). The decode and
    # chunk programs additionally take a ``fault_bias`` logit offset
    # (per-slot [slots] / scalar) that is 0.0 in production — adding
    # +0.0 to an fp32 row is value-identical, so clean-path tokens are
    # unchanged — and NaN/Inf under a FaultPlan injection, which makes
    # the in-program guard see REAL non-finite logits.
    def _kv_scales_of(self, cache):
        """The ``(k_scale, v_scale)`` pair the quantized tier threads
        into the model's cache modes; None on the bf16 default (a
        static, trace-time choice — quantization is an engine property,
        not an operand)."""
        if cache.k_scale is None:
            return None
        return (cache.k_scale, cache.v_scale)

    def _quantize_prefill_kv(self, cache, k_new, v_new):
        """Quantize a prefill's stacked ``[layers, B, heads, P, d]``
        K/V into the cache's int8 codes (identity on the bf16 tier):
        the one STORAGE cast the model does not perform itself, because
        ``return_kv`` prefill never sees the cache. The model has
        already round-tripped these values through the scale grid
        (``kv_scales`` in the ``return_kv`` forward), so this quantize
        is an exact code recovery — the bytes stored here are the bytes
        chunked prefill would have written."""
        if cache.k_scale is None:
            return k_new, v_new
        return (quantize(k_new, cache.k_scale[:, None, :, None, None]),
                quantize(v_new, cache.v_scale[:, None, :, None, None]))

    @staticmethod
    def _lora_kw(lora, adapter_ids):
        """The model-apply kwargs for the two optional trailing LoRA
        operands — EMPTY when the tier is off, so a LoRA-less engine's
        traces stay verbatim (the bitwise baseline)."""
        return {} if lora is None else {"lora": lora,
                                        "adapter_ids": adapter_ids}

    def _prefill_impl(self, params, cache, tokens, length, slot,
                      temperature, key, lora=None, adapter_ids=None):
        self.prefill_traces += 1    # python body runs at trace time only
        logits, (k_new, v_new) = self._model.apply(
            {"params": params}, tokens, train=False, return_kv=True,
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        k_new, v_new = self._quantize_prefill_kv(cache, k_new, v_new)
        cache = cache.insert(slot, k_new, v_new, length)
        last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                            keepdims=False)        # [V]
        last = jnp.asarray(last, jnp.float32)
        finite = jnp.all(jnp.isfinite(last))
        token = sample_tokens(last[None], temperature[None], key,
                              self.top_k)[0]
        return cache, token, finite

    def _chunk_impl(self, params, cache, tokens, slot, offset, n_valid,
                    temperature, fault_bias, key, lora=None,
                    adapter_ids=None):
        self.chunk_traces += 1      # python body runs at trace time only
        k_slot, v_slot = cache.slot_view(slot)
        offset = jnp.asarray(offset, jnp.int32)
        logits, (k2, v2) = self._model.apply(
            {"params": params}, tokens, train=False,
            cache=(k_slot, v_slot), positions=offset[None],
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        cache = cache.write_slot(slot, k2, v2, offset + n_valid)
        # sample at the last VALID row: the request's first token when
        # this is the prompt's final chunk, discarded by the host
        # otherwise (one program either way — finality is not traced)
        last = jax.lax.dynamic_index_in_dim(logits[0], n_valid - 1,
                                            keepdims=False)        # [V]
        last = jnp.asarray(last, jnp.float32) + fault_bias
        finite = jnp.all(jnp.isfinite(last))
        token = sample_tokens(last[None], temperature[None], key,
                              self.top_k)[0]
        return cache, token, finite

    def _decode_impl(self, params, cache, last_tokens, active,
                     temperature, fault_bias, key, lora=None,
                     adapter_ids=None):
        self.decode_traces += 1     # python body runs at trace time only
        # prefix-pool rows sit past the serving slots in the same
        # arrays: slice them out (static) so the decode batch stays
        # [slots, 1] — retained prefixes cost storage, not compute.
        # With prefix_pool == 0 the front IS the whole cache and this
        # degenerates bitwise to a model_view()/advance decode.
        positions = jnp.minimum(cache.lengths[:self.slots],
                                self.max_len - 1)
        logits, (k2, v2) = self._model.apply(
            {"params": params}, last_tokens[:, None], train=False,
            cache=cache.front_view(self.slots), positions=positions,
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        rows = jnp.asarray(logits[:, 0, :], jnp.float32) \
            + fault_bias[:, None]
        finite = jnp.all(jnp.isfinite(rows), axis=-1)         # [slots]
        tokens = sample_tokens(rows, temperature, key, self.top_k)
        return cache.advance_front(k2, v2, active), tokens, finite

    def _copy_impl(self, cache, src, dst, length):
        self.copy_traces += 1       # python body runs at trace time only
        return cache.copy_slot(src, dst, length)

    @staticmethod
    def _accept_longest_prefix(rows, tokens, n_drafted):
        """In-program accept-longest-prefix over fp32 logit ``rows``
        ``[B, K+1, V]`` for draft ``tokens`` ``[B, K+1]`` (per row:
        column 0 is the last committed token, columns 1..K the drafts;
        drafts past ``n_drafted[b]`` are padding and never accepted —
        rows with ``n_drafted[b] == 0`` are fixed-shape passengers and
        accept nothing). Greedy only — every emitted token IS the
        greedy target, which is the whole bitwise-parity argument.
        Returns ``(greedy [B, K+1] int32, n_accepted [B] int32)``."""
        K = tokens.shape[1] - 1
        greedy = jnp.argmax(rows, axis=-1).astype(jnp.int32)  # [B, K+1]
        match = (greedy[:, :K] == tokens[:, 1:]) \
            & (jnp.arange(K, dtype=jnp.int32)[None, :]
               < n_drafted[:, None])
        n_accepted = jnp.sum(
            jnp.cumprod(match.astype(jnp.int32), axis=1),
            axis=1).astype(jnp.int32)
        return greedy, n_accepted

    def _verify_impl(self, params, cache, tokens, n_drafted, fault_bias,
                     lora=None, adapter_ids=None):
        self.verify_traces += 1     # python body runs at trace time only
        K = tokens.shape[1] - 1
        # per-row offsets ARE the committed device lengths on the
        # contiguous layout (device state, exactly like decode); rows
        # with n_drafted == 0 ride the fixed-shape batch — their writes
        # are masked back out below, their outputs discarded by the host
        offsets = cache.lengths[:self.slots]
        logits, (k2, v2) = self._model.apply(
            {"params": params}, tokens, train=False,
            cache=cache.front_view(self.slots), positions=offsets,
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        rows = jnp.asarray(logits, jnp.float32) \
            + fault_bias[:, None, None]
        finite = jnp.all(jnp.isfinite(rows), axis=(1, 2))     # [slots]
        greedy, n_accepted = self._accept_longest_prefix(rows, tokens,
                                                         n_drafted)
        # commit ONLY the verifying rows whose padded window fits and
        # that hold a committed prefix: a passenger row near max_len
        # would have had its [K+1]-wide write clipped back over live
        # K/V (the model's position safety net relocates, it does not
        # drop), so its bytes are restored verbatim. verify_batch
        # raises host-side before any active row can reach this mask
        # (same contract as the paged path), so the in-program guard is
        # defense-in-depth for raw _jit_verify callers only — it keeps
        # an invalid window from corrupting the cache, never a public
        # API outcome. For verifying rows the rejected tail's K/V sits
        # past the committed length — unreachable (attention masks by
        # length) and overwritten write-then-attend by the slot's next
        # step; rollback is length arithmetic, no cache mutation to
        # undo.
        fits = (offsets > 0) & (offsets + K + 1 <= self.max_len)
        verifying = (n_drafted > 0) & fits
        mask = verifying[None, :, None, None, None]
        k_old, v_old = cache.front_view(self.slots)
        k2 = jnp.where(mask, jnp.asarray(k2, cache.dtype), k_old)
        v2 = jnp.where(mask, jnp.asarray(v2, cache.dtype), v_old)
        n_accepted = jnp.where(verifying, n_accepted, 0)
        new_len = jnp.where(verifying, offsets + n_accepted + 1, offsets)
        cache = cache.commit_front(k2, v2, new_len)
        return cache, greedy, n_accepted, finite

    # -------------------------------------------- compiled bodies (paged)
    def _paged_prefill_impl(self, params, cache, tokens, pt_row, length,
                            temperature, key, lora=None,
                            adapter_ids=None):
        self.prefill_traces += 1    # python body runs at trace time only
        logits, (k_new, v_new) = self._model.apply(
            {"params": params}, tokens, train=False, return_kv=True,
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        k_new, v_new = self._quantize_prefill_kv(cache, k_new, v_new)
        # scatter the padded [0, prefill_len) window into the slot's
        # pages: m whole pages, ids from the (traced) page-table row
        pl_ = self.page_len
        m = -(-self.prefill_len // pl_)
        pad = m * pl_ - self.prefill_len
        pages = jax.lax.dynamic_slice_in_dim(pt_row[0], 0, m)    # [m]

        def _scatter(pool, new):
            new = jnp.asarray(new, pool.dtype)
            if pad:
                new = jnp.pad(new, ((0, 0), (0, 0), (0, 0), (0, pad),
                                    (0, 0)))
            # [layers, 1, h, m*pl, d] -> [layers, m, h, pl, d]
            new = new[:, 0].reshape(cache.layers, cache.heads, m, pl_,
                                    cache.head_dim).transpose(0, 2, 1, 3,
                                                              4)
            return pool.at[:, pages].set(new)

        cache = cache.replace(k=_scatter(cache.k, k_new),
                              v=_scatter(cache.v, v_new))
        last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                            keepdims=False)   # [V(/tp)]
        last = self._gather_logits(jnp.asarray(last, jnp.float32))
        finite = jnp.all(jnp.isfinite(last))
        token = sample_tokens(last[None], temperature[None], key,
                              self.top_k)[0]
        return cache, token, finite

    def _paged_chunk_impl(self, params, cache, tokens, pt_row, offset,
                          n_valid, temperature, fault_bias, key,
                          lora=None, adapter_ids=None):
        self.chunk_traces += 1      # python body runs at trace time only
        offset = jnp.asarray(offset, jnp.int32)
        logits, (k2, v2) = self._model.apply(
            {"params": params}, tokens, train=False,
            cache=(cache.k, cache.v, pt_row), positions=offset[None],
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        cache = cache.replace(k=k2, v=v2)
        # sample at the last VALID row (see _chunk_impl)
        last = jax.lax.dynamic_index_in_dim(logits[0], n_valid - 1,
                                            keepdims=False)   # [V(/tp)]
        last = self._gather_logits(jnp.asarray(last, jnp.float32)) \
            + fault_bias
        finite = jnp.all(jnp.isfinite(last))
        token = sample_tokens(last[None], temperature[None], key,
                              self.top_k)[0]
        return cache, token, finite

    def _paged_decode_impl(self, params, cache, last_tokens, page_table,
                           lengths, temperature, fault_bias, key,
                           lora=None, adapter_ids=None):
        self.decode_traces += 1     # python body runs at trace time only
        # lengths are HOST state in the paged layout (the allocator owns
        # them); the program is a pure function of the operands. Length
        # growth happens host-side after the call — inactive slots'
        # tables point at the sentinel page, so their discarded write
        # cannot land on a live request's page.
        positions = jnp.minimum(lengths, self.max_len - 1)
        logits, (k2, v2) = self._model.apply(
            {"params": params}, last_tokens[:, None], train=False,
            cache=(cache.k, cache.v, page_table), positions=positions,
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        rows = self._gather_logits(jnp.asarray(logits[:, 0, :],
                                               jnp.float32)) \
            + fault_bias[:, None]
        finite = jnp.all(jnp.isfinite(rows), axis=-1)         # [slots]
        tokens = sample_tokens(rows, temperature, key, self.top_k)
        return cache.replace(k=k2, v=v2), tokens, finite

    def _paged_verify_impl(self, params, cache, tokens, page_table,
                           lengths, n_drafted, fault_bias, lora=None,
                           adapter_ids=None):
        self.verify_traces += 1     # python body runs at trace time only
        # unaligned_append: every row's [K+1] draft block lands at an
        # arbitrary mid-generation offset — per-position page scatters
        # instead of the whole-page chunk write (the host grew each
        # verifying slot's table to cover offset + K + 1 before this
        # call). Non-verifying rows arrive with ZEROED table rows and
        # length 0 from verify_batch, so their fixed-shape writes land
        # on the sentinel page and their (discarded) attention reads
        # garbage — a live decode slot's pages are never touched by a
        # verify batch it is not in.
        logits, (k2, v2) = self._model.apply(
            {"params": params}, tokens, train=False,
            cache=(cache.k, cache.v, page_table), positions=lengths,
            unaligned_append=True,
            kv_scales=self._kv_scales_of(cache),
            **self._lora_kw(lora, adapter_ids))
        cache = cache.replace(k=k2, v=v2)
        rows = self._gather_logits(jnp.asarray(logits, jnp.float32)) \
            + fault_bias[:, None, None]
        finite = jnp.all(jnp.isfinite(rows), axis=(1, 2))     # [slots]
        greedy, n_accepted = self._accept_longest_prefix(rows, tokens,
                                                         n_drafted)
        # lengths are host state on the paged layout: the rollback (the
        # host-side length arithmetic) happens in verify_batch after it
        # reads n_accepted — the rejected tail's pages stay allocated
        # to the slot, their K/V unreachable behind the length
        return cache, greedy, n_accepted, finite

    def _swap_out_impl(self, cache, page_ids):
        """The hierarchical-KV tier's OUTBOUND compiled program: gather
        the pool pages named by ``page_ids`` ``[max_pages]`` int32 into
        a fresh ``[layers, max_pages, heads, page_len, head_dim]``
        snapshot block per pool array — ONE dispatch per swap-out,
        fixed shape (entries shorter than ``max_pages`` pad their
        trailing ids with the page-0 sentinel, whose garbage is sliced
        off by the worker before storage). The output buffers are the
        SNAPSHOT the async swap rides: dispatched before the entry's
        pages are released, program order sequences this gather ahead
        of any later overwrite, so the worker's deferred force can
        never observe reused pages — write-then-attend protects
        attention readers, not cross-tier copies, which is why the
        snapshot must be taken here and not at completion time. Under
        a mesh each shard gathers its own heads slice (zero
        collectives — pinned from HLO). Pure data movement: no
        attention, no sampling, no PRNG — the copy-program precedent,
        so it owes the tuned tables no ``decode.*`` key."""
        self.swap_out_traces += 1   # python body runs at trace time only
        page_ids = jnp.asarray(page_ids, jnp.int32)
        return cache.k[:, page_ids], cache.v[:, page_ids]

    def _swap_in_impl(self, cache, k_blk, v_blk, page_ids):
        """The hierarchical-KV tier's INBOUND compiled program: scatter
        a host-restored page block ``[layers, max_pages, heads, page_len,
        head_dim]`` into the pool rows named by ``page_ids``
        ``[max_pages]`` int32 — ONE dispatch per swap-in, fixed shape
        (entries shorter than ``max_pages`` pad their trailing ids with
        the page-0 sentinel, whose garbage absorbs the padded writes
        exactly as it absorbs inactive-slot decode writes). Under a
        mesh each shard scatters its own heads slice (zero
        collectives — pinned from HLO). Pure data movement: no
        attention, no sampling, no PRNG — the copy-program precedent,
        so it owes the tuned tables no ``decode.*`` key."""
        self.swap_in_traces += 1    # python body runs at trace time only
        page_ids = jnp.asarray(page_ids, jnp.int32)
        k = cache.k.at[:, page_ids].set(jnp.asarray(k_blk, cache.dtype))
        v = cache.v.at[:, page_ids].set(jnp.asarray(v_blk, cache.dtype))
        return cache.replace(k=k, v=v)

    # ------------------------------------------------------------- host API
    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def prefill(self, slot: int, prompt: Sequence[int],
                temperature: float = 0.0) -> int:
        """Monolithic prefill: the whole ``prompt`` into ``slot`` in one
        compiled call; returns the first sampled token (host int) and
        blocks until it is on the host. This is the legacy/baseline path
        — it stalls the caller (and any decode heartbeat) for the full
        prompt; production serving ingests through :meth:`prefill_chunk`
        one chunk per scheduler tick instead. Kept compiled because it
        is the chunked path's bitwise-parity oracle and the
        head-of-line-blocking baseline (``Scheduler(chunked=False)``)."""
        n = len(prompt)
        if not 0 < n <= self.prefill_len:
            raise ValueError(f"prompt length {n} not in (0, "
                             f"prefill_len={self.prefill_len}]")
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} not in [0, {self.slots})")
        tokens = np.zeros((1, self.prefill_len), np.int32)
        tokens[0, :n] = np.asarray(prompt, np.int32)
        t0 = time.perf_counter()
        if self.paged:
            # monolithic prefill writes the full padded window: the
            # slot restarts cold (stale pages released, the admission
            # reservation — if the scheduler made one — kept so the
            # fresh pages draw it down rather than eating into other
            # slots' promises) with enough pages to hold it
            self.release_slot(slot, keep_reservation=True)
            self._grow_slot(slot, -(-self.prefill_len // self.page_len))
            self.cache, token, finite = self._runtime_call(
                lambda: self._with_prefill_blocks(
                    lambda: self._jit_prefill(
                        self.params, self.cache, jnp.asarray(tokens),
                        jnp.asarray(self._page_table[slot:slot + 1]),
                        np.int32(n), np.float32(temperature),
                        self._next_key(), *self._lora_args(slot))))
            self._host_len[slot] = n
        else:
            self.cache, token, finite = self._runtime_call(
                lambda: self._with_prefill_blocks(
                    lambda: self._jit_prefill(
                        self.params, self.cache, jnp.asarray(tokens),
                        np.int32(n), np.int32(slot),
                        np.float32(temperature), self._next_key(),
                        *self._lora_args(slot))))
        tw = time.perf_counter()
        token = int(token)                  # device sync
        self.last_prefill_finite = bool(finite)
        self.device_wait_s += time.perf_counter() - tw
        if not self.last_prefill_finite:
            self._count_nonfinite(1)
        if self._registry is not None:
            self._registry.observe("serving.prefill.s",
                                   time.perf_counter() - t0)
            self._registry.counter_inc("serving.prefill.calls")
            self._registry.counter_inc("serving.tokens_generated")
        self.tokens_generated += 1
        return token

    def prefill_chunk(self, slot: int, chunk: Sequence[int], offset: int,
                      temperature: float = 0.0, *, final: bool = True,
                      fault_bias: float = 0.0) -> int:
        """Ingest one chunk of a prompt into ``slot`` at cache position
        ``offset`` and return the token sampled at the chunk's last
        valid row (host int). The token is the request's first output
        token when ``final`` is True (the time-to-first-token boundary);
        for mid-prompt chunks it is a throwaway — the program samples
        unconditionally so finality never retraces.

        ``final`` is host-side accounting only (tokens_generated and the
        telemetry counters tick once per request, on the real token).

        ``fault_bias`` is the chaos harness's injection operand: a
        float added to the sampled logits row inside the compiled
        program (0.0 in production — value-identical; NaN/Inf under a
        :class:`~apex_tpu.serving.FaultPlan` makes the in-program
        finiteness guard fire for real). The guard's verdict lands in
        :attr:`last_chunk_finite` either way.

        Internally this is :meth:`prefill_chunk_dispatch` followed
        immediately by :meth:`prefill_chunk_reconcile` — the depth-0
        composition IS the bitwise oracle the dispatch-ahead prefill
        path (``pipeline_depth >= 1``) is pinned against.
        """
        return self.prefill_chunk_reconcile(self.prefill_chunk_dispatch(
            slot, chunk, offset, temperature, final=final,
            fault_bias=fault_bias))

    def prefill_chunk_dispatch(self, slot: int, chunk: Sequence[int],
                               offset: int, temperature: float = 0.0,
                               *, final: bool = True,
                               fault_bias: float = 0.0) -> PendingPrefill:
        """Dispatch one chunk-prefill step WITHOUT forcing its sampled
        token to host — the prefill-path half of the dispatch-ahead
        split (:class:`PendingDecode`'s twin). Validates, grows the
        slot's page run, issues the compiled chunk program and updates
        host-side ingestion length; the returned handle's ``token`` /
        ``finite`` stay on device until :meth:`prefill_chunk_reconcile`.
        The force-early lint covers this function by name: no
        ``int()`` / ``np.asarray`` / ``jax.device_get`` may appear in
        its body."""
        n = len(chunk)
        if not 0 < n <= self.chunk_len:
            raise ValueError(f"chunk length {n} not in (0, "
                             f"chunk_len={self.chunk_len}]")
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} not in [0, {self.slots})")
        if not 0 <= offset <= self.prefill_len - n:
            raise ValueError(
                f"chunk [{offset}, {offset + n}) exceeds prefill_len="
                f"{self.prefill_len}")
        if offset + self.chunk_len > self.max_len:
            # the program writes the PADDED chunk window; past max_len
            # the model's position clip would relocate it over earlier
            # K/V — reject instead of corrupting (scheduler offsets are
            # chunk multiples, which the constructor already bounds;
            # this guards direct callers at arbitrary offsets)
            raise ValueError(
                f"padded chunk window [{offset}, "
                f"{offset + self.chunk_len}) exceeds max_len="
                f"{self.max_len}")
        tokens = np.zeros((1, self.chunk_len), np.int32)
        tokens[0, :n] = chunk       # host list -> int32, no device read
        t0 = time.perf_counter()
        if self.paged:
            if offset % self.page_len:
                raise ValueError(
                    f"paged chunk offset {offset} must be page-aligned "
                    f"(page_len={self.page_len})")
            if offset == 0:
                # cold start on a (possibly re-used) slot: stale pages
                # back to the pool, the admission reservation kept (the
                # fresh pages must draw it down, not eat into other
                # slots' promises). A prefix hit instead enters through
                # attach_prefix, which resumes at a non-zero offset.
                self.release_slot(slot, keep_reservation=True)
            self._grow_slot(
                slot, -(-(offset + self.chunk_len) // self.page_len))
            self.cache, token, finite = self._runtime_call(
                lambda: self._jit_chunk(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(self._page_table[slot:slot + 1]),
                    np.int32(offset), np.int32(n),
                    np.float32(temperature), np.float32(fault_bias),
                    self._next_key(), *self._lora_args(slot)))
            self._host_len[slot] = offset + n
        else:
            self.cache, token, finite = self._runtime_call(
                lambda: self._jit_chunk(
                    self.params, self.cache, jnp.asarray(tokens),
                    np.int32(slot), np.int32(offset), np.int32(n),
                    np.float32(temperature), np.float32(fault_bias),
                    self._next_key(), *self._lora_args(slot)))
        return PendingPrefill(
            token=token, finite=finite, slot=slot, final=final,
            t_dispatch=t0, dispatch_s=time.perf_counter() - t0)

    def prefill_chunk_reconcile(self, pending: PendingPrefill) -> int:
        """Force a dispatched chunk's sampled token to host and finish
        its accounting (finiteness verdict, ``device_wait_s``, the
        ``serving.prefill_chunk_s`` / ``serving.prefill.chunks`` /
        ``serving.tokens_generated`` counters) — the batched-readback
        half of the dispatch-ahead prefill split. Returns the host
        token; a throwaway unless the chunk was ``final``."""
        if pending.reconciled:
            raise RuntimeError("PendingPrefill already reconciled")
        pending.reconciled = True
        tw = time.perf_counter()
        token = int(pending.token)          # device sync
        self.last_chunk_finite = bool(pending.finite)
        self.device_wait_s += time.perf_counter() - tw
        if not self.last_chunk_finite:
            self._count_nonfinite(1)
        if self._registry is not None:
            self._registry.observe(
                "serving.prefill_chunk_s",
                pending.dispatch_s + time.perf_counter() - tw)
            self._registry.counter_inc("serving.prefill.chunks")
            if pending.final:
                self._registry.counter_inc("serving.tokens_generated")
        if pending.final:
            self.tokens_generated += 1
        return token

    def prefill_chunked(self, slot: int, prompt: Sequence[int],
                        temperature: float = 0.0) -> int:
        """Drain a whole prompt through the chunk-prefill program
        back-to-back and return the first sampled token — the chunked
        counterpart of :meth:`prefill` for callers without a scheduler
        (warmup, parity tests, ``--generate``). Production serving
        interleaves the same chunks with decode steps instead
        (:class:`~apex_tpu.serving.Scheduler`)."""
        n = len(prompt)
        if not 0 < n <= self.prefill_len:
            raise ValueError(f"prompt length {n} not in (0, "
                             f"prefill_len={self.prefill_len}]")
        token = None
        for lo in range(0, n, self.chunk_len):
            hi = min(lo + self.chunk_len, n)
            token = self.prefill_chunk(slot, list(prompt[lo:hi]), lo,
                                       temperature, final=hi == n)
        return token

    def chunks_for(self, prompt_len: int) -> int:
        """Chunk-prefill steps a prompt of ``prompt_len`` costs
        (``ceil(prompt_len / chunk_len)``)."""
        return -(-int(prompt_len) // self.chunk_len)

    def copy_kv(self, src: int, dst: int, length: int) -> None:
        """The contiguous layout's fourth compiled program: copy row
        ``src``'s K/V into row ``dst`` and set ``dst``'s length to
        ``length`` (traced scalars — one executable serves every
        donor/destination/length triple). Rows address serving slots AND
        prefix-pool rows, so registration (slot → pool row) and
        restoration (pool row → admitted slot) are the same program.
        Cheap by construction: one ``[layers, heads, max_len, head_dim]``
        device-to-device copy, no attention or MLP compute. RETIRED on
        the paged path — prefix reuse there is a page-refcount bump
        (:meth:`attach_prefix` / :meth:`retain_prefix`), zero data
        movement — so a paged engine refuses to compile it."""
        if self.paged:
            raise RuntimeError(
                "copy_kv is retired on the paged engine: prefix hits "
                "share pages (copy-on-write) instead of copying rows — "
                "use attach_prefix/retain_prefix, or build "
                "Engine(paged=False) for the contiguous baseline")
        rows = self.slots + self.prefix_pool
        if not 0 <= src < rows or not 0 <= dst < rows:
            raise ValueError(f"copy rows ({src} -> {dst}) must be in "
                             f"[0, {rows})")
        if src == dst:
            raise ValueError("copy source and destination must differ")
        if not 0 < length <= self.max_len:
            raise ValueError(f"copy length {length} not in (0, "
                             f"max_len={self.max_len}]")
        t0 = time.perf_counter()
        self.cache = self._jit_copy(self.cache, np.int32(src),
                                    np.int32(dst), np.int32(length))
        if self._registry is not None:
            self._registry.observe("serving.prefix.copy_s",
                                   time.perf_counter() - t0)

    def restore_prefix(self, slot: int, row: int, length: int) -> None:
        """Admission-time prefix hit: pool row ``row``'s first
        ``length`` positions become serving ``slot``'s cache prefix; the
        scheduler then resumes chunk prefill at offset ``length``."""
        self.copy_kv(row, slot, length)

    def store_prefix(self, row: int, slot: int, length: int) -> None:
        """Registration: retain serving ``slot``'s first ``length``
        positions (a completed, block-aligned prompt prefix) in pool row
        ``row``."""
        self.copy_kv(slot, row, length)

    def _with_prefill_blocks(self, fn):
        """Run ``fn`` with the ``decode.prefill_block_q``/``_k`` tuned
        keys temporarily installed as the flash-attention geometry.
        Blocks resolve at TRACE time, so this bites exactly once — on
        the call that traces the prefill program — and the training
        ``flash.*`` values are restored before anything else traces."""
        if self._pf_bq is None and self._pf_bk is None:
            return fn()
        keys = ("flash.block_q", "flash.block_k")
        saved = {k: vmem.overrides().get(k) for k in keys}
        for k, v in zip(keys, (self._pf_bq, self._pf_bk)):
            if v:
                vmem.set_override(k, v)
        try:
            return fn()
        finally:
            for k in keys:
                if saved[k] is None:
                    vmem.remove_override(k)
                else:
                    vmem.set_override(k, saved[k])

    # ------------------------------------------------- paged host bookkeeping
    def _require_paged(self, what: str) -> None:
        if not self.paged:
            raise RuntimeError(f"{what} is a paged-engine operation; "
                               "this engine was built with paged=False")

    def _alloc_page(self, slot: int) -> int:
        """One fresh page for ``slot`` (drawing down its admission
        reservation when it has one). Pool pressure first evicts LRU
        prefix entries — retained prefixes are a cache, live requests
        are not — then fails loudly: with scheduler-driven admission the
        reservation makes this unreachable; a direct caller that
        overcommits gets an exception, not silent corruption."""
        reserved = self._slot_reserved[slot] > 0
        page = self.pool.alloc(reserved=reserved)
        while page is None and self.prefix_cache is not None \
                and self.prefix_cache.evict_lru():
            page = self.pool.alloc(reserved=reserved)
        if page is None:
            raise RuntimeError(
                f"KV page pool exhausted ({self.num_pages} pages, "
                f"page_len={self.page_len}) — admit through the "
                "scheduler (page reservation) or build a larger pool")
        if reserved:
            self._slot_reserved[slot] -= 1
        return page

    def _grow_slot(self, slot: int, n_pages: int) -> None:
        """Ensure ``slot`` owns at least ``n_pages`` pages (appending
        fresh ones to its table row)."""
        have = int(self._n_pages[slot])
        for i in range(have, min(int(n_pages), self.max_pages)):
            self._page_table[slot, i] = self._alloc_page(slot)
            self._n_pages[slot] = i + 1

    def release_slot(self, slot: int,
                     keep_reservation: bool = False) -> None:
        """Return ``slot``'s pages to the pool (refcounts decide whether
        each is truly freed — pages shared with a retained prefix or
        another slot live on) and reset its table row to the sentinel.
        The scheduler calls this the moment a request finishes — paged
        reclamation is immediate, not deferred to the next overwrite.
        ``keep_reservation`` preserves the slot's admission reservation
        (the cold-start path inside an admitted request)."""
        self._require_paged("release_slot")
        n = int(self._n_pages[slot])
        if n:
            self.pool.release(self._page_table[slot, :n].tolist())
        self._page_table[slot, :] = 0
        self._n_pages[slot] = 0
        self._host_len[slot] = 0
        if not keep_reservation and self._slot_reserved[slot]:
            self.pool.unreserve(int(self._slot_reserved[slot]))
            self._slot_reserved[slot] = 0

    def pages_required(self, prompt_len: int, max_new_tokens: int,
                       monolithic: bool = False) -> int:
        """Worst-case pages a request can touch: the padded prefill
        extent (whole chunks — or the whole ``prefill_len`` window on
        the monolithic path) or the decode growth to its token budget,
        whichever reaches further, all capped at ``max_len``. The
        scheduler reserves this at admission so mid-decode allocation
        can never fail. Deliberately ignores any prefix-hit discount —
        conservative admission keeps the hit/miss counters exact (the
        match runs only for requests that actually admitted)."""
        self._require_paged("pages_required")
        if monolithic:
            prefill_extent = self.prefill_len
        else:
            prefill_extent = min(self.chunks_for(prompt_len)
                                 * self.chunk_len, self.max_len)
        occupied = min(int(prompt_len) + int(max_new_tokens),
                       self.max_len)
        return self.pool.pages_for(max(prefill_extent, occupied))

    def try_reserve_slot(self, slot: int, n_pages: int) -> bool:
        """The scheduler's admission gate: set aside ``n_pages`` for
        ``slot``, evicting LRU prefix entries while the pool cannot
        cover the promise. False (nothing changed) when even a fully
        drained prefix cache leaves the pool short — the request stays
        queued."""
        self._require_paged("try_reserve_slot")
        n_pages = int(n_pages)
        while self.pool.available < n_pages:
            if self.prefix_cache is None \
                    or not self.prefix_cache.evict_lru():
                return False
        if not self.pool.reserve(n_pages):
            return False            # unreachable given the loop above
        self._slot_reserved[slot] += n_pages
        return True

    # ------------------------------------------------- hierarchical KV tier
    def _on_host_tier_evict(self, key: int) -> None:
        """The host arena evicted ``key``'s bytes under capacity
        pressure: the swapped index entry now has no backing anywhere —
        drop it (a dangling swapped entry would be the exact rot the
        auditor's cross-tier walk flags). On a SHARED arena every
        co-owning engine hears every eviction — the drop is a no-op
        for keys this engine never indexed, and only the owner ticks
        the eviction counter (N engines must not count one eviction N
        times)."""
        owned = self.prefix_cache.drop(key)
        if self._registry is not None:
            if owned or not self.host_tier_shared:
                self._registry.counter_inc("serving.swap.host_evictions")
            self._registry.gauge_set("serving.swap.host_bytes",
                                     float(self.host_tier.bytes_used))

    def _dispatch_swap_out(self, key, pages) -> bool:
        """The prefix cache's swap-out hook — the ADMISSION-SIDE half
        of a (by default asynchronous) page migration, and the
        dispatch-ahead region the force-early lint covers BY NAME: no
        ``int()`` / ``float()`` / ``np.asarray`` / ``jax.device_get``
        may appear here, because a single forced read silently reverts
        the whole tier to the synchronous admission stall with zero
        token-level symptom (the bytes are right either way — only
        the wall-clock rots).

        Reserves the entry's arena bytes (capacity eviction and the
        decline decision run NOW, on this thread, so async and sync
        arena states evolve identically), DISPATCHES the fixed-shape
        compiled ``swap_out`` gather — the pool-byte snapshot is taken
        by program order at dispatch, BEFORE the caller releases the
        entry's pages for reuse — and hands the un-forced device
        blocks to the :class:`~apex_tpu.serving.SwapWorker`
        (:meth:`_complete_swap_out` forces, checksums and stores them
        off the hot path; ``sync_swap=True`` runs that half inline —
        the pre-async behaviour). False (the caller destroys instead)
        when the tier declines — an entry bigger than the whole arena.
        The admission-path cost of the whole hook is observed as
        ``serving.swap.admit_stall_s`` — the histogram the bench's
        sync-vs-async claim reads."""
        tier = self.host_tier
        if tier is None:
            return False
        m = len(pages)
        if m > self.max_pages:
            return False            # cannot happen by construction
        t0 = time.perf_counter()
        c = self.cache
        # the reservation is pure shape arithmetic — no device read:
        # K and V, m whole pages each, in the pool's storage dtype
        nbytes = 2 * m * c.layers * c.heads * c.page_len * c.head_dim \
            * np.dtype(c.dtype).itemsize
        if not tier.put_pending(key, nbytes, shards=self.tp):
            return False
        # SHAPE-STABLE dispatch: pad the gather to max_pages with the
        # page-0 sentinel (harmless garbage, sliced off by the worker)
        # so every swap-out of every entry size shares one compiled
        # gather — an entry-sized gather would silently recompile
        # mid-serve the first time an unseen page count appears. The
        # gather is UNDONATED, so even this CPU backend dispatches it
        # asynchronously (~0.1 ms) instead of executing it inline.
        ids = np.zeros(self.max_pages, np.int32)
        ids[:m] = list(pages)
        k_dev, v_dev = self._runtime_call(
            lambda: self._jit_swap_out(self.cache, jnp.asarray(ids)))
        tr = self._tracer
        ctx = None
        if tr is not None:
            # the admission-side span: dispatch cost only (nbytes and
            # m are pure shape arithmetic — this hook, like the rest
            # of the region, performs no forced read); the trace
            # binding is captured NOW so the worker-side store span
            # joins the same request's trace from its own thread
            ctx = tr.current()
            tr.event_current("swap_out", t0=t0, dur=tr.now() - t0,
                             key=key, pages=m, bytes=nbytes)
        job = lambda: self._complete_swap_out(  # noqa: E731
            key, k_dev, v_dev, m, t0, trace_id=ctx)
        if self._swap_worker is None:
            job()                   # sync_swap: the measurable baseline
        else:
            self._swap_worker.submit(key, job)
        if self._registry is not None:
            self._registry.observe("serving.swap.admit_stall_s",
                                   time.perf_counter() - t0)
            self._registry.gauge_set(
                "serving.swap.swap_out_queue_depth",
                0.0 if self._swap_worker is None
                else len(self._swap_worker.pending_keys()))
        return True

    def _complete_swap_out(self, key, k_dev, v_dev, m: int,
                           t0: float, trace_id=None) -> None:
        """The WORKER-SIDE half of a swap-out: force the dispatched
        snapshot blocks to host (the memcpy the async tier moves off
        the admission path), slice off the sentinel padding, and
        complete the arena's pending record (defensive copy + per-
        shard CRC inside :meth:`HostTier.complete`). A record evicted
        (or cleared) while the bytes were in flight discards silently
        — its index entry is already gone. Runs on the
        :class:`~apex_tpu.serving.SwapWorker` thread by default
        (inline under ``sync_swap=True``); the registry is
        thread-safe, so the traffic counters land from here either
        way. On the WORKER thread the force deliberately does NOT
        touch :attr:`device_wait_s` — that ledger belongs to the
        scheduler thread's heartbeat split, and a worker-side force
        blocks nobody's beat; running INLINE (``sync_swap=True``, or
        the post-close degradation) it blocks the scheduler thread
        exactly like the pre-async path did, so the wait is charged —
        the sync baseline's duty-cycle split must not silently
        flatter itself."""
        tier = self.host_tier
        worker = self._swap_worker
        inline = worker is None \
            or threading.current_thread() is not worker._thread
        tw = time.perf_counter()
        k_host = np.asarray(k_dev)[:, :m]   # the deferred force
        v_host = np.asarray(v_dev)[:, :m]
        if inline:
            self.device_wait_s += time.perf_counter() - tw
        stored = tier.complete(key, k_host, v_host)
        tr = self._tracer
        if tr is not None and trace_id is not None:
            # emitted from whichever thread ran the force — the
            # serving-swap-worker daemon by default — with the trace
            # id captured at dispatch: honest cross-thread attribution
            tr.event(trace_id, "swap_out_store", t0=tw,
                     dur=time.perf_counter() - tw, key=key, pages=m,
                     bytes=k_host.nbytes + v_host.nbytes,
                     stored=stored, inline=inline)
        if not stored:
            return                  # evicted mid-flight: bytes dropped
        if self._registry is not None:
            self._registry.counter_inc("serving.swap.swapped_out_pages",
                                       int(m))
            self._registry.observe("serving.swap.out_s",
                                   time.perf_counter() - t0)
            self._registry.gauge_set("serving.swap.host_bytes",
                                     float(tier.bytes_used))

    def _count_swap_verify_failed(self) -> None:
        self.swap_verify_failed += 1
        if self._registry is not None:
            self._registry.counter_inc("serving.swap.verify_failed")

    def _trace_swap_in(self, t0: float, key: int, joined: bool,
                       outcome: str, pages: int) -> None:
        """One ``swap_in`` span per host→device migration attempt,
        attributed to the admitting request via the scheduler's
        thread-local binding (a no-op without a tracer or binding).
        ``outcome`` is ``restored`` / ``verify_failed`` (missing or
        checksum-failed bytes — the CRC verdict) / ``deferred`` (pool
        too tight); ``joined`` marks a hit that waited on its own
        in-flight swap-out."""
        tr = self._tracer
        if tr is not None:
            tr.event_current("swap_in", t0=t0,
                             dur=time.perf_counter() - t0, key=key,
                             joined=joined, outcome=outcome,
                             pages=pages, crc_ok=outcome != "verify_failed")

    def _swap_in(self, key: int):
        """Migrate a swapped prefix entry's page bytes host→device:
        pop + checksum-verify the arena record, allocate fresh pool
        pages (LRU-evicting resident prefixes under pressure, and only
        from capacity NOT promised to admitted requests), write each
        page through the one compiled ``swap_in`` program, and mark
        the entry resident on the new page ids (one refcount per page
        held by the entry, exactly like registration). Returns the
        full restored page list, or None on degradation:

        - missing / checksum-failed / wrong-geometry host bytes → the
          entry is DROPPED and ``serving.swap.verify_failed`` counts —
          a verified miss (the caller re-prefills), never a wrong
          token;
        - pool too tight even after draining resident prefixes → the
          bytes go BACK to the arena and the entry stays swapped (a
          later, less-pressured hit can still restore it).

        A hit racing its own IN-FLIGHT swap-out (the entry is still
        in the *swapping* state) first JOINS the worker's copy —
        counted as ``serving.swap.swap_join_waits``, the wait charged
        to :attr:`device_wait_s` like any forced device read — so the
        arena record is complete (or failed) before it is taken:
        partial bytes are unobservable by construction. A join that
        surfaces the worker job's exception degrades to the same
        verified miss as missing bytes."""
        tier, pcache = self.host_tier, self.prefix_cache
        t0 = time.perf_counter()
        joined = False
        if tier is not None and self._swap_worker is not None \
                and self._swap_worker.in_flight(key):
            joined = True
            if self._registry is not None:
                self._registry.counter_inc("serving.swap.swap_join_waits")
            tw = time.perf_counter()
            try:
                self._swap_worker.join(key)
            except Exception as e:  # noqa: BLE001 — degrade, never crash
                # the job died before completing: the record is still
                # pending, so take() below returns None and the hit
                # degrades to the usual verified miss
                _logger.warning("swap-out of entry %d failed on the "
                                "worker (%s: %s) — degrading its hit "
                                "to a verified miss", key,
                                type(e).__name__, e)
            self.device_wait_s += time.perf_counter() - tw
        rec = tier.take(key) if tier is not None else None
        if rec is None or not rec.valid:
            pcache.drop(key)
            self._count_swap_verify_failed()
            self._trace_swap_in(t0, key, joined, "verify_failed", 0)
            return None
        k_host, v_host = rec.k, rec.v
        c = self.cache
        want = (c.layers, k_host.shape[1] if k_host.ndim == 5 else -1,
                c.heads, c.page_len, c.head_dim)
        if k_host.shape != want or v_host.shape != want \
                or k_host.dtype != np.dtype(c.dtype) \
                or v_host.dtype != np.dtype(c.dtype):
            pcache.drop(key)
            self._count_swap_verify_failed()
            self._trace_swap_in(t0, key, joined, "verify_failed", 0)
            return None
        m = int(k_host.shape[1])
        if m > self.max_pages:
            pcache.drop(key)
            self._count_swap_verify_failed()
            self._trace_swap_in(t0, key, joined, "verify_failed", 0)
            return None
        # unreserved allocation must never eat into admission promises:
        # draw only from `available` (free minus reserved), making room
        # by LRU-evicting (= swapping out) resident prefix entries
        while self.pool.available < m:
            if not pcache.evict_lru():
                tier.put(key, k_host, v_host, shards=rec.shards)
                _logger.debug("swap-in of entry %d deferred: pool too "
                              "tight for %d pages", key, m)
                self._trace_swap_in(t0, key, joined, "deferred", 0)
                return None
        pages = [self.pool.alloc() for _ in range(m)]
        # one fixed-shape dispatch restores the whole entry: pad the
        # block to max_pages, trailing ids to the page-0 sentinel
        # (its garbage absorbs the padded writes — the inactive-slot
        # idiom), so every swap-in of every entry size shares ONE
        # executable and ONE dispatch
        P = self.max_pages
        blk_shape = (c.layers, P, c.heads, c.page_len, c.head_dim)
        k_blk = np.zeros(blk_shape, k_host.dtype)
        v_blk = np.zeros(blk_shape, v_host.dtype)
        k_blk[:, :m], v_blk[:, :m] = k_host, v_host
        ids = np.zeros(P, np.int32)
        ids[:m] = pages
        self.cache = self._runtime_call(
            lambda: self._jit_swap_in(self.cache, jnp.asarray(k_blk),
                                      jnp.asarray(v_blk),
                                      jnp.asarray(ids)))
        pcache.swap_in_complete(key, pages)
        self._trace_swap_in(t0, key, joined, "restored", m)
        if self._registry is not None:
            self._registry.counter_inc("serving.swap.swapped_in_pages",
                                       m)
            self._registry.counter_inc("serving.swap.hit_after_swap")
            self._registry.observe("serving.swap.in_s",
                                   time.perf_counter() - t0)
            self._registry.gauge_set("serving.swap.host_bytes",
                                     float(tier.bytes_used))
        return pages

    def attach_prefix(self, slot: int, match) -> bool:
        """Admission-time prefix hit, paged style: the matched entry's
        pages become the head of ``slot``'s page table by refcount bump
        — ZERO data movement (the contiguous layout paid a compiled
        row-copy here). Chunk prefill then resumes at the matched
        offset; the first write past the share lands on a fresh page by
        construction (matches are chunk-aligned, chunks cover whole
        pages). Pages the hit shares are refunded from the slot's
        conservative admission reservation.

        A ``match.swapped`` hit (hierarchical KV) first migrates the
        entry's page bytes back from the host tier (:meth:`_swap_in`);
        on success the restored pages share exactly like a resident
        hit. Returns False — with NOTHING attached (the caller must
        treat the admission as a miss and re-prefill cold) — when the
        swap-in degraded; True on every attached hit."""
        self._require_paged("attach_prefix")
        if getattr(match, "swapped", False):
            restored = self._swap_in(match.row)
            if restored is None:
                return False
            k = match.length // self.page_len
            match = dataclasses.replace(
                match, pages=tuple(restored[:k]), swapped=False)
        pages = list(match.pages)
        if match.length != len(pages) * self.page_len:
            raise ValueError(
                f"prefix match length {match.length} does not cover "
                f"whole pages (page_len={self.page_len})")
        self.release_slot(slot, keep_reservation=True)
        self.pool.share(pages)
        self._page_table[slot, :len(pages)] = pages
        self._n_pages[slot] = len(pages)
        self._host_len[slot] = match.length
        refund = min(len(pages), int(self._slot_reserved[slot]))
        if refund:
            self._slot_reserved[slot] -= refund
            self.pool.unreserve(refund)
        return True

    def retain_prefix(self, slot: int, prompt: Sequence[int],
                      keys: Optional[Sequence[int]] = None) -> str:
        """Registration, paged style: retain ``prompt``'s block-aligned
        prefix by SHARING the pages that already hold it in ``slot`` —
        no copy, no reserved rows. Returns the
        :meth:`PrefixCache.register` outcome; on ``"registered"`` the
        entry holds its own refcount on each page (released at entry
        eviction), so the prefix survives the slot. ``keys`` are the
        prompt's precomputed rolling block keys (the pipelined
        scheduler's hash offload; None hashes inline)."""
        self._require_paged("retain_prefix")
        if self.prefix_cache is None:
            raise RuntimeError("engine built without a prefix cache "
                               "(prefix_pool=0)")
        n_blocks = len(prompt) // self.chunk_len
        length = n_blocks * self.chunk_len
        n_pages = length // self.page_len
        pages = tuple(int(p) for p in self._page_table[slot, :n_pages])
        outcome = self.prefix_cache.register(prompt, pages=pages,
                                             keys=keys)
        if outcome == "registered":
            self.pool.share(pages)
        return outcome

    def export_handoff(self, slot: int, key: int,
                       prompt: Sequence[int],
                       keys: Optional[Sequence[int]] = None) -> int:
        """Disaggregated-serving EXPORT: land ``slot``'s ingested
        prefix of ``prompt`` in the host arena under the request's own
        ``key`` (its uid — positive and globally unique, so records
        from every engine sharing one arena coexist), ready for a
        decode-role replica to restore. Two existing mechanisms back
        to back, zero new compiled programs:

        1. :meth:`PrefixCache.register_handoff` retains the prefix as
           an ordinary paged entry on the slot's own pages (refcount
           share, no copy) — capped at ``aligned(n - 1)`` blocks
           exactly like every registration, because the final chunk
           must run through the importer's chunk-prefill program to
           sample the first token;
        2. :meth:`PrefixCache.swap_out_key` migrates it straight to
           the arena through the (async, per-shard-CRC'd, fixed-shape)
           ``swap_out`` gather — the same dispatch the pressure path
           uses, so ``serving.swap.*`` telemetry covers handoff bytes
           and latency for free.

        Returns the exported aligned length (the importer's exact
        resume offset), or 0 when nothing could be exported — prompt
        spans no full block, no tier, or the arena declined — in
        which case the importer simply re-prefills cold (an entry the
        arena declined stays RESIDENT here as an ordinary local
        prefix). Counts ``serving.disagg.handoff_bytes``."""
        self._require_paged("export_handoff")
        if self.prefix_cache is None or self.host_tier is None:
            return 0
        n_blocks = (len(prompt) - 1) // self.chunk_len
        if n_blocks == 0:
            return 0
        length = n_blocks * self.chunk_len
        if int(self._host_len[slot]) < length:
            raise RuntimeError(
                f"slot {slot} has ingested {int(self._host_len[slot])}"
                f" tokens of the {length}-token handoff prefix — "
                "export runs at ingestion completion, not before")
        n_pages = length // self.page_len
        pages = tuple(int(p) for p in self._page_table[slot, :n_pages])
        outcome = self.prefix_cache.register_handoff(
            key, prompt[:length], pages=pages, keys=keys)
        if outcome != "registered":
            return 0
        self.pool.share(pages)
        if not self.prefix_cache.swap_out_key(key):
            return 0
        if self._registry is not None:
            self._registry.counter_inc(
                "serving.disagg.handoff_bytes",
                self.host_tier.nbytes_of(key))
        return length

    @property
    def pages_free(self) -> int:
        """Free pages in the paged pool right now (0 on the contiguous
        layout) — the cheap host-only capacity gauge the router's
        least-loaded admission reads per routed request, without the
        fragmentation walk :meth:`pool_stats` pays."""
        return self.pool.free_pages if self.paged else 0

    def slot_pages(self, slot: int) -> int:
        """Pages currently held by ``slot`` (0 on the contiguous
        layout) — host bookkeeping only. The scheduler sums this over
        low-priority running slots for ``preemptible_pages``, the
        "reclaimable by preemption" headroom gauge in
        :meth:`Scheduler.load_snapshot`."""
        return int(self._n_pages[slot]) if self.paged else 0

    def pool_stats(self) -> dict:
        """Paged-pool telemetry snapshot: allocator counters plus the
        per-slot fragmentation view (allocated-but-invalid positions
        over allocated positions)."""
        self._require_paged("pool_stats")
        stats = self.pool.stats()
        stats["fragmentation"] = self.pool.fragmentation(
            self._host_len, self._n_pages)
        return stats

    def decode_step(self, last_tokens, active, temperatures,
                    fault_bias=None) -> np.ndarray:
        """One decode step over every slot: ``last_tokens`` [slots] int
        (each slot's most recent token), ``active`` [slots] bool,
        ``temperatures`` [slots] float. Returns the next token per slot
        (host int32 array; inactive rows are noise to discard).

        This is the SYNCHRONOUS shape — :meth:`decode_dispatch`
        immediately followed by :meth:`decode_reconcile`, the depth-0
        oracle path of the async pipelined heartbeat. Both halves run
        the same compiled program over the same operands, so the split
        changes no bytes.

        ``fault_bias`` ([slots] float, default all-zero) is added to
        the fp32 logits rows inside the compiled program — the chaos
        harness's per-slot NaN/Inf injection point (+0.0 elsewhere is
        value-identical, so healthy slots keep their exact tokens).
        The in-program finiteness verdict lands in
        :attr:`last_decode_finite` ([slots] bool); slots flagged False
        sampled from non-finite logits and must be quarantined, not
        trusted."""
        pending = self.decode_dispatch(last_tokens, active, temperatures,
                                       fault_bias=fault_bias)
        out, _finite, _dt = self.decode_reconcile(pending)
        return out

    def decode_dispatch(self, last_tokens, active, temperatures,
                        fault_bias=None) -> PendingDecode:
        """DISPATCH one decode step and return without waiting for it:
        the compiled call is enqueued on the device (JAX async
        dispatch), host bookkeeping advances speculatively (paged
        lengths grow by one for each active slot — pure arithmetic, the
        same rollback-free contract as PR 8's speculative lengths), and
        the sampled tokens stay ON DEVICE inside the returned
        :class:`PendingDecode` until :meth:`decode_reconcile` reads
        them back in one batched transfer.

        ``last_tokens`` may be a HOST int array or a DEVICE array — in
        particular the previous pending step's un-forced ``tokens`` —
        which is what lets the pipelined heartbeat chain decode step
        t+1 onto step t's output without the host ever touching the
        token values: the data dependency stays on the device, and the
        host think-time (drafting, admission, telemetry) overlaps the
        device's execution of the steps in flight.

        Nothing here counts tokens or observes latency — a dispatched
        token is not an emitted token until the reconcile decides it
        survived (a slot that turned out to finish mid-pipeline
        discards its speculated successors), so all accounting lives in
        :meth:`decode_reconcile`."""
        if fault_bias is None:
            fault_bias = np.zeros(self.slots, np.float32)
        else:
            fault_bias = np.asarray(fault_bias, np.float32)
            if fault_bias.shape != (self.slots,):
                raise ValueError(f"fault_bias {fault_bias.shape} must "
                                 f"be [{self.slots}]")
        act = np.asarray(active, bool)
        t0 = time.perf_counter()
        if self.paged:
            # write-then-attend writes at host_len: make sure each
            # active slot's write page exists BEFORE the program runs
            # (reservation at admission guarantees the pool can cover
            # it; a slot at max_len clamps onto its last page)
            for s in np.flatnonzero(act):
                pos = int(self._host_len[s])
                if pos < self.max_len:
                    self._grow_slot(s, self.pool.pages_for(pos + 1))
            self.cache, tokens, finite = self._runtime_call(
                lambda: self._jit_decode(
                    self.params, self.cache,
                    jnp.asarray(last_tokens, jnp.int32),
                    jnp.asarray(self._page_table),
                    jnp.asarray(self._host_len),
                    jnp.asarray(temperatures, jnp.float32),
                    jnp.asarray(fault_bias), self._next_key(),
                    *self._lora_args()))
            grow = act & (self._host_len < self.max_len)
            self._host_len[grow] += 1
        else:
            self.cache, tokens, finite = self._runtime_call(
                lambda: self._jit_decode(
                    self.params, self.cache,
                    jnp.asarray(last_tokens, jnp.int32),
                    jnp.asarray(act),
                    jnp.asarray(temperatures, jnp.float32),
                    jnp.asarray(fault_bias), self._next_key(),
                    *self._lora_args()))
        return PendingDecode(tokens=tokens, finite=finite, active=act,
                             t_dispatch=t0)

    def decode_reconcile(self, pending: PendingDecode, valid=None):
        """Read a dispatched decode step back to the host — ONE batched
        token transfer per step, never per-slot ``int()`` calls against
        device arrays — and account for it. Returns ``(tokens, finite,
        step_s)``: host int32 ``[slots]``, host bool ``[slots]``, and
        the dispatch→retire wall seconds (observed as
        ``serving.decode.step_s``; in sync mode this is exactly the old
        per-step measurement, in pipelined mode it still bounds the
        device's execution latency from above).

        ``valid`` ([slots] bool, default the dispatch mask) marks the
        slots whose token the caller will actually consume: the
        pipelined scheduler excludes slots whose request finished (or
        was quarantined / expired) while this step was in flight, so
        ``tokens_generated`` counts only emitted tokens and stays
        comparable with the sync path serving the same stream. The
        block time is charged to :attr:`device_wait_s`; the finiteness
        verdict lands in :attr:`last_decode_finite`."""
        if pending.reconciled:
            raise RuntimeError("PendingDecode already reconciled — each "
                               "dispatched step reads back exactly once")
        pending.reconciled = True
        valid = pending.active if valid is None \
            else np.asarray(valid, bool)
        tw = time.perf_counter()
        out = np.asarray(pending.tokens)    # device sync: step latency
        finite = np.asarray(pending.finite, bool)
        now = time.perf_counter()
        self.device_wait_s += now - tw
        dt = now - pending.t_dispatch
        self.last_decode_finite = finite
        bad = int(np.sum(valid & ~finite))
        if bad:
            self._count_nonfinite(bad)
        n_valid = int(np.sum(valid))
        self.tokens_generated += n_valid
        if self._registry is not None:
            self._registry.observe("serving.decode.step_s", dt)
            self._registry.counter_inc("serving.decode.steps")
            self._registry.counter_inc("serving.tokens_generated",
                                       n_valid)
        return out, finite, dt

    def sync(self) -> None:
        """Explicit device barrier: block until every dispatched
        program (decode steps in flight included) has retired. The
        pipelined heartbeat never needs this for correctness — the
        cache is threaded through every call, so program order IS
        dispatch order — but benches and tests use it to close a
        timing window, and the wait is charged to
        :attr:`device_wait_s` like any other forced sync."""
        tw = time.perf_counter()
        jax.block_until_ready(jax.tree_util.tree_leaves(self.cache))
        self.device_wait_s += time.perf_counter() - tw

    def _runtime_call(self, fn):
        """Invoke one compiled program, charging the call's block time
        to :attr:`device_wait_s`. On real accelerators JAX dispatch is
        asynchronous — the call returns in ~µs and the real wait
        surfaces at the forced read — but the CPU backend executes
        DONATED-buffer programs synchronously inside the call (the
        cache is donated on every program here), so without this the
        whole device execution would masquerade as host think-time,
        inverting the ``serving.heartbeat.*`` split and letting
        healthy CPU decode breach the watchdog's host budget. The ~µs
        of true dispatch overhead this misattributes on silicon is
        noise."""
        t0 = time.perf_counter()
        out = fn()
        self.device_wait_s += time.perf_counter() - t0
        return out

    def verify_batch(self, drafts, *, fault_bias=None, offsets=None):
        """One speculative draft-and-verify step for EVERY verifying
        slot at once: ``drafts`` maps ``slot -> (last_token,
        draft_tokens)`` and the whole map is scored by the ONE compiled
        ``[slots, K+1]`` verify program — B verify-eligible slots share
        one program invocation instead of B sequential calls (the same
        fixed-shape discipline as the decode step: slots not in the map
        ride along as padding — their cache bytes are provably
        untouched — and that waste is the price of one executable).

        Each verifying row embeds ``[last_token, d_1 .. d_K]`` at the
        slot's committed length (exactly where a plain decode step
        would write), runs shifted-causal attention, and computes
        ACCEPT-LONGEST-PREFIX in-program. Returns ``(tokens,
        n_accepted)``: ``tokens`` [slots, K+1] int32 greedy targets —
        row ``s``'s ``tokens[s, :n_accepted[s] + 1]`` is that slot's
        emitted output — and ``n_accepted`` [slots] int32 (0 on
        non-verifying rows). Greedy-only; fewer than ``draft_len``
        drafts per row are padded to the fixed shape and excluded from
        acceptance. Every verifying slot needs ``0 < offset`` and
        ``offset + draft_len + 1 <= max_len`` (the scheduler's endgame
        gate) — violated windows raise HERE, on both layouts, before
        anything mutates (the contiguous check reads the device
        lengths: a sync, priced into the parity-oracle path — a
        silently-masked row would return ``n_accepted = 0`` with
        nothing committed, indistinguishable from a real zero-accept
        verify, and the caller would emit a token whose K/V never
        landed).

        ``offsets`` (optional ``{slot: expected_offset}``) cross-checks
        the caller's bookkeeping against each verifying slot's
        committed length and raises on drift — the scheduler passes its
        computed offsets so scheduler-vs-engine divergence stays a loud
        error, exactly as the per-slot path always guaranteed.

        ``fault_bias`` ([slots] float, default all-zero) is the chaos
        harness's per-row injection operand. Per-slot verdicts land in
        :attr:`last_verify_finite_slots` (non-verifying rows always
        read True); a False verdict means that row's tokens are garbage
        — quarantine that slot, don't emit.
        """
        if self.spec is None:
            raise RuntimeError(
                "verify_batch needs an engine built with "
                "spec=SpecConfig(...) — the verify program's "
                "[slots, K+1] shape is fixed at construction")
        if not drafts:
            raise ValueError("verify_batch needs at least one "
                             "verifying slot (empty drafts are the "
                             "plain-decode fallback)")
        K = self.spec.draft_len
        tokens = np.zeros((self.slots, K + 1), np.int32)
        n_drafted = np.zeros(self.slots, np.int32)
        for slot, (last_token, d) in drafts.items():
            slot = int(slot)
            if not 0 <= slot < self.slots:
                raise ValueError(f"slot {slot} not in [0, {self.slots})")
            n = len(d)
            if not 1 <= n <= K:
                raise ValueError(f"draft length {n} not in [1, "
                                 f"draft_len={K}] (an empty draft is "
                                 "the plain-decode fallback, not a "
                                 "verify)")
            tokens[slot, 0] = int(last_token)
            tokens[slot, 1:1 + n] = np.asarray(d, np.int32)
            n_drafted[slot] = n
        active = n_drafted > 0
        if fault_bias is None:
            fault_bias = np.zeros(self.slots, np.float32)
        else:
            fault_bias = np.asarray(fault_bias, np.float32)
            if fault_bias.shape != (self.slots,):
                raise ValueError(f"fault_bias {fault_bias.shape} must "
                                 f"be [{self.slots}]")
        # validate EVERY verifying slot's window host-side, on BOTH
        # layouts, before anything mutates: a masked row would return
        # n_accepted=0 with nothing committed — indistinguishable from
        # a real zero-accept verify, so the caller would emit a bonus
        # token whose K/V never landed. The contiguous layout keeps
        # lengths on device, so this read is a device sync — an
        # acceptable price on the parity-oracle path for the same
        # loud-failure contract the paged path has always had.
        if self.paged:
            lens = self._host_len
        else:
            tw = time.perf_counter()
            lens = np.asarray(self.cache.lengths)[:self.slots]
            self.device_wait_s += time.perf_counter() - tw
        for s in np.flatnonzero(active):
            off = int(lens[s])
            if not 0 < off or off + K + 1 > self.max_len:
                raise ValueError(
                    f"verify window [{off}, {off + K + 1}) of slot "
                    f"{s} needs a committed prefix and must fit "
                    f"max_len={self.max_len}")
            if offsets is not None and s in offsets \
                    and int(offsets[s]) != off:
                raise ValueError(
                    f"verify offset {int(offsets[s])} disagrees with "
                    f"slot {s}'s committed length {off}")
        t0 = time.perf_counter()
        if self.paged:
            for s in np.flatnonzero(active):
                # the write extent must be backed by pages BEFORE the
                # program runs (reservation at admission guarantees the
                # pool can cover it when the scheduler gated the call)
                self._grow_slot(s, self.pool.pages_for(
                    int(self._host_len[s]) + K + 1))
            # non-verifying rows: sentinel-only table + offset 0, so
            # their fixed-shape writes can never land on a live page
            vt = np.where(active[:, None], self._page_table, 0)
            vlen = np.where(active, self._host_len, 0)
            self.cache, out, n_accepted, finite = self._runtime_call(
                lambda: self._jit_verify(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(vt.astype(np.int32)),
                    jnp.asarray(vlen.astype(np.int32)),
                    jnp.asarray(n_drafted), jnp.asarray(fault_bias),
                    *self._lora_args()))
        else:
            self.cache, out, n_accepted, finite = self._runtime_call(
                lambda: self._jit_verify(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(n_drafted), jnp.asarray(fault_bias),
                    *self._lora_args()))
        tw = time.perf_counter()
        # ONE batched readback per verify dispatch (tokens, acceptance,
        # verdicts) — the host never int()s a device element per slot
        out = np.asarray(out)           # device sync: step latency
        n_accepted = np.asarray(n_accepted, np.int32)
        finite = np.asarray(finite, bool)
        self.device_wait_s += time.perf_counter() - tw
        if self.paged:
            # rollback IS this assignment, per slot: the rejected tail's
            # K/V sits at [offset + m + 1, offset + K + 1), past the
            # committed length — unreachable, and overwritten
            # write-then-attend by the slot's next decode/verify step
            for s in np.flatnonzero(active):
                self._host_len[s] = int(self._host_len[s]) \
                    + int(n_accepted[s]) + 1
        self.last_verify_finite_slots = np.where(active, finite, True)
        # keep the long-standing scalar attribute live too: a caller
        # written against the pre-batching API must not read a stale
        # True past a batched verify that flagged a row
        self.last_verify_finite = bool(self.last_verify_finite_slots
                                       .all())
        bad = int(np.sum(active & ~finite))
        if bad:
            self._count_nonfinite(bad)
        emitted = int(np.sum(n_accepted[active])) + int(active.sum())
        self.tokens_generated += emitted
        if self._registry is not None:
            self._registry.observe("serving.spec.verify_s",
                                   time.perf_counter() - t0)
            self._registry.counter_inc("serving.spec.verify_slots",
                                       int(active.sum()))
            self._registry.counter_inc("serving.tokens_generated",
                                       emitted)
        return out, n_accepted

    def verify_step(self, slot: int, last_token: int,
                    drafts: Sequence[int], offset: int, *,
                    fault_bias: float = 0.0):
        """One speculative draft-and-verify step for a single ``slot``
        — a thin wrapper routing through the SAME compiled
        ``[slots, K+1]`` batched program as :meth:`verify_batch` (one
        executable either way; the other rows ride along as padding
        with their cache bytes untouched). Returns ``(tokens,
        n_accepted)`` for the slot: ``tokens`` [K+1] int32 greedy
        targets, ``tokens[:n_accepted + 1]`` the emitted output.
        ``offset`` must equal the slot's committed length and the
        padded window must fit: ``offset + draft_len + 1 <= max_len``.
        The finiteness verdict lands in :attr:`last_verify_finite`."""
        if self.spec is None:
            raise RuntimeError(
                "verify_step needs an engine built with "
                "spec=SpecConfig(...) — the verify program's "
                "[slots, K+1] shape is fixed at construction")
        # draft-length and slot-range validation live in verify_batch
        # (one copy of the contract); only the CALLER-offset window
        # check is this wrapper's own — it validates the argument
        # itself, where verify_batch validates the committed length
        K = self.spec.draft_len
        offset = int(offset)
        if not 0 < offset or offset + K + 1 > self.max_len:
            raise ValueError(
                f"verify window [{offset}, {offset + K + 1}) needs a "
                f"committed prefix and must fit max_len={self.max_len}")
        if not 0 <= slot < self.slots:
            raise ValueError(f"slot {slot} not in [0, {self.slots})")
        bias = np.zeros(self.slots, np.float32)
        bias[slot] = fault_bias
        # verify_batch validates the committed-length window and the
        # offset cross-check (both layouts) before anything mutates
        out, n_accepted = self.verify_batch(
            {slot: (last_token, list(drafts))}, fault_bias=bias,
            offsets={slot: offset})
        self.last_verify_finite = bool(
            self.last_verify_finite_slots[slot])
        return out[slot], int(n_accepted[slot])

    def _count_nonfinite(self, n: int) -> None:
        """One quarantine-worthy non-finite sampling event per affected
        slot: the ``serving.faults.nonfinite`` counter plus the host
        tally (kept registry-less so direct callers see it too)."""
        self.nonfinite_events += int(n)
        if self._registry is not None:
            self._registry.counter_inc("serving.faults.nonfinite",
                                       int(n))

    def page_table_snapshot(self):
        """DEBUG COPIES of the paged host state — ``(page_table,
        n_pages)`` numpy arrays safe to mutate (the chaos harness's
        :meth:`FaultPlan.corrupt_page_table` target and the
        :class:`~apex_tpu.serving.PoolAuditor`'s corruption-detection
        probe). Never hands out the live arrays."""
        self._require_paged("page_table_snapshot")
        return self._page_table.copy(), self._n_pages.copy()

    def lengths(self) -> np.ndarray:
        """Host view of per-slot cache lengths (host state on the paged
        path; a device read on the contiguous one)."""
        if self.paged:
            return self._host_len[:self.slots].copy()
        tw = time.perf_counter()
        out = np.asarray(self.cache.lengths)    # device sync
        self.device_wait_s += time.perf_counter() - tw
        return out

    def close(self) -> None:
        """Stop the engine's :class:`~apex_tpu.serving.SwapWorker`
        thread (no-op without a host tier or under ``sync_swap``;
        idempotent — the weakref finalizer registered at construction
        runs the same stop). The stop DRAINS first: swap-outs queued
        at kill time complete their arena puts, so a replica killed
        with a non-empty swap queue still reconciles — the cross-tier
        audit walks clean, nothing dangles. After close the engine
        stays usable: further swap-outs run inline (the sync
        degradation)."""
        if self._swap_worker is not None:
            self._swap_worker.stop()

    def set_registry(self, registry) -> None:
        """Swap the telemetry registry (e.g. after a compile-warmup pass,
        so first-trace latency never poisons the serving histograms)."""
        self._registry = registry
        self._emit_tp_gauges()
        self._emit_kv_gauges()
        self._emit_wq_gauges()
        if self.lora is not None:
            self.lora.set_registry(registry)
        self._emit_lora_gauges()

    def set_tracer(self, tracer) -> None:
        """Install a request tracer (``Scheduler(tracer=...)`` calls
        this); the engine's swap-path spans then attribute to the
        admitting request via the scheduler's thread-local binding."""
        self._tracer = tracer

    def reset(self, clear_prefixes: bool = False) -> None:
        """Zero the serving-slot lengths (slot table wipe; K/V left in
        place — length masking makes stale data unreachable). Retained
        prefixes SURVIVE a reset by default (they are warm state, not
        per-request state — a bench window reset must not throw away the
        cache it is measuring); pass ``clear_prefixes=True`` to drop
        them too. On the paged path the wipe also returns every slot's
        pages to the pool (retained prefixes keep theirs via their own
        refcounts)."""
        if self.lora is not None:
            # a slot wipe drops every live adapter binding; residency
            # (the arena rows) survives — warm state, like prefixes
            self._slot_adapter[:] = 0
            self.lora.release_all()
        if self.paged:
            for s in range(self.slots):
                self.release_slot(s)
            if clear_prefixes and self.prefix_cache is not None:
                # entry eviction releases each entry's page refs through
                # the pool (the on_evict hook). Swapped entries hold no
                # pages — their host-side bytes are dropped with the
                # arena below (warm resets keep BOTH tiers: a swapped
                # prefix is warm state exactly like a resident one).
                # A SHARED arena belongs to the whole fleet: discard
                # only this engine's own swapped keys, never clear()
                # the sibling engines' records out from under them.
                own_swapped = self.prefix_cache.swapped_keys()
                self.prefix_cache.clear()
                if self.host_tier is not None:
                    if self.host_tier_shared:
                        for k in own_swapped:
                            self.host_tier.discard(k)
                    else:
                        self.host_tier.clear()
                    if self._registry is not None:
                        self._registry.gauge_set(
                            "serving.swap.host_bytes",
                            float(self.host_tier.bytes_used))
            return
        lengths = self.cache.lengths
        if clear_prefixes:
            lengths = jnp.zeros_like(lengths)
            if self.prefix_cache is not None:
                self.prefix_cache.clear()
        else:
            lengths = lengths.at[:self.slots].set(0)
        self.cache = self.cache.replace(lengths=lengths)
