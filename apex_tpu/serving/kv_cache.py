"""Preallocated slot KV cache — the serving engine's only mutable state.

One device-resident pytree holds every request's attention history:

- ``k``/``v``: ``[layers, slots, heads, max_len, head_dim]`` — slot ``s``
  owns row ``[:, s]``; positions ``[0, lengths[s])`` are valid.
- ``lengths``: ``[slots]`` int32 — valid positions per slot (0 = free).

Storage dtype comes from the amp cast policies (bf16 by default — the
same ``half_dtype`` the O2/O3 tables resolve), halving HBM versus fp32
and feeding the decode kernel the dtype it upcasts per-tile anyway.

Slot semantics (the continuous-batching contract):

- **prefill** writes a request's prompt K/V into ``[0, P)`` of a free
  slot and sets its length; positions past the true prompt length hold
  pad garbage that is *never attended* (length masking) and is
  overwritten position-by-position as decode advances.
- **chunked prefill** ingests a prompt one chunk per decode heartbeat:
  :meth:`slot_view` hands the model one slot as a batch-of-one cache,
  the chunk's K/V lands at ``[offset, offset + C)``, and
  :meth:`write_slot` commits the view back with the grown length.
- **decode** writes each slot's new token at ``lengths[s]`` and then
  attends ``[0, lengths[s]]`` — write-then-attend, so garbage can never
  enter a softmax.
- **eviction** is free: a finished slot is just marked length-0 on the
  host; the next prefill overwrites it. No device-side compaction.
- **prefix pool**: an engine built with ``prefix_pool=N`` allocates N
  extra rows past its serving slots to retain popular prompt prefixes;
  :meth:`copy_slot` is the one compiled row-copy both directions share
  (register: slot → pool row; hit: pool row → fresh slot) and
  :meth:`front_view`/:meth:`advance_front` keep the decode batch off
  the pool rows.

Everything is functional: updates return a new :class:`KVCache` whose
buffers alias the old ones under jit donation (the engine donates the
cache to both of its compiled programs).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVCache"]


@flax.struct.dataclass
class KVCache:
    """Slot-major KV cache pytree (see module docstring for semantics)."""

    k: jnp.ndarray        # [layers, slots, heads, max_len, head_dim]
    v: jnp.ndarray        # [layers, slots, heads, max_len, head_dim]
    lengths: jnp.ndarray  # [slots] int32

    # ------------------------------------------------------------- geometry
    @property
    def layers(self) -> int:
        return self.k.shape[0]

    @property
    def slots(self) -> int:
        return self.k.shape[1]

    @property
    def heads(self) -> int:
        return self.k.shape[2]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    @property
    def dtype(self):
        return self.k.dtype

    def nbytes(self) -> int:
        """Device bytes held by the cache (both K and V)."""
        return int(self.k.size * self.k.dtype.itemsize * 2)

    # -------------------------------------------------------------- updates
    @classmethod
    def create(cls, *, layers: int, slots: int, heads: int, max_len: int,
               head_dim: int, dtype: Any = jnp.bfloat16) -> "KVCache":
        """Allocate a zeroed cache. ``dtype`` is normally the amp half
        dtype (``policy.half_dtype`` / ``compute_dtype`` — the serving
        engine resolves it from its policy)."""
        shape = (layers, slots, heads, max_len, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((slots,), jnp.int32))

    def insert(self, slot, k_new, v_new, length) -> "KVCache":
        """Write a prefilled request into ``slot``: ``k_new``/``v_new``
        are the model's stacked prefill K/V ``[layers, 1, heads, P, d]``
        (``P <= max_len``); the slot's length becomes ``length`` (the
        true prompt length — pad positions in ``[length, P)`` are masked
        by it). ``slot``/``length`` may be traced int32 scalars — the
        jitted prefill program is slot- and length-agnostic."""
        if k_new.ndim != 5 or k_new.shape[1] != 1:
            raise ValueError(f"insert expects [layers, 1, heads, P, d] "
                             f"prefill K/V, got {k_new.shape}")
        P = k_new.shape[3]
        if P > self.max_len:
            raise ValueError(f"prefill length {P} exceeds cache max_len "
                             f"{self.max_len}")
        slot = jnp.asarray(slot, jnp.int32)
        start = (jnp.int32(0), slot, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_new, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_new, self.v.dtype), start)
        lengths = self.lengths.at[slot].set(jnp.asarray(length, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def slot_view(self, slot):
        """The one-slot ``(k, v)`` pair (``[layers, 1, heads, max_len,
        head_dim]``) the model's chunk-prefill path consumes — slot ``s``
        as a batch-of-one cache. ``slot`` may be a traced int32 scalar
        (the jitted chunk-prefill program is slot-agnostic)."""
        slot = jnp.asarray(slot, jnp.int32)
        return (jax.lax.dynamic_slice_in_dim(self.k, slot, 1, axis=1),
                jax.lax.dynamic_slice_in_dim(self.v, slot, 1, axis=1))

    def write_slot(self, slot, k_slot, v_slot, length) -> "KVCache":
        """Write an updated :meth:`slot_view` back (``[layers, 1, heads,
        max_len, head_dim]``) and set the slot's length — the second half
        of a chunk-prefill step (``length`` = positions ingested so far;
        mid-prompt chunks leave it short of the true prompt length, so
        decode-side garbage writes past it are overwritten by the next
        chunk before anything can attend them)."""
        want = (self.layers, 1, self.heads, self.max_len, self.head_dim)
        if k_slot.shape != want or v_slot.shape != want:
            raise ValueError(f"write_slot expects full slot views "
                             f"{want}, got k {k_slot.shape} / "
                             f"v {v_slot.shape}")
        slot = jnp.asarray(slot, jnp.int32)
        start = (jnp.int32(0), slot, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_slot, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_slot, self.v.dtype), start)
        lengths = self.lengths.at[slot].set(jnp.asarray(length, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def copy_slot(self, src, dst, length) -> "KVCache":
        """Row copy for prefix reuse: slot ``src``'s full K/V row →
        slot ``dst``, whose length becomes ``length``. ``src``/``dst``/
        ``length`` may be traced int32 scalars — the engine's one
        compiled copy program serves every (donor, destination, matched
        length) triple. The copy is the full ``max_len`` window (slice
        sizes must be static under jit); positions past ``length`` carry
        donor garbage that is never attended (length masking) and is
        overwritten as chunk prefill resumes at ``length`` — the same
        contract prefill padding already lives by. ``src``'s own length
        is untouched."""
        k_row, v_row = self.slot_view(src)
        dst = jnp.asarray(dst, jnp.int32)
        start = (jnp.int32(0), dst, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        k = jax.lax.dynamic_update_slice(self.k, k_row, start)
        v = jax.lax.dynamic_update_slice(self.v, v_row, start)
        lengths = self.lengths.at[dst].set(jnp.asarray(length, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def model_view(self):
        """The ``(k, v)`` pair the model's decode path consumes
        (``[layers, slots, heads, max_len, head_dim]`` — already the
        cache layout; slots are the decode batch)."""
        return self.k, self.v

    def front_view(self, n: int):
        """The first ``n`` slot rows as a decode cache (``[layers, n,
        heads, max_len, head_dim]``; ``n`` static). An engine with a
        prefix pool reserves rows ``[n, slots)`` for retained prefixes —
        the decode batch must neither compute over nor advance them."""
        return self.k[:, :n], self.v[:, :n]

    def advance_front(self, k_front, v_front, active) -> "KVCache":
        """:meth:`advance` over the first ``k_front.shape[1]`` rows
        only: commit the model-returned decode stacks back into the full
        arrays (prefix-pool rows untouched) and grow the active front
        lengths."""
        n = k_front.shape[1]
        start = (jnp.int32(0),) * 5
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_front, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_front, self.v.dtype), start)
        front = self.lengths[:n]
        grow = jnp.asarray(active, bool) & (front < self.max_len)
        lengths = self.lengths.at[:n].set(
            jnp.where(grow, front + 1, front))
        return self.replace(k=k, v=v, lengths=lengths)

    def advance(self, k, v, active) -> "KVCache":
        """Absorb a decode step: ``k``/``v`` are the model-returned
        stacks (each slot's new token written at its old length) and
        ``active`` [slots] bool marks slots whose length advances —
        inactive slots keep their length so their (discarded) write is
        re-overwritten by the next real occupant."""
        grow = jnp.asarray(active, bool) & (self.lengths < self.max_len)
        return self.replace(k=k, v=v,
                            lengths=jnp.where(grow, self.lengths + 1,
                                              self.lengths))

    # ------------------------------------------------------------ reporting
    def occupancy(self, active=None) -> float:
        """Fraction of slots in use (host-side; by active mask when
        given, else by nonzero length)."""
        if active is not None:
            return float(np.mean(np.asarray(active, bool)))
        return float(np.mean(np.asarray(self.lengths) > 0))

    def padding_waste(self, active=None) -> float:
        """Fraction of the decode batch spent on empty slots — the
        continuous-batching inefficiency signal (1 - occupancy)."""
        return 1.0 - self.occupancy(active)
