"""Preallocated slot KV cache — the serving engine's only mutable state.

One device-resident pytree holds every request's attention history:

- ``k``/``v``: ``[layers, slots, heads, max_len, head_dim]`` — slot ``s``
  owns row ``[:, s]``; positions ``[0, lengths[s])`` are valid.
- ``lengths``: ``[slots]`` int32 — valid positions per slot (0 = free).

Storage dtype comes from the amp cast policies (bf16 by default — the
same ``half_dtype`` the O2/O3 tables resolve), halving HBM versus fp32
and feeding the decode kernel the dtype it upcasts per-tile anyway.

Slot semantics (the continuous-batching contract):

- **prefill** writes a request's prompt K/V into ``[0, P)`` of a free
  slot and sets its length; positions past the true prompt length hold
  pad garbage that is *never attended* (length masking) and is
  overwritten position-by-position as decode advances.
- **chunked prefill** ingests a prompt one chunk per decode heartbeat:
  :meth:`slot_view` hands the model one slot as a batch-of-one cache,
  the chunk's K/V lands at ``[offset, offset + C)``, and
  :meth:`write_slot` commits the view back with the grown length.
- **decode** writes each slot's new token at ``lengths[s]`` and then
  attends ``[0, lengths[s]]`` — write-then-attend, so garbage can never
  enter a softmax.
- **eviction** is free: a finished slot is just marked length-0 on the
  host; the next prefill overwrites it. No device-side compaction.
- **prefix pool**: an engine built with ``prefix_pool=N`` allocates N
  extra rows past its serving slots to retain popular prompt prefixes;
  :meth:`copy_slot` is the one compiled row-copy both directions share
  (register: slot → pool row; hit: pool row → fresh slot) and
  :meth:`front_view`/:meth:`advance_front` keep the decode batch off
  the pool rows.

Everything is functional: updates return a new :class:`KVCache` whose
buffers alias the old ones under jit donation (the engine donates the
cache to both of its compiled programs).

**Paged layout** (the serving engine's default since the block-table
refactor): :class:`PagedKVCache` replaces the per-slot rows with a
dense pool of fixed-size pages ``[layers, num_pages, heads, page_len,
head_dim]`` plus a host-side :class:`PagePool` allocator. A request
owns a *page list* instead of a row: its logical positions ``[0, L)``
live on pages ``table[0] .. table[ceil(L/page_len)-1]`` at in-page
offsets ``pos % page_len``. The engine materialises the per-slot lists
as a ``[slots, max_pages]`` int32 page-table operand each call; the
attention kernels gather K/V through it. What the indirection buys:

- **no per-slot max_len reservation** — a 40-token request holds
  ``ceil(40/page_len)`` pages, not ``max_len`` positions, so the same
  pool bytes serve far more logical requests;
- **copy-on-write prefix sharing** — a prefix-cache hit bumps the
  refcount of the donor's pages and writes their ids into the new
  slot's table: zero data movement (the contiguous layout's compiled
  ``copy_kv`` program is retired from the hit path). Shares are always
  whole-page (matches are chunk-aligned and ``chunk_len % page_len ==
  0``), so a shared page is never written: the first write past the
  shared prefix lands on a freshly allocated page by construction;
- **immediate reclamation** — a finished request's pages return to the
  free list the moment its slot is released (refcount permitting), not
  when the next prefill overwrites the row.

Page 0 is the **sentinel/garbage page**: never allocated, it absorbs
the fixed-shape decode program's writes for inactive slots (their page
tables point at it) so a dead slot's discarded write can never land on
a live request's page. Allocation is all-or-nothing with a reservation
ledger (:meth:`PagePool.reserve`): the scheduler reserves a request's
worst-case page demand at admission, so a request that was admitted can
always grow to its budget — pool pressure is absorbed at the admission
boundary (requests queue; prefix entries are evicted LRU-first), never
mid-decode.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVCache", "PagedKVCache", "PagePool"]


@flax.struct.dataclass
class KVCache:
    """Slot-major KV cache pytree (see module docstring for semantics).

    ``k_scale``/``v_scale`` (both None by default) are the quantized
    storage tier's per-``[layer, head]`` fp32 dequantization scales
    (:mod:`apex_tpu.serving.kv_quant`): when set, ``k``/``v`` hold int8
    codes and every reader multiplies through the matching scale. They
    ride the pytree so the donated cache stays self-describing; an
    unquantized cache flattens to exactly the same three leaves as
    before."""

    k: jnp.ndarray        # [layers, slots, heads, max_len, head_dim]
    v: jnp.ndarray        # [layers, slots, heads, max_len, head_dim]
    lengths: jnp.ndarray  # [slots] int32
    k_scale: Optional[jnp.ndarray] = None   # [layers, heads] fp32
    v_scale: Optional[jnp.ndarray] = None   # [layers, heads] fp32

    # ------------------------------------------------------------- geometry
    @property
    def layers(self) -> int:
        return self.k.shape[0]

    @property
    def slots(self) -> int:
        return self.k.shape[1]

    @property
    def heads(self) -> int:
        return self.k.shape[2]

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    @property
    def dtype(self):
        return self.k.dtype

    def nbytes(self) -> int:
        """Device bytes held by the cache (both K and V)."""
        return int(self.k.size * self.k.dtype.itemsize * 2)

    # -------------------------------------------------------------- updates
    @classmethod
    def create(cls, *, layers: int, slots: int, heads: int, max_len: int,
               head_dim: int, dtype: Any = jnp.bfloat16,
               k_scale=None, v_scale=None) -> "KVCache":
        """Allocate a zeroed cache. ``dtype`` is normally the amp half
        dtype (``policy.half_dtype`` / ``compute_dtype`` — the serving
        engine resolves it from its policy), or int8 with the
        ``k_scale``/``v_scale`` pair when the engine's
        :class:`~apex_tpu.serving.KVQuantConfig` tier is on."""
        shape = (layers, slots, heads, max_len, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((slots,), jnp.int32),
                   k_scale=k_scale, v_scale=v_scale)

    def insert(self, slot, k_new, v_new, length) -> "KVCache":
        """Write a prefilled request into ``slot``: ``k_new``/``v_new``
        are the model's stacked prefill K/V ``[layers, 1, heads, P, d]``
        (``P <= max_len``); the slot's length becomes ``length`` (the
        true prompt length — pad positions in ``[length, P)`` are masked
        by it). ``slot``/``length`` may be traced int32 scalars — the
        jitted prefill program is slot- and length-agnostic."""
        if k_new.ndim != 5 or k_new.shape[1] != 1:
            raise ValueError(f"insert expects [layers, 1, heads, P, d] "
                             f"prefill K/V, got {k_new.shape}")
        P = k_new.shape[3]
        if P > self.max_len:
            raise ValueError(f"prefill length {P} exceeds cache max_len "
                             f"{self.max_len}")
        slot = jnp.asarray(slot, jnp.int32)
        start = (jnp.int32(0), slot, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_new, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_new, self.v.dtype), start)
        lengths = self.lengths.at[slot].set(jnp.asarray(length, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def slot_view(self, slot):
        """The one-slot ``(k, v)`` pair (``[layers, 1, heads, max_len,
        head_dim]``) the model's chunk-prefill path consumes — slot ``s``
        as a batch-of-one cache. ``slot`` may be a traced int32 scalar
        (the jitted chunk-prefill program is slot-agnostic)."""
        slot = jnp.asarray(slot, jnp.int32)
        return (jax.lax.dynamic_slice_in_dim(self.k, slot, 1, axis=1),
                jax.lax.dynamic_slice_in_dim(self.v, slot, 1, axis=1))

    def write_slot(self, slot, k_slot, v_slot, length) -> "KVCache":
        """Write an updated :meth:`slot_view` back (``[layers, 1, heads,
        max_len, head_dim]``) and set the slot's length — the second half
        of a chunk-prefill step (``length`` = positions ingested so far;
        mid-prompt chunks leave it short of the true prompt length, so
        decode-side garbage writes past it are overwritten by the next
        chunk before anything can attend them)."""
        want = (self.layers, 1, self.heads, self.max_len, self.head_dim)
        if k_slot.shape != want or v_slot.shape != want:
            raise ValueError(f"write_slot expects full slot views "
                             f"{want}, got k {k_slot.shape} / "
                             f"v {v_slot.shape}")
        slot = jnp.asarray(slot, jnp.int32)
        start = (jnp.int32(0), slot, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_slot, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_slot, self.v.dtype), start)
        lengths = self.lengths.at[slot].set(jnp.asarray(length, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def copy_slot(self, src, dst, length) -> "KVCache":
        """Row copy for prefix reuse: slot ``src``'s full K/V row →
        slot ``dst``, whose length becomes ``length``. ``src``/``dst``/
        ``length`` may be traced int32 scalars — the engine's one
        compiled copy program serves every (donor, destination, matched
        length) triple. The copy is the full ``max_len`` window (slice
        sizes must be static under jit); positions past ``length`` carry
        donor garbage that is never attended (length masking) and is
        overwritten as chunk prefill resumes at ``length`` — the same
        contract prefill padding already lives by. ``src``'s own length
        is untouched."""
        k_row, v_row = self.slot_view(src)
        dst = jnp.asarray(dst, jnp.int32)
        start = (jnp.int32(0), dst, jnp.int32(0), jnp.int32(0),
                 jnp.int32(0))
        k = jax.lax.dynamic_update_slice(self.k, k_row, start)
        v = jax.lax.dynamic_update_slice(self.v, v_row, start)
        lengths = self.lengths.at[dst].set(jnp.asarray(length, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def model_view(self):
        """The ``(k, v)`` pair the model's decode path consumes
        (``[layers, slots, heads, max_len, head_dim]`` — already the
        cache layout; slots are the decode batch)."""
        return self.k, self.v

    def front_view(self, n: int):
        """The first ``n`` slot rows as a decode cache (``[layers, n,
        heads, max_len, head_dim]``; ``n`` static). An engine with a
        prefix pool reserves rows ``[n, slots)`` for retained prefixes —
        the decode batch must neither compute over nor advance them."""
        return self.k[:, :n], self.v[:, :n]

    def advance_front(self, k_front, v_front, active) -> "KVCache":
        """:meth:`advance` over the first ``k_front.shape[1]`` rows
        only: commit the model-returned decode stacks back into the full
        arrays (prefix-pool rows untouched) and grow the active front
        lengths."""
        n = k_front.shape[1]
        start = (jnp.int32(0),) * 5
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_front, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_front, self.v.dtype), start)
        front = self.lengths[:n]
        grow = jnp.asarray(active, bool) & (front < self.max_len)
        lengths = self.lengths.at[:n].set(
            jnp.where(grow, front + 1, front))
        return self.replace(k=k, v=v, lengths=lengths)

    def commit_front(self, k_front, v_front, front_lengths) -> "KVCache":
        """:meth:`advance_front`'s general sibling for the batched
        speculative verify: commit the model-returned front stacks and
        SET the front rows' lengths to ``front_lengths`` (``[n]`` int32,
        already computed in-program as ``offset + n_accepted + 1`` for
        verifying rows and the unchanged old length for the rest).
        Prefix-pool rows past the front are untouched."""
        n = k_front.shape[1]
        start = (jnp.int32(0),) * 5
        k = jax.lax.dynamic_update_slice(
            self.k, jnp.asarray(k_front, self.k.dtype), start)
        v = jax.lax.dynamic_update_slice(
            self.v, jnp.asarray(v_front, self.v.dtype), start)
        lengths = self.lengths.at[:n].set(
            jnp.asarray(front_lengths, jnp.int32))
        return self.replace(k=k, v=v, lengths=lengths)

    def advance(self, k, v, active) -> "KVCache":
        """Absorb a decode step: ``k``/``v`` are the model-returned
        stacks (each slot's new token written at its old length) and
        ``active`` [slots] bool marks slots whose length advances —
        inactive slots keep their length so their (discarded) write is
        re-overwritten by the next real occupant."""
        grow = jnp.asarray(active, bool) & (self.lengths < self.max_len)
        return self.replace(k=k, v=v,
                            lengths=jnp.where(grow, self.lengths + 1,
                                              self.lengths))

    # ------------------------------------------------------------ reporting
    def occupancy(self, active=None) -> float:
        """Fraction of slots in use (host-side; by active mask when
        given, else by nonzero length)."""
        if active is not None:
            return float(np.mean(np.asarray(active, bool)))
        return float(np.mean(np.asarray(self.lengths) > 0))

    def padding_waste(self, active=None) -> float:
        """Fraction of the decode batch spent on empty slots — the
        continuous-batching inefficiency signal (1 - occupancy)."""
        return 1.0 - self.occupancy(active)


@flax.struct.dataclass
class PagedKVCache:
    """Paged KV pool pytree: ``[layers, num_pages, heads, page_len,
    head_dim]`` K and V. Pure device storage — lengths and page tables
    are host state (the engine's :class:`PagePool` + numpy tables,
    passed as per-call operands), so the donated pytree is exactly the
    two hot arrays."""

    k: jnp.ndarray        # [layers, num_pages, heads, page_len, head_dim]
    v: jnp.ndarray        # [layers, num_pages, heads, page_len, head_dim]
    # quantized storage tier (kv_quant): per-[layer, head] fp32 dequant
    # scales; None on the bf16 default. Per-head — NOT per-page — so a
    # copy-on-write share never copies scale state alongside its pages.
    k_scale: Optional[jnp.ndarray] = None   # [layers, heads] fp32
    v_scale: Optional[jnp.ndarray] = None   # [layers, heads] fp32

    # ------------------------------------------------------------- geometry
    @property
    def layers(self) -> int:
        return self.k.shape[0]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def heads(self) -> int:
        return self.k.shape[2]

    @property
    def page_len(self) -> int:
        return self.k.shape[3]

    @property
    def head_dim(self) -> int:
        return self.k.shape[4]

    @property
    def dtype(self):
        return self.k.dtype

    def nbytes(self) -> int:
        """Device bytes held by the pool (both K and V)."""
        return int(self.k.size * self.k.dtype.itemsize * 2)

    @classmethod
    def create(cls, *, layers: int, num_pages: int, heads: int,
               page_len: int, head_dim: int, dtype: Any = jnp.bfloat16,
               k_scale=None, v_scale=None) -> "PagedKVCache":
        """Allocate a zeroed pool (``dtype`` normally the amp half
        dtype, or int8 with the scale pair under the engine's
        ``kv_quant`` tier). ``num_pages`` INCLUDES the page-0 sentinel,
        so the usable capacity is ``(num_pages - 1) * page_len``
        positions."""
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "sentinel/garbage page)")
        shape = (layers, num_pages, heads, page_len, head_dim)
        return cls(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   k_scale=k_scale, v_scale=v_scale)

    def layer_view(self):
        """The ``(k, v)`` pool pair the paged model path consumes."""
        return self.k, self.v


class PagePool:
    """Host-side page allocator for a :class:`PagedKVCache`.

    Three pieces of state, all numpy/python (no device work ever):

    - a **free list** of allocatable page ids (page 0 — the sentinel —
      is never on it);
    - **refcounts** per page: a page is held once per slot whose table
      references it plus once per prefix-cache entry retaining it;
      :meth:`release` returns it to the free list only at refcount 0 —
      a shared page is never freed while anything can still read it;
    - a **reservation ledger**: :meth:`reserve` sets aside capacity
      without naming pages, so the scheduler can guarantee at admission
      that a request's worst-case growth (prompt + ``max_new_tokens``)
      will find pages mid-decode. :meth:`alloc` draws down the caller's
      reservation when one exists.

    ``cow_shares`` (pages with refcount > 1) is the copy-on-write
    telemetry signal: every such page is serving >= 2 readers for the
    price of one.
    """

    def __init__(self, num_pages: int, page_len: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "sentinel/garbage page)")
        if page_len < 1:
            raise ValueError("page_len must be >= 1")
        self.num_pages = int(num_pages)
        self.page_len = int(page_len)
        self.refcount = np.zeros(self.num_pages, np.int32)
        # LIFO free list: recently-freed pages are re-used first (their
        # HBM is most likely still warm in whatever cache hierarchy sits
        # above it); ids descend so fresh pools allocate low pages first
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self.reserved_total = 0

    # ------------------------------------------------------------- capacity
    @property
    def free_pages(self) -> int:
        """Pages on the free list (ignores reservations)."""
        return len(self._free)

    @property
    def available(self) -> int:
        """Pages an admission may still reserve: free minus already-
        promised reservations (never negative)."""
        return max(0, len(self._free) - self.reserved_total)

    @property
    def pages_in_use(self) -> int:
        """Allocatable pages currently referenced (excludes sentinel)."""
        return self.num_pages - 1 - len(self._free)

    @property
    def cow_shares(self) -> int:
        """Pages shared by more than one reader — each is a prefix-cache
        copy the paged layout never had to materialise."""
        return int(np.sum(self.refcount > 1))

    def pages_for(self, positions: int) -> int:
        """Pages covering ``positions`` logical positions."""
        return -(-int(positions) // self.page_len)

    def free_list(self) -> Tuple[int, ...]:
        """Snapshot of the free list (page ids, allocation order not
        guaranteed) — the :class:`~apex_tpu.serving.PoolAuditor`'s view
        for free-list hygiene checks (no duplicates, refcount 0 only,
        disjoint from referenced pages)."""
        return tuple(self._free)

    # ----------------------------------------------------------- allocation
    def reserve(self, n: int) -> bool:
        """Promise ``n`` pages to a future caller (no pages named yet).
        False — and no state change — when the pool cannot cover the
        promise on top of existing reservations."""
        n = int(n)
        if n < 0:
            raise ValueError("reserve expects n >= 0")
        if n > self.available:
            return False
        self.reserved_total += n
        return True

    def unreserve(self, n: int) -> None:
        """Return unused reservation (a finished request rarely used its
        worst case)."""
        self.reserved_total = max(0, self.reserved_total - int(n))

    def alloc(self, *, reserved: bool = False) -> Optional[int]:
        """One page off the free list (refcount -> 1), or None when the
        list is empty. ``reserved=True`` draws down the ledger — the
        caller is consuming a promise made at admission."""
        if not self._free:
            return None
        page = self._free.pop()
        self.refcount[page] = 1
        if reserved:
            self.reserved_total = max(0, self.reserved_total - 1)
        return page

    def share(self, pages: Iterable[int]) -> None:
        """One more reader per page (copy-on-write: a prefix hit or a
        prefix-cache registration shares pages instead of copying)."""
        for p in pages:
            p = int(p)
            if not 0 < p < self.num_pages:
                raise ValueError(f"page {p} out of range (1, "
                                 f"{self.num_pages})")
            if self.refcount[p] <= 0:
                raise ValueError(f"page {p} is free — cannot share")
            self.refcount[p] += 1

    def release(self, pages: Iterable[int]) -> None:
        """One fewer reader per page; pages reaching refcount 0 return
        to the free list immediately (the paged layout's instant
        reclamation)."""
        for p in pages:
            p = int(p)
            if not 0 < p < self.num_pages:
                raise ValueError(f"page {p} out of range (1, "
                                 f"{self.num_pages})")
            if self.refcount[p] <= 0:
                raise ValueError(f"page {p} already free")
            self.refcount[p] -= 1
            if self.refcount[p] == 0:
                self._free.append(p)

    # ------------------------------------------------------------ reporting
    def fragmentation(self, lengths: Sequence[int],
                      pages_per_slot: Sequence[int]) -> float:
        """Internal fragmentation: the fraction of allocated SLOT
        positions holding no valid token (last-page slack + padded
        prefill windows). Prefix-entry pages held at refcount but
        referenced by no slot are the caller's to exclude — this is the
        per-slot view."""
        alloc = int(np.sum(np.asarray(pages_per_slot, np.int64))) \
            * self.page_len
        if alloc == 0:
            return 0.0
        used = int(np.sum(np.asarray(lengths, np.int64)))
        return max(0.0, 1.0 - used / alloc)

    def stats(self) -> dict:
        """Snapshot for telemetry / bench rows."""
        return {
            "num_pages": self.num_pages,
            "page_len": self.page_len,
            "pages_in_use": self.pages_in_use,
            "pages_free": self.free_pages,
            "pages_reserved": self.reserved_total,
            "cow_shares": self.cow_shares,
        }
