"""SLO policy core — priority classes, deadline bookkeeping, tenant
fairness.

This module is the PURE half of SLO-aware scheduling: it defines what
"more important" means (:class:`SLOConfig` — class→priority mapping,
queue-aging boost, preemption/deadline-admission switches) and who has
been served how much (:class:`TenantLedger` — weighted-fair virtual
service accounting). The :class:`~apex_tpu.serving.Scheduler` consumes
both; the :class:`~apex_tpu.serving.Router` and
:class:`~apex_tpu.serving.FleetController` pass the config through to
every replica so one policy governs the whole fleet.

Deliberately imports NOTHING from the rest of the serving package (the
scheduler imports *this* module), so:

- :class:`SLOConfig` is a plain picklable dataclass — it rides the
  process fleet's pickle frames to worker processes unchanged, and the
  priority arithmetic is deterministic, so a controller and its
  workers rank identically from the same config.
- :class:`TenantLedger` is the opposite by design: it holds a lock and
  live counters (process-LOCAL shared state), refuses to pickle
  loudly, and never crosses a process boundary — the in-process Router
  shares ONE ledger across its replicas; each fleet worker process
  builds its own (per-process fairness, an honest scope documented in
  docs/serving.md).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Mapping, Optional

__all__ = ["SLOConfig", "TenantLedger"]


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """The scheduling policy knob set (frozen: one immutable value is
    shared by the scheduler, router and fleet — nobody mutates policy
    mid-serve).

    - ``classes`` maps SLO class name → integer base priority (higher
      = more important). A request names its class via
      ``Request.slo_class``; its own ``Request.priority`` ADDS to the
      class base (a within-class tie-break, and the whole priority for
      class-less requests).
    - ``aging_s``: queue-aging period — every ``aging_s`` seconds a
      QUEUED request waits, its effective priority rises by 1, which
      bounds starvation under a sustained high-priority flood (the
      boost earned in the queue is PINNED at admission, so an aged-up
      request cannot be instantly re-preempted by the next fresh
      high-priority arrival). None disables aging.
    - ``preempt``: under admission pressure, preempt the
      lowest-priority RUNNING request (strictly below the candidate's
      effective priority) instead of queueing the candidate behind it.
    - ``deadline_admission``: reject a submit whose ``deadline_s``
      cannot be met at the measured decode-step EMA
      (:class:`~apex_tpu.serving.DeadlineUnmeetable`, with an honest
      ``retry_after_s``) instead of accepting work destined to miss.
    - ``max_preemptions``: per-request cap on how many times one
      request may be preempted (None = unbounded); a capped request
      becomes un-preemptible, which bounds churn on pathological
      priority ladders.
    - ``tenant_weights``: tenant → weight for the weighted-fair
      ledger (unlisted tenants weigh 1.0).
    - ``tenant_max_share``: cap on the fraction of slots one tenant
      may occupy concurrently (None = no quota). At least one slot is
      always allowed, so a quota can never starve a tenant outright.
    """

    classes: Mapping[str, int] = dataclasses.field(
        default_factory=lambda: {"batch": 0, "interactive": 10})
    aging_s: Optional[float] = None
    preempt: bool = True
    deadline_admission: bool = True
    max_preemptions: Optional[int] = None
    tenant_weights: Mapping[str, float] = dataclasses.field(
        default_factory=dict)
    tenant_max_share: Optional[float] = None

    def base_priority(self, request) -> int:
        """``request``'s static priority: its class's base (when it
        names one) plus its own ``priority`` field. Raises
        ``ValueError`` for an unknown class name — submit validates
        with this, so typos fail loudly at the door, not silently as
        priority 0."""
        cls = getattr(request, "slo_class", None)
        base = 0
        if cls is not None:
            if cls not in self.classes:
                raise ValueError(
                    f"unknown slo_class {cls!r} — this SLOConfig "
                    f"defines {sorted(self.classes)}")
            base = int(self.classes[cls])
        return base + int(getattr(request, "priority", 0))

    def effective_priority(self, request, now: float) -> int:
        """Base priority plus the queue-aging boost: +1 per full
        ``aging_s`` elapsed since the ORIGINAL submit (retries and
        preemptions never reset that clock, so every pass through the
        queue keeps the age already earned)."""
        pri = self.base_priority(request)
        t0 = getattr(request, "_t_submit", None)
        if self.aging_s is not None and self.aging_s > 0 \
                and t0 is not None and now > t0:
            pri += int((now - t0) / self.aging_s)
        return pri

    @property
    def top_priority(self) -> int:
        """The highest class base priority — the reference level for
        "preemptible headroom": pages held by running requests
        strictly below this could be reclaimed for a top-class
        arrival, which is what ``load_snapshot()['preemptible_pages']``
        reports and ``routing_policy.rank_replicas`` folds in for
        prioritized requests."""
        return max(self.classes.values(), default=0)


class TenantLedger:
    """Weighted-fair service accounting, thread-safe and deliberately
    process-local (see the module docstring). Each finished request
    charges its tenant ``tokens / weight`` of VIRTUAL service; the
    scheduler admits, among equal-priority candidates, the tenant with
    the LEAST virtual service first — classic weighted fair queueing,
    where a weight-2 tenant sustains twice the token rate of a
    weight-1 tenant before losing ties."""

    def __init__(self, weights: Optional[Mapping[str, float]] = None):
        self._lock = threading.Lock()
        self.weights: Dict[str, float] = dict(weights or {})
        self._virtual: Dict[str, float] = {}
        self._tokens: Dict[str, int] = {}

    def weight(self, tenant: str) -> float:
        w = float(self.weights.get(tenant, 1.0))
        return w if w > 0 else 1.0

    def charge(self, tenant: str, tokens: int) -> None:
        """Record ``tokens`` served for ``tenant`` (finish-time, so
        abandoned work is never charged)."""
        with self._lock:
            self._virtual[tenant] = self._virtual.get(tenant, 0.0) \
                + tokens / self.weight(tenant)
            self._tokens[tenant] = self._tokens.get(tenant, 0) \
                + int(tokens)

    def virtual_served(self, tenant: str) -> float:
        """``tenant``'s weighted virtual service so far (0.0 for a
        tenant never charged) — the admission tie-break key: lower
        means owed more."""
        with self._lock:
            return self._virtual.get(tenant, 0.0)

    def tokens_served(self, tenant: str) -> int:
        with self._lock:
            return self._tokens.get(tenant, 0)

    def snapshot(self) -> Dict[str, dict]:
        """Per-tenant ``{tokens, virtual, weight}`` view (telemetry /
        tests)."""
        with self._lock:
            return {t: {"tokens": self._tokens.get(t, 0),
                        "virtual": v,
                        "weight": self.weight(t)}
                    for t, v in self._virtual.items()}

    def __reduce__(self):
        raise TypeError(
            "TenantLedger is process-local shared state (a lock and "
            "live counters) — it never crosses the fleet's pickle "
            "frames; each worker process builds its own from the "
            "SLOConfig's tenant_weights")
