"""Speculative decoding: host-side n-gram drafting for draft-and-verify.

Decode is one token per step per slot — the wall-clock floor of every
serving bench. Draft-and-verify lifts tokens-per-step above 1 without a
second model: a cheap DRAFTER guesses the next ``K`` tokens, ONE
compiled verify program (:meth:`~apex_tpu.serving.Engine.verify_step`,
the chunk-append machinery at shape ``[1, K+1]``) scores all of them in
a single step, and accept-longest-prefix keeps greedy output bitwise
identical to plain decode: every emitted token is the verify program's
own greedy target, and a draft token is accepted only when it EQUALS
the greedy target at its position — so the emitted stream is exactly
the token-by-token greedy stream, just discovered up to ``K+1`` tokens
per step instead of one.

This module is the drafter half, all host-side numpy/python (no device
work, no compiled programs — drafting can never retrace anything):

- :class:`SpecConfig` — the engine-level knobs: ``draft_len`` (K, the
  verify program's static draft width) and ``ngram`` (the longest
  suffix n-gram the lookup tries to match).
- :func:`draft_tokens` — prompt-lookup / n-gram drafting (PLD): find
  the most recent earlier occurrence of the sequence's trailing
  n-gram inside ``prompt + generated`` and propose the tokens that
  followed it. Shared-prefix templates, multi-turn histories and
  repetitive generations — exactly the workloads the prefix cache
  serves — are full of such matches; free-running text simply drafts
  nothing and the scheduler falls back to the plain decode program.
- :class:`DraftWorker` — the THREADED drafter the async pipelined
  heartbeat uses (``Scheduler(pipeline_depth >= 1)``): a single
  background thread that precomputes drafts (and prefix block hashes)
  while the device executes dispatched-ahead programs, so host
  think-time overlaps device compute instead of serializing with it.
  Jobs are pure closures over snapshots, so a precomputed draft is
  byte-identical to the inline one — threading changes WHEN host work
  runs, never what it computes.

An EMPTY draft costs nothing: the slot takes this heartbeat's ordinary
decode step. A wrong draft costs one verify step that still emits at
least one correct token (the bonus/greedy token at the first
mismatch), so speculation never emits fewer tokens per program call
than plain decode — the only regression risk is the verify step's
extra FLOPs, which is why ``Scheduler(speculative=False)`` keeps
today's path as the measurable baseline.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["DraftWorker", "SpecConfig", "draft_tokens"]


@dataclasses.dataclass(frozen=True)
class SpecConfig:
    """Speculative-decoding knobs (engine-level: ``draft_len`` fixes
    the verify program's compiled shape).

    - ``draft_len`` (K): draft tokens per verify step. The verify
      program is ``[1, K+1]`` — bigger K amortises more dispatches per
      accepted run but wastes more compute when acceptance is low.
      On silicon, K+1 a multiple of 8 keeps the verify attention on
      its Pallas path (smaller shapes fall back to the exact jnp
      reference — same tokens, more FLOPs).
    - ``ngram``: longest trailing n-gram the prompt-lookup tries to
      match (it degrades toward ``min_ngram`` before giving up).
    - ``min_ngram``: shortest match worth drafting from (1 = a single
      repeated token already drafts; raise it to cut spurious drafts
      on near-random text).
    """

    draft_len: int = 4
    ngram: int = 3
    min_ngram: int = 1

    def __post_init__(self):
        if self.draft_len < 1:
            raise ValueError("draft_len must be >= 1")
        if self.ngram < 1:
            raise ValueError("ngram must be >= 1")
        if not 1 <= self.min_ngram <= self.ngram:
            raise ValueError(
                f"min_ngram {self.min_ngram} must be in [1, "
                f"ngram={self.ngram}]")


class DraftWorker:
    """One background thread that precomputes pure host-side heartbeat
    work — n-gram drafts and prefix block hashes — while the device
    executes dispatched programs (the async pipelined heartbeat's
    host-overlap half).

    The contract that keeps this SAFE to thread is purity: every
    submitted job is a closure over an immutable SNAPSHOT of its inputs
    (the caller copies token lists before submitting), and
    :func:`draft_tokens` / the prefix cache's rolling hash are pure
    functions — so a precomputed result is byte-identical to the inline
    computation it replaces, regardless of when the thread gets
    scheduled. Timing can never change tokens, only overlap.

    API: :meth:`submit` enqueues ``fn`` under ``key`` (idempotent — a
    key already queued or done is not re-run); :meth:`take` returns the
    result for ``key``, waiting briefly if the job is mid-flight, or
    simply runs ``fn`` inline when the key was never submitted (the
    scheduler's depth-0 path and every miss degrade to today's inline
    behavior). Results are consumed on take; unclaimed results (a
    request that finished before its draft was needed) age out of a
    small ring so the worker cannot leak memory across a long serve.
    The thread is a daemon and :meth:`stop` is idempotent — the
    scheduler registers it with ``weakref.finalize``.

    Job closures MAY emit request-trace spans (:mod:`apex_tpu
    .telemetry.tracing`): with a tracer attached the scheduler's
    draft closures self-time and emit their ``draft`` span from
    whichever thread runs them, so drafting work shows up on this
    thread's lane (``serving-draft-worker``) in the Chrome trace —
    the tracer is lock-protected and appends are token-invisible, so
    the purity contract above is untouched."""

    _MAX_UNCLAIMED = 256

    def __init__(self):
        self._jobs: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._results: Dict[Any, Any] = {}
        self._inflight: set = set()
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-draft-worker")
        self._thread.start()

    def _run(self) -> None:
        while True:
            item = self._jobs.get()
            if item is None:
                return
            key, fn = item
            try:
                result = fn()
            except BaseException as e:  # noqa: BLE001 — surfaced at take
                result = _JobError(e)
            with self._cond:
                self._inflight.discard(key)
                self._results[key] = result
                while len(self._results) > self._MAX_UNCLAIMED:
                    # drop the oldest unclaimed result (dict order);
                    # a later take simply recomputes inline
                    self._results.pop(next(iter(self._results)))
                self._cond.notify_all()

    def submit(self, key, fn: Callable[[], Any]) -> None:
        """Enqueue ``fn`` to run on the worker thread under ``key``
        (no-op if the key is already queued or completed). ``fn`` MUST
        close over snapshots, never live mutable state."""
        with self._lock:
            if self._stopped or key in self._inflight \
                    or key in self._results:
                return
            self._inflight.add(key)
        self._jobs.put((key, fn))

    def take(self, key, fn: Callable[[], Any]):
        """The result for ``key``: precomputed if :meth:`submit` ran it
        (waiting out a mid-flight job), else ``fn()`` inline — the
        caller cannot tell the difference because jobs are pure."""
        with self._cond:
            while key in self._inflight:
                self._cond.wait(timeout=1.0)
            if key in self._results:
                result = self._results.pop(key)
                if isinstance(result, _JobError):
                    raise result.error
                return result
        return fn()

    def stop(self) -> None:
        """Shut the thread down (idempotent; registered as the owning
        scheduler's finalizer)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
        self._jobs.put(None)
        self._thread.join(timeout=2.0)


@dataclasses.dataclass
class _JobError:
    """A worker job's raised exception, parked until its take()."""

    error: BaseException


def _rfind(data: bytes, pattern: bytes, last_start: int) -> int:
    """TOKEN index of the last occurrence of ``pattern`` in the
    4-byte-per-token encoding ``data`` starting at token index
    ``<= last_start``; -1 when absent (or ``last_start`` < 0). One
    C-speed ``bytes.rfind`` per try, with a backward re-search loop for
    the rare byte-misaligned hit (a real match starts on a token
    boundary) — the heartbeat calls this for every greedy slot every
    tick, so the common no-match case must not cost Python-loop time."""
    if last_start < 0:
        return -1
    pos = data.rfind(pattern, 0, last_start * 4 + len(pattern))
    while pos >= 0 and pos % 4:
        pos = data.rfind(pattern, 0, pos + len(pattern) - 1)
    return pos // 4 if pos >= 0 else -1


def draft_tokens(tokens: Sequence[int], config: SpecConfig,
                 max_draft: Optional[int] = None) -> List[int]:
    """Prompt-lookup draft for the NEXT positions of ``tokens``
    (``prompt + generated so far``, including the pending token that is
    not yet in the KV cache).

    Tries the trailing n-gram at ``config.ngram`` down to
    ``config.min_ngram``; the first size with an earlier occurrence
    wins. Among occurrences, the most recent one with a FULL
    ``draft_len`` follower window is preferred — on periodic text the
    newest match always ends right next to the sequence end and would
    truncate every draft to the period length — falling back to the
    most recent occurrence with at least one follower. The followers —
    up to ``min(config.draft_len, max_draft)`` — are the draft (they
    may overlap the suffix itself, which is how repetition drafts its
    own loop). Returns ``[]`` when nothing matches (the scheduler's
    plain-decode fallback) — never raises on short sequences.
    """
    limit = config.draft_len if max_draft is None \
        else min(config.draft_len, int(max_draft))
    L = len(tokens)
    if limit < 1 or L < config.min_ngram + 1:
        return []
    tokens = list(tokens)
    # one 4-byte-per-token encoding per call: every n-gram try below is
    # a C-speed substring search over it, not a Python scan
    data = np.asarray(tokens, "<u4").tobytes()
    for n in range(min(config.ngram, L - 1), config.min_ngram - 1, -1):
        pattern = data[(L - n) * 4:]
        i = _rfind(data, pattern, L - n - limit)   # full follower window
        if i < 0:
            i = _rfind(data, pattern, L - n - 1)   # >= 1 follower
        if i < 0:
            continue
        follow = tokens[i + n:i + n + limit]
        if follow:
            return list(follow)
    return []
