"""Shared symmetric int8 quantization core for the serving quant tiers.

Two storage tiers quantize to int8 with fp32 scales — the KV cache
(:mod:`apex_tpu.serving.kv_quant`, per-``[layer, head]`` scales, PR 10)
and the serving weights (:mod:`apex_tpu.serving.weight_quant`,
per-output-channel scales) — and both depend on exactly the same
numeric core: symmetric linear quantization to ``[-QMAX, QMAX]`` with a
1-D scale vector broadcast at a chosen axis, ``scale = absmax * margin
/ QMAX`` resolution, and the LOUD degenerate-absmax guard (an absmax of
0 would make ``quantize`` divide by ~0 and ``dequantize`` return 0
everywhere; a non-finite one would poison every consumer — both must
fail at construction/calibration time, never later as NaN output).

This module is that core, factored out so the tiers cannot drift: the
grid both quantize on is one implementation, the error bound
(``scale / 2`` per element for in-range inputs, clipping beyond) is one
argument, and a fix to the guard reaches both tiers at once. Everything
here is tier-agnostic — no engine, cache or parameter knowledge.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax.numpy as jnp
import numpy as np

__all__ = ["QMAX", "check_absmax", "dequantize", "expand_scale",
           "quantize", "quantize_host", "scale_from_absmax"]

# symmetric int8: +/-127 levels (the -128 code is never produced, so the
# grid is symmetric and dequantization needs no zero-point)
QMAX = 127


def expand_scale(scale, ndim: int, axis: int):
    """Broadcast a 1-D scale vector to rank ``ndim`` with its dimension
    at ``axis`` — the shape glue every quantized write/read site shares
    (callers with stacked scales — e.g. the KV tier's ``[layers,
    heads]`` — index or broadcast the extra axes themselves)."""
    scale = jnp.asarray(scale, jnp.float32)
    if scale.ndim != 1:
        raise ValueError(f"expand_scale wants a 1-D scale vector, got "
                         f"{scale.shape}")
    shape = [1] * ndim
    shape[axis] = scale.shape[0]
    return scale.reshape(shape)


def quantize(x, scale, *, axis: Optional[int] = None):
    """Symmetric int8 quantization of ``x``: ``round(x / scale)``
    clipped to ``[-QMAX, QMAX]``. With ``axis``, ``scale`` is a 1-D
    vector placed at that axis of ``x`` (the KV tier's per-head axis,
    the weight tier's output-channel axis); without it, ``scale`` must
    already broadcast against ``x``."""
    s = jnp.asarray(scale, jnp.float32) if axis is None \
        else expand_scale(scale, jnp.ndim(x), axis)
    q = jnp.round(jnp.asarray(x, jnp.float32) / s)
    return jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)


def quantize_host(x, scale, *, axis: int) -> np.ndarray:
    """The pure-numpy twin of :func:`quantize` for construction-time
    HOST work (weight quantization happens once, before any device
    placement): same grid, same fp32 math, same round-half-even — but
    the full-size source leaf never transits a device (at real model
    sizes that transient is exactly what the sharder's host-copy
    discipline exists to avoid)."""
    x = np.asarray(x, np.float32)
    s = np.asarray(scale, np.float32)
    shape = [1] * x.ndim
    shape[axis] = s.shape[0]
    q = np.round(x / s.reshape(shape))
    return np.clip(q, -QMAX, QMAX).astype(np.int8)


def dequantize(q, scale, *, axis: Optional[int] = None):
    """Inverse of :func:`quantize` (fp32 out) — the jnp oracle half of
    dequant-in-kernel/epilogue: consumers fold the same scale multiply
    into their block loads (attention kernels) or their GEMM epilogues
    (the weight tier) instead of materialising this."""
    s = jnp.asarray(scale, jnp.float32) if axis is None \
        else expand_scale(scale, jnp.ndim(q), axis)
    return jnp.asarray(q, jnp.float32) * s


def check_absmax(absmax, *, describe: Callable[[Tuple[int, ...]], str],
                 hint: str) -> np.ndarray:
    """The loud degenerate-calibration guard both tiers share: raise
    :class:`ValueError` when any entry of ``absmax`` is zero, negative
    or non-finite. ``describe`` formats the first offending index into
    the tier's own coordinates (``[layer, head]`` / ``output channel``)
    and ``hint`` names the tier's remedy. Returns ``absmax`` as a
    float32 numpy array for the caller's scale resolution."""
    absmax = np.asarray(absmax, np.float32)
    bad = ~np.isfinite(absmax) | (absmax <= 0)
    if bad.any():
        idx = tuple(int(i) for i in np.argwhere(bad)[0])
        raise ValueError(
            f"degenerate {describe(idx)}: {float(absmax[idx])!r} — an "
            f"absmax of 0 or a non-finite absmax would produce "
            f"degenerate quantization scales (all-zero or NaN "
            f"dequantized values); {hint}")
    return absmax


def scale_from_absmax(absmax, margin: float) -> np.ndarray:
    """The one scale resolution both tiers pin their numerics to:
    ``scale = absmax * margin / QMAX`` (fp32). ``margin`` is headroom
    on the calibrated absmax for the KV tier (decode-time values can
    exceed a prompt-sample absmax); the weight tier's absmax is exact
    (weights are static), so its margin only sets the clip-vs-grid
    trade — each tier documents and pins its own default."""
    return (np.asarray(absmax, np.float32)
            * np.float32(margin) / QMAX).astype(np.float32)
