"""Content-addressed KV prefix reuse — the serving engine's prompt cache.

Real traffic is dominated by shared prompt prefixes (system prompts,
few-shot templates, multi-turn history); without reuse every request
re-runs chunk prefill over tokens whose K/V already sit byte-identical
in another row of the cache. This module is the host-side index that
eliminates that recompute:

- **Content addressing**: a retained prefix is keyed by a *rolling hash
  over token blocks* — block ``i``'s key folds block ``i-1``'s, so key
  ``H_i`` identifies the entire ``(i+1)``-block prefix and matching a
  new prompt is one incremental walk over its blocks. Blocks are
  ``block_len`` tokens, aligned to the engine's ``chunk_len``: a match
  always ends on a chunk boundary, so the remaining suffix drops
  straight into the *existing* per-row-offset chunk-prefill program at
  the matched offset — reuse composes with chunked prefill and the
  chunk computations that produced the donor K/V are bitwise identical
  to the ones the cold path would run.
- **Storage**: matched prefixes live in *pool rows* — cache rows the
  engine reserves past its serving slots (``Engine(prefix_pool=N)``).
  Registration copies a completed prompt's block-aligned K/V from its
  serving slot into a pool row through the engine's one compiled
  row-copy program; a hit copies it back into the admitted slot the
  same way.
- **Refcounts + LRU**: every hit pins its donor entry (``acquire``)
  until the request leaves its slot (``release``); eviction is
  least-recently-used over entries at refcount 0 only — a prefix in use
  by a live slot is never evicted. When every entry is pinned and the
  pool is full, registration degrades gracefully: the request is served
  cold and a ``pool_full`` tick is counted, nothing crashes.
- **Exactness**: hash keys are a lookup accelerator, not the source of
  truth — every match is verified token-for-token against the entry's
  retained tokens before it is trusted, so a hash collision can only
  cost a miss, never a wrong-token hit. Matches are additionally capped
  below the full prompt (``aligned(n - 1)``): at least the final block
  always runs through chunk prefill, because that program — not the
  copy — samples the request's first output token.

The class is pure host bookkeeping (dicts and counters); all device
work happens in the engine's copy program, injected per call as
``copy_fn``. Telemetry is the caller's job (the scheduler mirrors
:meth:`stats` into ``serving.prefix.*``); the raw counters here keep the
class importable without a registry.

**Paged entries** (the block-table engine): construct with
``pool_rows=()`` and an ``on_evict`` hook, and register with
``pages=(...)`` instead of ``copy_fn``. A paged entry retains no pool
row and copies nothing — it records the page ids that already hold the
prefix (the engine bumps their refcounts on ``"registered"``), and
eviction hands them back through ``on_evict`` (the engine wires
:meth:`PagePool.release`, so a page still shared with a live slot
survives its entry). Two consequences replace the contiguous pinning
story: registration can never be ``pool_full`` (sharing costs zero new
pages — capacity pressure moves to the engine's admission reservation,
which calls :meth:`evict_lru` instead), and hits need no
acquire/release (the pages protect themselves via refcounts; evicting
a donor entry mid-request is harmless).

**Hierarchical KV** (paged + an engine host tier): eviction under pool
pressure becomes a SWAP — the victim entry's page bytes migrate
device→host (the engine's ``swap_out`` hook, wired via
:meth:`PrefixCache.set_swap_hooks`; by default the hook only
DISPATCHES the migration — the copy completes on a
:class:`~apex_tpu.serving.SwapWorker` thread, off the admission path —
but the snapshot is taken by program order at dispatch, so the hook
returning True means the bytes are safe), its device pages return to
the pool immediately, and the entry stays in the index in the
``swapped`` state (arena-side it passes through *swapping* while the
copy is in flight), so :meth:`match` and :meth:`probe` still report it
(the router's affinity probe keeps seeing swapped AND swapping
prefixes — ``contains`` answers for both). A hit on a swapped entry
carries ``PrefixMatch.swapped=True``; the engine joins any in-flight
copy, migrates the bytes back into fresh pages (checksum-verified — a
corrupt or missing swap-in degrades to a verified miss via
:meth:`drop` + :meth:`unrecord_hit`, never a wrong token) and calls
:meth:`swap_in_complete` before sharing as usual. Prefix capacity is
then bounded by host RAM, not device HBM.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from apex_tpu.log_util import get_logger

__all__ = ["PrefixCache", "PrefixMatch"]

_logger = get_logger("serving")

# synthetic paged-entry keys: ONE process-wide negative counter, not a
# per-cache one. With a per-cache counter two engines sharing one
# HostTier arena (disaggregated serving) would both mint key -1, and a
# put under a colliding key REPLACES — engine A's swapped entry would
# silently come back backed by engine B's bytes, which pass the CRC
# (they are B's honest bytes) while being the WRONG prefix's K/V. The
# keys are opaque host bookkeeping, so global uniqueness costs nothing.
_paged_key = itertools.count(-1, -1)


def _roll(h: int, block: Tuple[int, ...]) -> int:
    """One step of the rolling block hash: fold the previous blocks'
    key with this block's tokens. Host-local (python ``hash``), so it
    needs no cross-process stability — collisions are tolerated because
    every lookup is verified against the entry's retained tokens."""
    return hash((h,) + block)


@dataclasses.dataclass
class _Entry:
    """One retained prefix: ``tokens`` (the full block-aligned prefix)
    living in cache row ``row`` (contiguous layout) or on pool pages
    ``pages`` (paged layout; ``row`` is then a synthetic negative key);
    ``refcount`` pins a contiguous entry against eviction while a live
    slot's admission copied from it (paged entries need no pin — their
    pages carry their own refcounts in the engine's page pool).

    ``swapped`` is the hierarchical-KV tier's resident/swapped state:
    a swapped paged entry holds NO device pages (``pages`` is None,
    ``swapped_pages`` remembers how many it held) — its page bytes
    live in the engine's host-DRAM :class:`~apex_tpu.serving
    .HostTier` under key ``row``, and a hit migrates them back before
    sharing (:meth:`Engine.attach_prefix`'s swap-in path)."""

    row: int
    tokens: Tuple[int, ...]
    n_blocks: int
    refcount: int = 0
    last_used: int = 0
    pages: Optional[Tuple[int, ...]] = None
    swapped: bool = False
    swapped_pages: int = 0


@dataclasses.dataclass(frozen=True)
class PrefixMatch:
    """A verified admission-time hit: copy ``length`` positions from
    cache row ``row`` (then :meth:`PrefixCache.acquire` it for the
    request's slot lifetime) — or, for a paged entry, share ``pages``
    into the admitted slot's page table (``row`` is the entry's
    synthetic key; no acquire needed). ``swapped=True`` marks a hit
    whose page bytes sit in the host tier (``pages`` is None until
    the engine swaps them back in)."""

    row: int
    length: int
    pages: Optional[Tuple[int, ...]] = None
    swapped: bool = False


class PrefixCache:
    """Host-side index of retained prompt prefixes (see module
    docstring). ``block_len`` must equal the engine's ``chunk_len``;
    ``pool_rows`` are the cache row ids reserved for retained prefixes
    (the engine hands over ``[slots, slots + prefix_pool)``)."""

    def __init__(self, *, block_len: int, pool_rows: Sequence[int] = (),
                 on_evict: Optional[Callable[[Tuple[int, ...]],
                                             None]] = None):
        if block_len < 1:
            raise ValueError("block_len must be >= 1")
        self.block_len = int(block_len)
        self.pool_rows: List[int] = list(pool_rows)
        if len(set(self.pool_rows)) != len(self.pool_rows):
            raise ValueError("pool_rows must be distinct")
        self._free: List[int] = list(self.pool_rows)
        self._entries: Dict[int, _Entry] = {}        # row/key -> entry
        self._index: Dict[int, Tuple[int, int]] = {}  # key -> (row, blocks)
        self._clock = itertools.count(1)
        # paged entries: synthetic negative keys (never collide with
        # cache row ids, nor — being process-unique — with sibling
        # caches sharing one host arena) + the page-release hook
        # eviction fires
        self._paged_key = _paged_key
        self._on_evict = on_evict
        # hierarchical-KV hooks (engine-wired via set_swap_hooks; both
        # None = no host tier, eviction destroys as always)
        self._swap_out_fn: Optional[Callable[[int, Tuple[int, ...]],
                                             bool]] = None
        self._swap_contains: Optional[Callable[[int], bool]] = None
        # raw counters (the scheduler mirrors them into serving.prefix.*)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.pool_full = 0
        self.tokens_reused = 0
        self.registrations = 0
        self.swap_outs = 0
        self.swap_ins = 0

    # ------------------------------------------------------------- geometry
    @property
    def capacity(self) -> int:
        return len(self.pool_rows)

    @property
    def size(self) -> int:
        return len(self._entries)

    @property
    def hit_rate(self) -> float:
        """Hits over admissions consulted so far (0.0 before the first)."""
        n = self.hits + self.misses
        return self.hits / n if n else 0.0

    # -------------------------------------------------------------- hashing
    def block_keys(self, tokens: Sequence[int], n_blocks: int) -> List[int]:
        """The first ``n_blocks`` rolling keys of ``tokens`` — ``H_i``
        covers blocks ``[0, i]`` (``(i+1) * block_len`` tokens)."""
        keys, h = [], 0
        for i in range(n_blocks):
            block = tuple(int(t) for t in
                          tokens[i * self.block_len:(i + 1) * self.block_len])
            h = _roll(h, block)
            keys.append(h)
        return keys

    # ------------------------------------------------------------- matching
    def match(self, prompt: Sequence[int],
              keys: Optional[Sequence[int]] = None) -> \
            Optional[PrefixMatch]:
        """Longest cached block-aligned prefix of ``prompt``, verified
        token-for-token; None on a miss. The match never covers the
        whole prompt (cap ``aligned(n - 1)``): the final block must run
        through chunk prefill so its logits produce the request's first
        token. Counts toward :attr:`hit_rate` either way.

        ``keys`` (optional) are ``prompt``'s PRECOMPUTED rolling block
        keys — at least ``(n - 1) // block_len`` of them, e.g. from
        :meth:`block_keys` run on a :class:`~apex_tpu.serving
        .DraftWorker` thread at submit time (the async heartbeat's
        hash offload). The hash is deterministic and every hit is
        still verified token-for-token below, so precomputed and
        inline keys are interchangeable bit-for-bit."""
        best = self._best_match(prompt, keys)
        if best is None:
            self.misses += 1
            return None
        self.hits += 1
        self.tokens_reused += best.length
        entry = self._entries[best.row]
        entry.last_used = next(self._clock)
        return best

    def probe(self, prompt: Sequence[int],
              keys: Optional[Sequence[int]] = None) -> int:
        """READ-ONLY affinity probe: the length of the longest cached
        block-aligned prefix of ``prompt`` (0 on a miss), verified
        token-for-token exactly like :meth:`match` — but touching
        NOTHING: no hit/miss counters, no LRU refresh, no refcounts.
        This is the :class:`~apex_tpu.serving.Router`'s routing signal
        — it probes EVERY replica's cache per request, and a probe that
        counted would poison :attr:`hit_rate` (and churn LRU order) on
        the N-1 replicas the request never lands on. Same ``keys``
        contract as :meth:`match`."""
        best = self._best_match(prompt, keys)
        return 0 if best is None else best.length

    def _best_match(self, prompt: Sequence[int],
                    keys: Optional[Sequence[int]] = None) -> \
            Optional[PrefixMatch]:
        """The pure match walk shared by :meth:`match` (which adds
        counter + LRU bookkeeping) and :meth:`probe` (which must not)."""
        n = len(prompt)
        max_blocks = (n - 1) // self.block_len       # strictly < n tokens
        if keys is None:
            keys = self.block_keys(prompt, max_blocks)
        best: Optional[PrefixMatch] = None
        for i in range(max_blocks):
            h = keys[i]
            hit = self._index.get(h)
            if hit is None:
                continue
            row, blocks = hit
            entry = self._entries.get(row)
            length = blocks * self.block_len
            if entry is None or len(entry.tokens) < length:
                continue
            # hash keys accelerate, tokens decide: a collision (or an
            # entry the key outlived) can only cost a miss here
            if tuple(entry.tokens[:length]) != tuple(
                    int(t) for t in prompt[:length]):
                continue
            if entry.swapped:
                # hierarchical KV: the entry's page bytes live in the
                # host tier. A hit is still a hit — the engine swaps
                # them back in at attach time — but only while the
                # tier actually holds the bytes (contains is a pure
                # read: probe stays side-effect-free through it)
                if self._swap_contains is None \
                        or not self._swap_contains(row):
                    continue
                best = PrefixMatch(row=row, length=length, pages=None,
                                   swapped=True)
                continue
            if entry.pages is None:
                pages = None
            else:
                # the entry's page_len: its tokens spread evenly over
                # its pages (both block- and page-aligned by the
                # engine's registration contract)
                page_len = len(entry.tokens) // len(entry.pages)
                pages = entry.pages[:length // page_len]
            best = PrefixMatch(row=row, length=length, pages=pages)
        return best

    # ------------------------------------------------------------ refcounts
    def acquire(self, match: PrefixMatch) -> None:
        """Pin the matched entry while the admitted request occupies its
        slot (the scheduler releases on request finish/eviction)."""
        self._entries[match.row].refcount += 1

    def release(self, match: PrefixMatch) -> None:
        entry = self._entries.get(match.row)
        if entry is not None and entry.refcount > 0:
            entry.refcount -= 1

    def unrecord_hit(self, match: PrefixMatch) -> None:
        """Reverse one :meth:`match`'s hit accounting — the failed
        swap-in path (missing or checksum-failed host bytes): the
        engine degrades the hit to a verified miss and re-prefills, so
        the counters must read a miss too or :attr:`hit_rate` would
        claim reuse that never happened."""
        self.hits -= 1
        self.misses += 1
        self.tokens_reused -= match.length

    # ---------------------------------------------------------- registration
    def register(self, prompt: Sequence[int],
                 copy_fn: Optional[Callable[[int, int], None]] = None,
                 *, pages: Optional[Sequence[int]] = None,
                 keys: Optional[Sequence[int]] = None) -> str:
        """Retain ``prompt``'s block-aligned prefix. Contiguous layout:
        ``copy_fn(row, length)`` runs the engine's row-copy program
        (serving slot → pool row ``row``) and is called at most once,
        only after a row is secured. Paged layout: pass ``pages``
        instead — the page ids already holding the prefix; no copy, no
        row, and the CALLER bumps the pages' refcounts iff the outcome
        is ``"registered"`` (eviction releases them through
        ``on_evict``). Returns the outcome:

        - ``"registered"`` — a pool row was (re)filled with the prefix
          (contiguous) / the prefix's pages were recorded (paged);
        - ``"duplicate"`` — the exact prefix is already retained (LRU
          refreshed, no copy, no extra refcounts);
        - ``"too_short"`` — the prompt spans no full block;
        - ``"pool_full"`` — contiguous only: every row is held by a
          pinned (refcount > 0) entry — graceful degradation, nothing
          evicted. Paged registration never hits this (sharing costs
          zero new pages).

        ``keys`` (optional) are the prompt's precomputed rolling block
        keys (at least ``n_blocks`` of them) — same contract as
        :meth:`match`.
        """
        if (copy_fn is None) == (pages is None):
            raise ValueError("register takes exactly one of copy_fn "
                             "(contiguous) or pages (paged)")
        n_blocks = len(prompt) // self.block_len
        if n_blocks == 0:
            return "too_short"
        length = n_blocks * self.block_len
        keys = self.block_keys(prompt, n_blocks) if keys is None \
            else list(keys[:n_blocks])
        hit = self._index.get(keys[-1])
        if hit is not None:
            row, blocks = hit
            entry = self._entries.get(row)
            if entry is not None and blocks == n_blocks and tuple(
                    entry.tokens[:length]) == tuple(
                    int(t) for t in prompt[:length]):
                entry.last_used = next(self._clock)
                return "duplicate"
        if pages is not None:
            if length % len(pages):
                raise ValueError(
                    f"{len(pages)} pages cannot evenly hold a "
                    f"{length}-token prefix")
            row = next(self._paged_key)
            entry = _Entry(row=row,
                           tokens=tuple(int(t) for t in prompt[:length]),
                           n_blocks=n_blocks, last_used=next(self._clock),
                           pages=tuple(int(p) for p in pages))
        else:
            row = self._take_row()
            if row is None:
                self.pool_full += 1
                return "pool_full"
            try:
                copy_fn(row, length)
            except BaseException:
                self._free.append(row)   # don't leak the row on a failed copy
                raise
            entry = _Entry(row=row,
                           tokens=tuple(int(t) for t in prompt[:length]),
                           n_blocks=n_blocks, last_used=next(self._clock))
        self._entries[row] = entry
        for i, key in enumerate(keys):
            # shorter-prefix keys already owned by another entry keep
            # their owner (it is just as valid a donor); this entry
            # claims every depth not yet addressed
            if key not in self._index:
                self._index[key] = (row, i + 1)
        self.registrations += 1
        return "registered"

    def register_handoff(self, key: int, prompt: Sequence[int], *,
                         pages: Optional[Sequence[int]] = None,
                         n_pages: int = 0,
                         keys: Optional[Sequence[int]] = None) -> str:
        """Register a disaggregated-serving HANDOFF prefix under an
        EXTERNALLY supplied key (the request uid — positive, globally
        unique, so records from N engines sharing one
        :class:`~apex_tpu.serving.HostTier` arena can never collide
        the way each cache's private negative synthetic keys would).
        Two sides of the same handoff:

        - **exporter** (prefill-role engine): pass ``pages`` — the
          slot's page ids holding the ingested prefix. The entry is
          registered RESIDENT exactly like an ordinary paged
          registration (the caller bumps page refcounts on
          ``"registered"``), ready for :meth:`swap_out_key` to land it
          in the shared arena.
        - **importer** (decode-role engine): pass ``n_pages`` with
          ``pages=None`` — the entry is born directly in the
          ``swapped`` state, backed by the arena record the exporter
          already published; the ordinary admission match + swap-in
          machinery then restores and shares it (or degrades to a
          verified miss) with zero handoff-specific code.

        Either way the entry is an ORDINARY swapped/resident prefix
        afterwards: affinity probes see it, host-capacity eviction
        drops it, ``drop``/``swap_in_complete`` treat it like any
        other. An existing entry under ``key`` is replaced (uid keys
        are single-writer by construction). Returns ``"registered"``
        or ``"too_short"`` (no full block — nothing worth handing
        off)."""
        if (pages is not None) and n_pages:
            raise ValueError("register_handoff takes pages (exporter) "
                             "or n_pages (importer), not both")
        key = int(key)
        if key < 0:
            raise ValueError("handoff keys are request uids (>= 0); "
                             "negative keys are the cache's private "
                             "synthetic namespace")
        n_blocks = len(prompt) // self.block_len
        if n_blocks == 0:
            return "too_short"
        length = n_blocks * self.block_len
        if pages is not None and length % len(pages):
            raise ValueError(
                f"{len(pages)} pages cannot evenly hold a "
                f"{length}-token prefix")
        keys = self.block_keys(prompt, n_blocks) if keys is None \
            else list(keys[:n_blocks])
        self.drop(key)              # uid re-registration replaces
        entry = _Entry(
            row=key, tokens=tuple(int(t) for t in prompt[:length]),
            n_blocks=n_blocks, last_used=next(self._clock),
            pages=(tuple(int(p) for p in pages)
                   if pages is not None else None),
            swapped=pages is None,
            swapped_pages=0 if pages is not None else int(n_pages))
        self._entries[key] = entry
        for i, k in enumerate(keys):
            if k not in self._index:
                self._index[k] = (key, i + 1)
        self.registrations += 1
        return "registered"

    def swap_out_key(self, key: int) -> bool:
        """Targeted resident→swapped migration of entry ``key`` (the
        handoff export: the entry's bytes must land in the shared
        arena NOW, not whenever LRU pressure would have picked it).
        Same contract as the :meth:`evict_lru` swap path — the engine
        hook snapshots the bytes before the device pages are released.
        False when the key is unknown, already swapped, or the tier
        declined (the caller hands off without a record and the
        importer re-prefills)."""
        entry = self._entries.get(int(key))
        if entry is None or entry.swapped:
            return False
        return self._swap_out(entry)

    def _take_row(self) -> Optional[int]:
        """A free pool row, evicting the least-recently-used refcount-0
        entry when none is free; None when every entry is pinned."""
        if self._free:
            return self._free.pop()
        victims = [e for e in self._entries.values() if e.refcount == 0]
        if not victims:
            return None
        victim = min(victims, key=lambda e: e.last_used)
        self._evict(victim)
        return victim.row

    def evict_lru(self) -> bool:
        """Evict the least-recently-used refcount-0 entry (pool-pressure
        valve: the paged engine calls this when an admission reservation
        cannot be covered — retained prefixes are a cache, the admitted
        request is not). False when nothing is evictable.

        With a host tier wired (:meth:`set_swap_hooks`) eviction is a
        SWAP-OUT first: the victim's page bytes migrate device→host and
        the entry stays matchable in the ``swapped`` state — its device
        pages are released either way, which is what the caller's
        pressure loop needs. Only resident entries are victims: a
        swapped entry holds no device pages, so evicting it would free
        nothing (the pressure loop would spin) — swapped entries leave
        the tier through host-capacity eviction or a failed swap-in,
        never through this valve."""
        victims = [e for e in self._entries.values()
                   if e.refcount == 0 and not e.swapped]
        if not victims:
            return False
        victim = min(victims, key=lambda e: e.last_used)
        if self._swap_out(victim):
            return True
        self._evict(victim)
        return True

    # -------------------------------------------------- hierarchical KV
    def set_swap_hooks(self, *, swap_out: Callable[[int, Tuple[int, ...]],
                                                   bool],
                       contains: Callable[[int], bool]) -> None:
        """Wire the host-DRAM tier (engine-side): ``swap_out(key,
        pages)`` migrates an evicted entry's page bytes device→host
        and returns True on success — True may mean the copy is merely
        DISPATCHED (async swap-out): the engine guarantees the
        snapshot precedes any page reuse, so this cache treats the
        entry as swapped either way. False = tier off/declined → the
        entry is destroyed, the pre-tier behaviour. ``contains(key)``
        is the read-only backing probe the match walk consults for
        swapped entries (in-flight *swapping* entries answer True)."""
        self._swap_out_fn = swap_out
        self._swap_contains = contains

    def _swap_out(self, entry: _Entry) -> bool:
        """Migrate ``entry`` resident→swapped: bytes to the host tier
        (via the engine hook, which must SNAPSHOT the bytes — copy, or
        dispatch the compiled gather that program-orders the copy —
        BEFORE this releases the device pages), page refcounts back to
        the pool. False — and no state change — when no tier is wired,
        the entry is not paged, or the tier declined the bytes."""
        if self._swap_out_fn is None or entry.pages is None:
            return False
        if not self._swap_out_fn(entry.row, entry.pages):
            return False
        if self._on_evict is not None:
            self._on_evict(entry.pages)
        entry.swapped_pages = len(entry.pages)
        entry.pages = None
        entry.swapped = True
        self.swap_outs += 1
        _logger.debug("prefix cache swapped out %d-block prefix "
                      "(key %d, %d pages)", entry.n_blocks, entry.row,
                      entry.swapped_pages)
        return True

    def swap_in_complete(self, key: int, pages: Sequence[int]) -> None:
        """Mark entry ``key`` resident again on freshly migrated
        ``pages`` (the engine already wrote the host bytes into them
        and holds one refcount per page on the entry's behalf — the
        same ownership shape registration leaves behind)."""
        entry = self._entries[key]
        if not entry.swapped:
            raise ValueError(f"entry {key} is not swapped")
        entry.pages = tuple(int(p) for p in pages)
        entry.swapped = False
        entry.swapped_pages = 0
        self.swap_ins += 1

    def drop(self, key: int) -> bool:
        """Fully evict entry ``key`` (resident or swapped): the failed-
        swap-in degradation and the host tier's capacity-eviction
        callback both land here. A resident victim's pages go back
        through ``on_evict``; a swapped victim holds none. False when
        the key is unknown (already dropped)."""
        entry = self._entries.get(key)
        if entry is None:
            return False
        self._evict(entry)
        return True

    def swapped_keys(self) -> List[int]:
        """Keys of entries currently in the swapped state — the
        :class:`~apex_tpu.serving.PoolAuditor`'s cross-tier view:
        every one of these must be backed by a host-tier entry, and
        every host-tier entry must appear here."""
        return [e.row for e in self._entries.values() if e.swapped]

    def _evict(self, entry: _Entry) -> None:
        del self._entries[entry.row]
        for key, (_, blocks) in [(k, v) for k, v in self._index.items()
                                 if v[0] == entry.row]:
            # a shorter shared prefix the victim addressed may still be
            # resident inside a surviving longer entry — rebind instead
            # of orphaning the depth (keeps "longest cached prefix"
            # true after churn)
            heir = next(
                (e for e in self._entries.values()
                 if e.n_blocks >= blocks and e.tokens[:blocks
                    * self.block_len] == entry.tokens[:blocks
                    * self.block_len]), None)
            if heir is None:
                del self._index[key]
            else:
                self._index[key] = (heir.row, blocks)
        self.evictions += 1
        if entry.pages is not None and self._on_evict is not None:
            # hand the entry's page refcounts back (a page still shared
            # with a live slot survives — the pool frees it at zero)
            self._on_evict(entry.pages)
        _logger.debug("prefix cache evicted %d-block prefix from row %d",
                      entry.n_blocks, entry.row)

    # ------------------------------------------------------------- lifecycle
    def clear(self) -> None:
        """Drop every entry and index key (counters survive — they are
        run-scoped, not cache-scoped). Paged entries hand their page
        refcounts back through ``on_evict`` so the pool reclaims them."""
        if self._on_evict is not None:
            for entry in self._entries.values():
                if entry.pages is not None:
                    self._on_evict(entry.pages)
        self._entries.clear()
        self._index.clear()
        self._free = list(self.pool_rows)

    def page_holds(self) -> List[Tuple[int, ...]]:
        """Every paged entry's retained page-id tuple — the refcounts
        the cache legitimately holds in the engine's
        :class:`~apex_tpu.serving.PagePool`, exposed for the
        :class:`~apex_tpu.serving.PoolAuditor`'s reconciliation walk.
        Empty for a contiguous-layout cache (row entries hold no
        pages)."""
        return [entry.pages for entry in self._entries.values()
                if entry.pages is not None]

    def stats(self) -> dict:
        """One host-side snapshot of the cache's counters and occupancy
        (the scheduler mirrors this into ``serving.prefix.*``)."""
        return {
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hit_rate,
            "tokens_reused": self.tokens_reused,
            "evictions": self.evictions,
            "pool_full": self.pool_full,
            "registrations": self.registrations,
            "swap_outs": self.swap_outs,
            "swap_ins": self.swap_ins,
            "entries": self.size,
            "swapped_entries": len(self.swapped_keys()),
            "capacity": self.capacity,
        }

    _DELTA_KEYS = ("hits", "misses", "tokens_reused", "evictions",
                   "pool_full", "registrations", "swap_outs",
                   "swap_ins")

    def stats_since(self, baseline: dict) -> dict:
        """The counter DELTAS since ``baseline`` (a prior :meth:`stats`
        snapshot), with ``hit_rate`` recomputed over the window's own
        hits/misses. The raw counters are run-scoped, not cache-scoped —
        they survive :meth:`clear` and every engine ``reset()`` on
        purpose (cumulative totals stay honest across warm windows) —
        so any per-window reading (the router's per-replica affinity
        accounting, the bench's measured-window hit rate) must be a
        delta: reading :attr:`hit_rate` directly after a warm reset
        silently blends the warmup's hits in. Occupancy (``entries`` /
        ``capacity``) is reported as-of-now — it is state, not a
        counter."""
        now = self.stats()
        out = {k: now[k] - baseline.get(k, 0) for k in self._DELTA_KEYS}
        consulted = out["hits"] + out["misses"]
        out["hit_rate"] = out["hits"] / consulted if consulted else 0.0
        out["entries"] = self.size
        out["swapped_entries"] = len(self.swapped_keys())
        out["capacity"] = self.capacity
        return out
