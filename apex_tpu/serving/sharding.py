"""Tensor-parallel sharding for the serving engine's compiled programs.

Everything a single-chip ``serving.Engine`` compiles — paged decode,
chunk prefill, monolithic prefill, speculative verify — is capped by
one chip's HBM and FLOPs. This module supplies the three pieces that
let ``Engine(mesh=...)`` serve the SAME programs Megatron-style over a
tensor-parallel mesh axis:

1. **a partition-rule table** over the :class:`~apex_tpu.models
   .transformer_lm.TransformerLM` parameter pytree
   (:func:`partition_rules` + :func:`match_partition_rules`, the
   ``match_partition_rules`` idiom from the pjit exemplars): attention
   qkv and the MLP up-projection are COLUMN-parallel (output features
   split over the ``tp`` axis), the attention output projection and the
   MLP down-projection are ROW-parallel (input features split),
   embeddings / positional table / LayerNorms replicated;
2. **a parameter sharder** (:func:`shard_params`) that places the cast
   param tree on the mesh per those rules — including the two host-side
   transforms a plain even split cannot express:

   - the fused qkv kernel's output axis is laid out ``(3, heads, d)``,
     so a contiguous split would hand shard 0 all of Q plus half of K
     — :func:`shard_params` PERMUTES it to ``(tp, 3, heads/tp, d)``
     first, so the even split per the rule gives every shard its own
     heads' Q, K **and** V in the exact ``(3, local_heads, d)`` layout
     the per-shard module expects;
   - ROW-parallel biases are value-scaled by ``1/tp``: the module adds
     the bias inside its Dense on every shard and the post-GEMM
     ``psum`` sums the shards, so ``psum(x @ W_t + b/tp) = x @ W + b``
     exactly once (``1/tp`` is an exponent shift for power-of-two tp —
     exact in bf16/fp32; tp=1 is the identity);

3. **cache/pool specs** (:func:`cache_pspec`): the paged KV pool is
   sharded along the HEADS axis — ``[layers, num_pages, heads/tp,
   page_len, head_dim]`` per shard — so every attention gather, page
   scatter and per-page kernel step is shard-local. Attention NEVER
   crosses ICI: each shard runs the unchanged paged kernels over fewer
   heads (the grid over ``batch x heads`` simply has fewer rows), and
   page tables / lengths / tokens / sampling scalars stay replicated
   host state.

The collective inventory this buys (:func:`expected_collectives`, the
HLO pin in ``tests/L0/test_sharding.py``):

- **2 psums per transformer block** — after the row-parallel attention
  projection and after the row-parallel MLP down-projection (the two
  canonical Megatron all-reduces; residual stream replicated);
- **1 all-gather at the logits** — the tied LM head is computed
  vocab-parallel (each shard matmuls its ``vocab/tp`` slice of the
  replicated embedding, cutting the head GEMM — the largest single
  matmul in a decode step — by ``tp``) and only the ROWS BEING SAMPLED
  are gathered back to the full vocabulary (``[rows, vocab/tp]`` →
  ``[rows, vocab]``), so greedy/temperature/top-k sampling and the
  fused non-finite guard run on full rows exactly as on one chip.

``Engine(mesh=None)`` remains the verbatim single-chip baseline (none
of this module is on that path); a ``tp=1`` mesh runs the sharded
programs over one device — identity collectives, bitwise-pinned against
``mesh=None`` on a greedy stream.
"""

from __future__ import annotations

import re
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["partition_rules", "match_partition_rules", "shard_params",
           "cache_pspec", "scale_pspec", "shard_cache", "zeros_sharded",
           "expected_collectives", "tp_axis_of", "validate_tp_geometry"]

# host-side transforms a plain even split cannot express, keyed by the
# SAME regexes the rule table uses (see shard_params). kernel_scale is
# the weight-quant tier's per-output-channel dequant vector: it lives
# on the qkv OUTPUT axis, so it rides the same head-group permutation
# as the kernel and bias — every local channel keeps its own scale,
# which is what makes tp=1 bitwise vs the unsharded quantized engine.
_QKV_RE = re.compile(r"attn/qkv/(kernel|bias|kernel_scale)$")
_ROW_BIAS_RE = re.compile(r"(attn/proj|mlp_out)/bias$")


def partition_rules(axis: str = "tp") -> Tuple[Tuple[str, PartitionSpec],
                                               ...]:
    """The TransformerLM partition-rule table: ``(regex, PartitionSpec)``
    pairs matched first-wins against ``/``-joined parameter paths
    (``block_0/attn/qkv/kernel``). Column-parallel output splits for
    qkv and the MLP up-projection, row-parallel input splits for the
    output projections, everything else replicated (embeddings stay
    replicated so the lookup is collective-free; the logits are sliced
    vocab-parallel *in-program* instead — see the module docstring)."""
    P = PartitionSpec
    # kernel_scale leaves are the weight-quant tier's per-output-channel
    # dequant vectors: column-parallel kernels split on the OUTPUT axis,
    # so their scales split with them (qkv's additionally head-group
    # permuted — see _QKV_RE); row-parallel kernels split on the INPUT
    # axis, so their per-output scales replicate (the scale is constant
    # across shards, which is exactly why scaling each partial sum
    # before the psum is exact). wte's embedding_scale replicates with
    # the embedding via the catch-all; the vocab-parallel head slices
    # matrix and scale together in-program.
    return (
        (r"attn/qkv/kernel$", P(None, axis)),   # column-parallel (heads)
        (r"attn/qkv/bias$", P(axis)),
        (r"attn/qkv/kernel_scale$", P(axis)),
        (r"attn/proj/kernel$", P(axis, None)),  # row-parallel
        (r"attn/proj/bias$", P()),              # replicated, scaled 1/tp
        (r"attn/proj/kernel_scale$", P()),      # replicated (row-par.)
        (r"mlp_in/kernel$", P(None, axis)),     # column-parallel
        (r"mlp_in/bias$", P(axis)),
        (r"mlp_in/kernel_scale$", P(axis)),
        (r"mlp_out/kernel$", P(axis, None)),    # row-parallel
        (r"mlp_out/bias$", P()),                # replicated, scaled 1/tp
        (r"mlp_out/kernel_scale$", P()),        # replicated (row-par.)
        (r".*", P()),   # wte(+scale)/wpe/LayerNorms/ln_f: replicated
    )


def _leaf_name(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_partition_rules(rules, params):
    """A pytree of :class:`PartitionSpec` mirroring ``params``: each
    leaf gets the spec of the first rule whose regex ``re.search``-es
    its ``/``-joined path (the ``match_partition_rules`` idiom). Scalar
    leaves are always replicated; a leaf no rule matches is an error —
    an unsharded new parameter must be CHOSEN, not defaulted silently
    (the catch-all ``.*`` rule in :func:`partition_rules` is that
    choice, made visibly)."""

    def _spec(path, leaf):
        name = _leaf_name(path)
        if np.ndim(leaf) == 0 or np.size(leaf) == 1:
            return PartitionSpec()
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        raise ValueError(f"no partition rule matches param {name!r}")

    return jax.tree_util.tree_map_with_path(_spec, params)


def tp_axis_of(mesh) -> str:
    """The mesh's tensor-parallel axis name. Serving meshes are 1-D —
    the KV pool shards over exactly one axis (heads), so a 2-D mesh is
    a configuration error named loudly here."""
    names = tuple(mesh.axis_names)
    if len(names) != 1:
        raise ValueError(
            f"serving needs a 1-D tensor-parallel mesh, got axes "
            f"{names}: shard the engine over one axis (heads/MLP) and "
            "scale further with replica engines")
    return names[0]


def validate_tp_geometry(tp: int, *, num_heads: int, hidden: int,
                         mlp_ratio: int, vocab_size: int) -> None:
    """The divisibility contract a tensor-parallel engine needs:
    heads (the KV pool's shard axis and attention's work unit), the MLP
    inner width (column/row splits) and the vocabulary (the in-program
    logits slice) must all split evenly over ``tp``. Rejected at
    construction — a ragged shard would otherwise surface as a shape
    error deep inside the first traced program."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if num_heads % tp:
        raise ValueError(
            f"num_heads {num_heads} is not divisible by tp={tp}: the "
            "KV pool shards along the heads axis, so every shard must "
            "own a whole number of heads")
    if (mlp_ratio * hidden) % tp:
        raise ValueError(
            f"MLP inner width {mlp_ratio * hidden} is not divisible by "
            f"tp={tp} (column/row-parallel MLP split)")
    if vocab_size % tp:
        raise ValueError(
            f"vocab_size {vocab_size} is not divisible by tp={tp}: the "
            "tied LM head computes a vocab/tp logits slice per shard")


def _group_qkv_kernel(kernel, tp: int, num_heads: int):
    """Permute a fused qkv kernel ``[in, 3*heads*d]`` (output laid out
    ``(3, heads, d)``) so a contiguous even split over the output axis
    hands shard ``t`` its own heads' Q, K and V in ``(3, heads/tp, d)``
    order — the exact layout the per-shard module's
    ``reshape(B, S, 3, local_heads, d)`` expects."""
    three_h = kernel.shape[-1]
    d = three_h // (3 * num_heads)
    hl = num_heads // tp
    lead = kernel.shape[:-1]
    k = kernel.reshape(*lead, 3, tp, hl, d)
    # (..., 3, tp, hl, d) -> (..., tp, 3, hl, d): shard-major
    k = np.moveaxis(k, -4, -3)
    return np.ascontiguousarray(k).reshape(*lead, three_h)


def shard_params(params, mesh, *, num_heads: int, axis: str = None,
                 rules=None):
    """Place a (policy-cast) TransformerLM param tree on ``mesh`` per
    the partition-rule table: qkv leaves are head-group permuted first
    (see :func:`_group_qkv_kernel`), row-parallel biases are value-
    scaled by ``1/tp`` (the per-shard Dense adds the scaled bias and
    the post-GEMM psum restores it exactly once), then every leaf is
    ``device_put`` with its rule's :class:`NamedSharding`. ``tp=1``
    leaves every value bitwise untouched (permutation and scaling are
    identities).

    The transforms run on HOST copies: each leaf is pulled to numpy,
    permuted/scaled there, and ``device_put`` straight into its sharded
    layout — so no device ever holds a transient full-size permuted
    copy of the weights (the caller's original arrays are the caller's;
    at real model sizes pass host-resident params)."""
    if axis is None:
        axis = tp_axis_of(mesh)
    if rules is None:
        rules = partition_rules(axis)
    tp = int(np.prod([mesh.shape[a] for a in mesh.axis_names]))
    specs = match_partition_rules(rules, params)

    def _place(path, leaf, spec):
        name = _leaf_name(path)
        arr = np.asarray(leaf)
        if _QKV_RE.search(name):
            arr = _group_qkv_kernel(arr, tp, num_heads)
        elif _ROW_BIAS_RE.search(name) and tp > 1:
            # exact for power-of-two tp (exponent shift); the fp32
            # round-trip keeps ml_dtypes halves off numpy ufunc paths
            arr = (arr.astype(np.float32) / tp).astype(arr.dtype)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(_place, params, specs)


def cache_pspec(axis: str = "tp") -> PartitionSpec:
    """The paged KV pool's partition spec: ``[layers, num_pages,
    heads/tp, page_len, head_dim]`` per shard — heads-axis sharding, so
    attention never crosses ICI (each shard's paged kernels run
    unchanged over fewer heads; page tables and lengths stay replicated
    host state)."""
    return PartitionSpec(None, None, axis, None, None)


def scale_pspec(axis: str = "tp") -> PartitionSpec:
    """The quantized-cache tier's scale spec: per-``[layer, head]``
    dequantization scales split along the SAME heads axis as the pool
    (``[layers, heads/tp]`` per shard), so every shard quantizes and
    dequantizes its own heads with its own slice — the int8 tier adds
    zero collectives, exactly like the pool sharding itself."""
    return PartitionSpec(None, axis)


def shard_cache(cache, mesh, axis: str = None):
    """Reshard an EXISTING :class:`~apex_tpu.serving.PagedKVCache` onto
    ``mesh`` with the heads-sharded pool spec. For a FRESH pool prefer
    :func:`zeros_sharded` — resharding an existing pool necessarily
    holds the full arrays somewhere first, which is exactly what a pool
    sized to aggregate HBM cannot afford."""
    if axis is None:
        axis = tp_axis_of(mesh)
    ns = NamedSharding(mesh, cache_pspec(axis))
    return cache.replace(k=jax.device_put(cache.k, ns),
                         v=jax.device_put(cache.v, ns))


def zeros_sharded(shape, dtype, mesh, spec: PartitionSpec):
    """Allocate a zeroed array DIRECTLY in its sharded layout: a jitted
    ``zeros`` with sharded ``out_shardings``, so XLA materialises each
    shard on its own device and NO chip ever holds the full array. This
    is what lets ``Engine(mesh=...)`` build a KV pool sized to
    AGGREGATE HBM — the whole point of sharding it — instead of OOMing
    device 0 on a transient full-size allocation at construction."""
    ns = NamedSharding(mesh, spec)
    with mesh:
        return jax.jit(lambda: jnp.zeros(shape, dtype),
                       out_shardings=ns)()


def expected_collectives(num_layers: int) -> dict:
    """The collective inventory of ONE sharded serving program (the
    scheduled-HLO pin): two all-reduces per transformer block (post-
    attention-projection and post-MLP-down-projection psums) and one
    all-gather at the logits (the sampled rows' ``vocab/tp`` slices
    rejoined). The embedding lookup is collective-free (replicated
    table) and the KV pool is heads-sharded, so attention itself adds
    nothing."""
    return {"all_reduce": 2 * int(num_layers), "all_gather": 1}
