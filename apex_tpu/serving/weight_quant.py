"""Quantized serving weights: int8 GEMM kernels with per-output-channel
fp32 scales, dequantized in the matmul epilogue.

The amp cast policies (:mod:`apex_tpu.amp.policy` O0-O3) pick the
COMPUTE half dtype and PR 10's :class:`~apex_tpu.serving.KVQuantConfig`
picked the cache STORAGE dtype; this module extends the same machinery
to the third HBM-resident population — the serving weights. The big
GEMM kernels of every transformer block (fused qkv, attention output
projection, MLP up/down) plus the tied vocab head (the ``wte``
embedding, doubling as the LM head matrix) are stored as int8 with one
fp32 scale per OUTPUT CHANNEL, and the scale multiplies the GEMM's
accumulator in the epilogue — exactly where PR 10 folds KV scales into
the attention kernels' block loads — so dequantized weights never
materialise and the engine's compiled-program set is unchanged (the
trace-count pins hold; quantization is a params property, not a new
executable). Together with int8 KV this roughly doubles model-size
headroom per chip on top of the KV tier's 2x concurrency.

Scale layout — per output channel, the design's load-bearing choice:

- **epilogue fold is exact algebra**: with one scale per output channel
  ``j``, ``sum_i x_i * (Wq_ij * s_j) == (sum_i x_i * Wq_ij) * s_j`` —
  the multiply commutes out of the contraction, so dequant rides the
  accumulator for free (per-input-channel or per-block scales would
  not commute and would force a materialised dequant or a custom
  kernel);
- **tensor parallelism shards scales with their weights** under the
  PR 9 partition-rule table: column-parallel kernels (qkv, mlp_in)
  split on the output axis, so their scale vectors split the same way
  (the fused qkv layout is head-group PERMUTED before splitting —
  scales ride the same permutation, so every local channel keeps its
  own scale and tp=1 stays bitwise vs unsharded); row-parallel kernels
  (proj, mlp_out) split on the INPUT axis, so their per-output scales
  replicate, and ``psum(partial_shard * s + b/tp) == s * sum(partials)
  + b`` — scaling each shard's partial sum before the reduce is exact
  because the scale is constant across shards;
- **the tied head quantizes per vocab row**: the head GEMM's output
  channels are vocab entries, so the embedding gets one scale per row —
  the embedding LOOKUP dequantizes its row by the same scale (one
  gathered multiply), and the vocab-parallel head slices scale and
  matrix together with the same ``dynamic_slice``.

Calibration needs no forward pass: unlike K/V (activations whose range
must be sampled), weights are static — the per-channel absmax read off
the checkpoint IS the range, so ``margin`` is not headroom here:
values below 1.0 clip the weight tails (measured as a match-rate
collapse) and values at or above 1.0 differ only by grid pitch, with
the 1.2 default pinned by the bench stream (see
:class:`WeightQuantConfig`). The loud-failure contract is PR
10's, shared through :mod:`apex_tpu.serving.quant_common`: an all-zero
or non-finite output channel raises at ENGINE CONSTRUCTION with the
parameter path and channel named, never surfacing later as NaN logits.

Accuracy is the PR 10 contract one tier over: greedy serving under
``Engine(weight_quant=WeightQuantConfig())`` is a token-match-rate
claim vs the bf16 oracle (``bench_serving.py --quantized-weights``),
while ``weight_quant=None`` stays the default and the bitwise baseline
— none of this module is on its trace path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from .quant_common import (QMAX, check_absmax, quantize_host,
                           scale_from_absmax)

__all__ = ["WeightQuantConfig", "QuantDense", "QuantEmbed",
           "param_bytes", "param_count", "quant_scale_absmax"]

# the serving GEMM kernels the tier quantizes, as (path-suffix, channel
# axis) pairs over the TransformerLM tree: Dense kernels are
# [in, out] (channel axis -1); the tied embedding is [vocab, hidden]
# and its head-GEMM output channels are the VOCAB ROWS (axis 0)
_DENSE_SITES = ("attn/qkv", "attn/proj", "mlp_in", "mlp_out")
_SCALE_LEAVES = ("kernel_scale", "embedding_scale")


@dataclasses.dataclass(frozen=True)
class WeightQuantConfig:
    """Storage tier for the serving weights (``Engine(weight_quant=
    WeightQuantConfig())``): int8 GEMM kernels with per-output-channel
    fp32 scales, dequantized in the matmul epilogue.

    Parameters
    ----------
    dtype:
        Weight storage dtype. Only ``int8`` is implemented (the bf16
        default lives at ``weight_quant=None``, not here).
    granularity:
        Only ``"channel"`` (one scale per output channel) is
        implemented — the granularity at which the epilogue fold is
        exact algebra and tensor parallelism shards scales with their
        weights (see the module docstring).
    margin:
        Factor on the per-channel absmax (``scale = absmax * margin /
        QMAX``). Weights are static, so unlike the KV tier no headroom
        is NEEDED — but the setting still matters at both ends:
        margins below 1.0 CLIP the per-channel weight tails and
        collapse the match rate (measured: 0.94 -> 0.47 on the bench
        stream at 0.85), while margins above 1.0 trade a slightly
        coarser grid for nothing systematic — at tiny-model scale the
        near-tie argmaxes make that range noise-dominated, and the
        1.2 default is the value the bench smoke stream pinned at
        token-match-rate 1.0 (both the weights-only and the
        weights+KV combined tier), per the PR 10 tune-then-pin
        contract. Recalibrate on your own stream when the dashboard
        match rate matters more than the pin.
    """

    dtype: Any = jnp.int8
    granularity: str = "channel"
    margin: float = 1.2

    def __post_init__(self):
        if jnp.dtype(self.dtype) != jnp.int8:
            raise ValueError(
                f"WeightQuantConfig supports int8 storage only, got "
                f"{jnp.dtype(self.dtype).name} (bf16 weights are the "
                f"weight_quant=None default, not a quant config)")
        if self.granularity != "channel":
            raise ValueError(
                f"WeightQuantConfig supports granularity='channel' "
                f"(one scale per output channel — the granularity the "
                f"epilogue fold is exact at), got "
                f"{self.granularity!r}")
        if not (np.isfinite(self.margin) and self.margin > 0):
            raise ValueError(f"margin must be finite and > 0, got "
                             f"{self.margin}")

    # ------------------------------------------------------- quantization
    def _quantize_leaf(self, leaf, path: str, axis: int):
        """One kernel/embedding leaf -> ``(int8 codes, fp32 [out]
        scale)`` with the loud per-channel absmax guard. ``axis`` is
        the output-channel axis. Everything runs on HOST copies
        (:func:`~apex_tpu.serving.quant_common.quantize_host`) — no
        full-size leaf transits a device, and the fp32 round-trip
        keeps ml_dtypes halves off numpy ufunc paths (the sharding
        module's own discipline)."""
        w = np.asarray(leaf, np.float32)
        reduce_axes = tuple(a for a in range(w.ndim)
                            if a != axis % w.ndim)
        absmax = check_absmax(
            np.max(np.abs(w), axis=reduce_axes),
            describe=lambda idx: (
                f"weight absmax of {path} output channel {idx[0]}"),
            hint="an all-zero or non-finite output channel cannot be "
                 "per-channel quantized; fix the checkpoint or serve "
                 "this model with weight_quant=None")
        scale = scale_from_absmax(absmax, self.margin)
        q = quantize_host(w, scale, axis=axis % w.ndim)
        return jnp.asarray(q), jnp.asarray(scale)

    def quantize_params(self, params):
        """The quantized parameter tree the engine serves from: every
        targeted GEMM kernel (``attn/qkv``, ``attn/proj``, ``mlp_in``,
        ``mlp_out`` — per-module ``kernel`` leaves) becomes int8 with a
        sibling fp32 ``kernel_scale`` [out] leaf, the tied ``wte``
        embedding becomes int8 with a per-vocab-row ``embedding_scale``
        leaf, and everything else (biases, LayerNorms, ``wpe``) rides
        through untouched in its policy-cast dtype. Raises loudly when
        the tree holds NO quantizable site (a model this tier does not
        understand must not silently serve unquantized) or when any
        output channel's absmax is degenerate."""
        from collections.abc import Mapping

        sites = []

        def _walk(node, prefix):
            if not isinstance(node, Mapping):
                return node
            out = {}
            for name, child in node.items():
                path = f"{prefix}/{name}" if prefix else str(name)
                if name == "kernel" and not isinstance(child, dict) \
                        and prefix.endswith(_DENSE_SITES):
                    q, s = self._quantize_leaf(child, path, axis=-1)
                    out["kernel"] = q
                    out["kernel_scale"] = s
                    sites.append(path)
                elif name == "embedding" \
                        and not isinstance(child, dict) \
                        and prefix.endswith("wte"):
                    q, s = self._quantize_leaf(child, path, axis=0)
                    out["embedding"] = q
                    out["embedding_scale"] = s
                    sites.append(path)
                else:
                    out[name] = _walk(child, path)
            return out

        quantized = _walk(dict(params), "")
        if not sites:
            raise ValueError(
                "weight_quant found no quantizable GEMM kernels in the "
                "parameter tree (expected attn/qkv, attn/proj, mlp_in, "
                "mlp_out kernels and/or a wte embedding — the "
                "TransformerLM serving contract); refusing to serve "
                "silently unquantized")
        return quantized


# ------------------------------------------------------ serving modules
# The flax modules the quantized serving branch of TransformerLM swaps
# in for nn.Dense / nn.Embed. They read the SAME parameter paths
# (<site>/kernel, <site>/bias, wte/embedding) plus the scale leaves
# quantize_params added, so the partition-rule table and every
# checkpoint/sharding tool keep one tree shape to reason about. Used at
# apply time only (the engine provides quantized params); their inits
# exist to satisfy flax's shape validation and are never serving state.
class QuantDense(nn.Module):
    """Dense over an int8 ``kernel`` with the fp32 per-output-channel
    ``kernel_scale`` multiplied onto the accumulator in the epilogue:
    ``y = (x @ Wq) * s + b``. The dot runs in ``dtype`` (the engine's
    inference half — int8 codes cast losslessly: every value in
    [-127, 127] is exact in bf16), the epilogue in fp32 (the same
    fp32-epilogue idiom as the MLP GELU), and the output returns to
    ``dtype`` so downstream dataflow matches ``nn.Dense``'s."""

    features: int
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features),
                            self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros_init(),
                          (self.features,), self.param_dtype)
        scale = self.param("kernel_scale", nn.initializers.ones_init(),
                           (self.features,), jnp.float32)
        dtype = self.dtype or jnp.float32
        # the dot reads dtype-width operands (int8 codes cast
        # losslessly) but KEEPS its accumulator fp32 into the epilogue
        # — the MXU's own semantics, and one fewer rounding than
        # dot-to-bf16 then rescale — where the per-channel scale and
        # the bias apply before the single cast back to dtype
        acc = jax.lax.dot_general(
            jnp.asarray(x, dtype), jnp.asarray(kernel, dtype),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        y = acc * jnp.asarray(scale, jnp.float32) \
            + jnp.asarray(bias, jnp.float32)
        return jnp.asarray(y, dtype)


class QuantEmbed(nn.Module):
    """Embedding over an int8 ``embedding`` with per-vocab-row fp32
    ``embedding_scale``: a lookup gathers its row's codes AND scale
    (one extra [B, S] gather + multiply, dequantized in fp32 then cast
    to ``dtype`` — the serving half, so the residual stream's entry
    width matches the ``nn.Embed`` path it swaps in for), and the
    tied-head GEMM's caller reads ``embedding`` / ``embedding_scale``
    directly to fold the row scales onto the logits accumulator (vocab
    rows ARE the head GEMM's output channels)."""

    num_embeddings: int
    features: int
    dtype: Optional[Any] = None
    param_dtype: Any = jnp.float32

    def setup(self):
        self.embedding = self.param(
            "embedding", nn.initializers.normal(stddev=0.02),
            (self.num_embeddings, self.features), self.param_dtype)
        self.embedding_scale = self.param(
            "embedding_scale", nn.initializers.ones_init(),
            (self.num_embeddings,), jnp.float32)

    def __call__(self, tokens):
        rows = jnp.take(jnp.asarray(self.embedding, jnp.float32),
                        tokens, axis=0)
        rows = rows * jnp.take(self.embedding_scale, tokens)[..., None]
        return jnp.asarray(rows, self.dtype or jnp.float32)


# ------------------------------------------------------- accounting
def param_bytes(params) -> int:
    """Total bytes of a parameter tree — the numerator of the
    ``serving.wq.bytes_per_param`` gauge and the bench leg's
    weight-bytes-reduction claim (global bytes under a mesh: a sharded
    leaf reports its full logical size)."""
    return int(sum(leaf.nbytes
                   for leaf in jax.tree_util.tree_leaves(params)))


def param_count(params) -> int:
    """Total WEIGHT elements of a parameter tree, scale leaves
    excluded — the denominator of ``serving.wq.bytes_per_param``:
    scales are overhead the gauge must charge to the weights they
    dequantize, not dilute away as extra 'parameters'."""
    n = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = str(getattr(path[-1], "key", path[-1]))
        if name not in _SCALE_LEAVES:
            n += int(np.prod(np.shape(leaf)) or 1)
    return n


def quant_scale_absmax(params) -> float:
    """The largest absolute weight the calibrated scales can represent
    (``max(scale) * QMAX`` over every scale leaf) — the
    ``serving.wq.quant_scale_absmax`` gauge. Weights are static, so
    unlike the KV tier's drift signal this is a pure provenance number:
    it changes only when the checkpoint (or margin) does, and a
    dashboard step in it flags a silent weight swap."""
    worst = 0.0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        name = str(getattr(path[-1], "key", path[-1]))
        if name in _SCALE_LEAVES:
            worst = max(worst, float(jnp.max(leaf)))
    return worst * QMAX
