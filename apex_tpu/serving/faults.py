"""Fault isolation for the serving stack: injection, policy, auditing.

Production serving treats bad numerics and flaky steps as *expected
events to absorb*, not crashes — the same stance apex's dynamic loss
scaler takes toward training overflow (detect, skip, keep going). This
module is the serving-side counterpart, three pieces:

- :class:`FaultPlan` — a **seeded, deterministic fault injector**. A
  plan is a schedule of :class:`FaultSpec` events keyed by scheduler
  heartbeat (``tick``): non-finite logit injection into chosen decode
  slots (delivered through the compiled programs' ``fault_bias``
  operand, so the engine's in-program finiteness guard sees REAL
  NaN/Inf logits), transient exceptions raised at the chunk-prefill or
  decode call boundary (:class:`InjectedFault` — raised *instead of*
  the compiled call, so cache state is never half-mutated), heartbeat
  stalls (a plain sleep the watchdog must catch), whole-replica deaths
  consumed by the :class:`~apex_tpu.serving.Router`'s step loop (the
  router-tier fault: the dead replica's requests drain onto the
  survivors), and page-table corruption applied to **debug copies only**
  (:meth:`FaultPlan.corrupt_page_table` — proving the
  :class:`PoolAuditor` detects corruption; it is never pointed at the
  live tables). Deterministic by construction: explicit specs or
  :meth:`FaultPlan.random` from a seed — the chaos tests and
  ``bench_serving.py --chaos`` replay identical schedules.

- :class:`FaultPolicy` — the **per-request containment knobs** the
  scheduler applies when a fault (injected or real) surfaces: requeue
  with capped exponential backoff up to ``max_retries`` then a typed
  ``FAILED`` terminal status, a wall-clock watchdog budget per
  heartbeat (breach → ``serving.watchdog.stall`` + the ``on_stall``
  callback), and the :class:`PoolAuditor` sampling rate. The scheduler
  always runs with a policy (defaults are production-shaped);
  containment is not opt-in.

- :class:`PoolAuditor` — the **page-pool invariant checker**: an
  O(pages) host-side walk reconciling :class:`~apex_tpu.serving
  .PagePool` refcounts against every live slot's page table plus every
  prefix-cache entry's retained pages, plus free-list hygiene
  (no duplicates, refcount-0 only, disjoint from referenced pages) and
  page conservation (in-use + free == allocatable). Any mismatch
  raises :class:`PoolInvariantError` *loudly* — a leaked page
  (refcount above its visible readers: HBM that will never come back)
  or a double-free/dangling reference (refcount below: a table reading
  a page the allocator may hand to someone else) is corruption, not
  telemetry. Run it every event in tests (``every_n=1``); sample it in
  production (``FaultPolicy.audit_every_n``).

The guarantees this layer buys, pinned by ``tests/L0/test_faults.py``:
under an injected fault schedule every un-faulted greedy request
completes **bitwise token-identical** to a fault-free run (healthy
slots in a batch with a quarantined slot keep their exact tokens — the
guard is per-slot, the program is unchanged), every faulted request
reaches a typed terminal status, and the auditor reports zero
leaked/double-freed pages at drain.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.log_util import get_logger

__all__ = ["FaultSpec", "FaultPlan", "FaultPolicy", "InjectedFault",
           "PoolAuditor", "PoolInvariantError", "fault_kind"]

_logger = get_logger("serving")


def fault_kind(error: Optional[str]) -> str:
    """Coarse classification of a quarantine error string — the
    ``kind`` annotation the request tracer stamps on ``quarantine``
    spans (and anything else that wants to bucket faults without
    parsing free text): ``"nonfinite"`` for guard-flagged NaN/Inf
    logits, ``"swap"`` for hierarchical-KV verification failures,
    ``"injected"`` for :class:`InjectedFault` transients (the chaos
    harness's signature), ``"exception"`` for every other transient.
    Checked in that order: an injected *non-finite* fault surfaces
    through the guard's error text and classifies as the numeric
    fault it manifested as."""
    low = (error or "").lower()
    if "non-finite" in low or "nan" in low or "inf " in low:
        return "nonfinite"
    if "swap" in low or "checksum" in low or "crc" in low:
        return "swap"
    if "injectedfault" in low:
        return "injected"
    return "exception"

# injection sites a FaultSpec(kind="exception") may name ("verify" is
# the speculative draft-and-verify call; it only fires on schedulers
# running speculative=True — see FaultPlan.random's ``sites``)
_EXCEPTION_SITES = ("chunk", "decode", "verify")


class InjectedFault(RuntimeError):
    """A :class:`FaultPlan`-scheduled transient failure, raised at the
    compiled-call boundary (the call itself never runs, so engine/cache
    state is exactly what it was before the heartbeat reached the
    call). ``slot`` names the victim slot when the site attributes one
    (decode faults), else -1 (the scheduler attributes the in-flight
    request at the call site)."""

    def __init__(self, message: str, slot: int = -1):
        super().__init__(message)
        self.slot = int(slot)
        self.transient = True


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault.

    ``kind``:

    - ``"nonfinite"`` — add ``value`` (default NaN) to slot ``slot``'s
      decode logits at heartbeat ``tick`` via the decode program's
      ``fault_bias`` operand. The engine's in-program guard must flag
      the slot; every other slot's logits gain exactly ``+0.0``.
    - ``"exception"`` — raise :class:`InjectedFault` at heartbeat
      ``tick`` from injection site ``site`` (``"chunk"`` / ``"decode"``
      / ``"verify"``), instead of running the compiled call.
    - ``"stall"`` — sleep ``stall_s`` seconds at heartbeat ``tick``
      (the watchdog-budget breach the plan manufactures).
    - ``"replica_death"`` — the ROUTER-tier fault: kill replica
      ``replica`` at ROUTER tick ``tick``. Consumed by
      :meth:`FaultPlan.take_replica_deaths` from the
      :class:`~apex_tpu.serving.Router`'s step loop (a scheduler-tier
      plan never sees it) — the router drains the dead replica's
      queued and in-flight requests onto the survivors, so the death
      is a routing event, not an outage.
    - ``"swap_corruption"`` — the hierarchical-KV tier fault: at
      heartbeat ``tick``, flip one byte of a deterministically chosen
      entry in the engine's host-DRAM swap arena
      (:meth:`FaultPlan.maybe_corrupt_swap`, consumed from the
      scheduler's step loop on engines with a
      :class:`~apex_tpu.serving.HostTier`). The NEXT swap-in of the
      victim fails its CRC and must degrade to a verified miss
      (re-prefill, ``serving.swap.verify_failed``) — never a wrong
      token. An injection landing on an entry whose async swap-out is
      still IN FLIGHT (the *swapping* state) is armed instead and rots
      the bytes the moment the worker stores them — the race resolves
      to the same verified miss.
    - ``"handoff_corruption"`` — the disaggregated-serving fault, the
      same arena bit-flip as ``swap_corruption`` but victimizing only
      **handoff records** (arena keys >= 0 — request uids; ordinary
      paged prefixes use negative synthetic keys), via
      :meth:`FaultPlan.maybe_corrupt_handoff`. The decode-side import's
      CRC fails and the request re-prefills on the decode replica
      (``serving.disagg.reprefills``) — never a wrong token, with zero
      retries charged to the request.
    - ``"worker_hang"`` — the PROCESS-fleet fault: worker ``replica``
      stops answering its transport at controller tick ``tick``
      (alive but unresponsive — the failure mode a hard kill can't
      exercise). Consumed by :meth:`FaultPlan.take_worker_hangs` from
      the :class:`~apex_tpu.serving.FleetController`'s step loop; the
      heartbeat's missed-beat detector must declare the worker dead
      and re-route its requests, exactly as if the process had died.
    """

    kind: str
    tick: int
    slot: int = -1
    site: str = "decode"
    value: float = float("nan")
    stall_s: float = 0.0
    replica: int = -1

    def __post_init__(self):
        if self.kind not in ("nonfinite", "exception", "stall",
                             "replica_death", "swap_corruption",
                             "handoff_corruption", "worker_hang"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind == "nonfinite" and self.slot < 0:
            raise ValueError("nonfinite faults need a victim slot")
        if self.kind == "exception" and self.site not in _EXCEPTION_SITES:
            raise ValueError(f"exception site {self.site!r} not in "
                             f"{_EXCEPTION_SITES}")
        if self.kind == "stall" and self.stall_s <= 0:
            raise ValueError("stall faults need stall_s > 0")
        if self.kind == "replica_death" and self.replica < 0:
            raise ValueError("replica_death faults need a victim "
                             "replica index")
        if self.kind == "worker_hang" and self.replica < 0:
            raise ValueError("worker_hang faults need a victim "
                             "replica index")


class FaultPlan:
    """A deterministic schedule of :class:`FaultSpec` events, consulted
    by the scheduler once per heartbeat (see module docstring). Plans
    are replayable: the same specs (or the same :meth:`random` seed)
    produce the same injections in the same heartbeats, which is what
    lets the chaos tests compare a chaos run against a fault-free run
    token-for-token."""

    def __init__(self, specs: Sequence[FaultSpec] = ()):
        self.specs: Tuple[FaultSpec, ...] = tuple(specs)
        self._nonfinite: Dict[int, List[FaultSpec]] = {}
        self._exceptions: Dict[Tuple[str, int], FaultSpec] = {}
        self._stalls: Dict[int, FaultSpec] = {}
        self._deaths: Dict[int, List[FaultSpec]] = {}
        self._swap_corruptions: Dict[int, FaultSpec] = {}
        self._handoff_corruptions: Dict[int, FaultSpec] = {}
        self._hangs: Dict[int, List[FaultSpec]] = {}
        for s in self.specs:
            if s.kind == "nonfinite":
                self._nonfinite.setdefault(int(s.tick), []).append(s)
            elif s.kind == "exception":
                self._exceptions[(s.site, int(s.tick))] = s
            elif s.kind == "replica_death":
                self._deaths.setdefault(int(s.tick), []).append(s)
            elif s.kind == "worker_hang":
                self._hangs.setdefault(int(s.tick), []).append(s)
            elif s.kind == "swap_corruption":
                self._swap_corruptions[int(s.tick)] = s
            elif s.kind == "handoff_corruption":
                self._handoff_corruptions[int(s.tick)] = s
            else:
                self._stalls[int(s.tick)] = s
        # raw injection counters (the chaos bench reads them)
        self.injected_nonfinite = 0
        self.injected_exceptions = 0
        self.injected_stalls = 0
        self.injected_replica_deaths = 0
        self.injected_swap_corruptions = 0
        self.injected_handoff_corruptions = 0
        self.injected_worker_hangs = 0

    @classmethod
    def random(cls, seed: int, ticks: int, *, slots: int,
               nonfinite_rate: float = 0.0, exception_rate: float = 0.0,
               stall_rate: float = 0.0, stall_s: float = 0.05,
               sites: Sequence[str] = ("chunk", "decode"),
               replica_death_rate: float = 0.0,
               replicas: int = 0,
               swap_corruption_rate: float = 0.0,
               handoff_corruption_rate: float = 0.0,
               worker_hang_rate: float = 0.0) -> "FaultPlan":
        """A seeded random schedule over ``ticks`` heartbeats: each
        tick independently draws a non-finite injection (uniform victim
        slot), a transient exception (site uniform over ``sites``),
        and/or a stall at the given per-tick rates. Same seed → same
        schedule, always. ``sites`` defaults to the two call sites every
        scheduler has — include ``"verify"`` only for speculative runs
        (a verify-site fault on a non-speculative scheduler never
        fires). ``replica_death_rate`` > 0 (router-tier plans only;
        requires ``replicas`` >= 1) additionally draws a replica death
        with a uniform victim — the draw is SKIPPED entirely at the
        default rate 0, so pre-router seeds replay bit-for-bit.
        ``swap_corruption_rate`` > 0 (hierarchical-KV engines only)
        draws a host-arena corruption per tick — same skipped-at-0
        contract, so every pre-host-tier seed also replays
        bit-for-bit. ``handoff_corruption_rate`` > 0 (disaggregated
        fleets only) draws a handoff-record corruption per tick — the
        draw is again skipped entirely at the default 0, preserving
        every pre-disaggregation seed. ``worker_hang_rate`` > 0
        (process-fleet plans only; requires ``replicas`` >= 1) draws a
        worker hang with a uniform victim — drawn LAST in the per-tick
        order and skipped entirely at the default 0, so every
        pre-fleet seed replays bit-for-bit."""
        for s in sites:
            if s not in _EXCEPTION_SITES:
                raise ValueError(f"exception site {s!r} not in "
                                 f"{_EXCEPTION_SITES}")
        if replica_death_rate > 0 and replicas < 1:
            raise ValueError("replica_death_rate > 0 needs replicas "
                             ">= 1 to draw victims from")
        if worker_hang_rate > 0 and replicas < 1:
            raise ValueError("worker_hang_rate > 0 needs replicas "
                             ">= 1 to draw victims from")
        rng = np.random.default_rng(seed)
        specs: List[FaultSpec] = []
        for t in range(int(ticks)):
            if rng.random() < nonfinite_rate:
                specs.append(FaultSpec(
                    kind="nonfinite", tick=t,
                    slot=int(rng.integers(0, max(1, slots)))))
            if rng.random() < exception_rate:
                specs.append(FaultSpec(
                    kind="exception", tick=t,
                    site=sites[int(rng.integers(0, len(sites)))]))
            if rng.random() < stall_rate:
                specs.append(FaultSpec(kind="stall", tick=t,
                                       stall_s=stall_s))
            if replica_death_rate > 0 \
                    and rng.random() < replica_death_rate:
                specs.append(FaultSpec(
                    kind="replica_death", tick=t,
                    replica=int(rng.integers(0, replicas))))
            if swap_corruption_rate > 0 \
                    and rng.random() < swap_corruption_rate:
                specs.append(FaultSpec(kind="swap_corruption", tick=t))
            if handoff_corruption_rate > 0 \
                    and rng.random() < handoff_corruption_rate:
                specs.append(FaultSpec(kind="handoff_corruption",
                                       tick=t))
            if worker_hang_rate > 0 \
                    and rng.random() < worker_hang_rate:
                specs.append(FaultSpec(
                    kind="worker_hang", tick=t,
                    replica=int(rng.integers(0, replicas))))
        return cls(specs)

    # ------------------------------------------------------------ injection
    def decode_bias(self, tick: int, slots: int) -> Optional[np.ndarray]:
        """The decode program's per-slot logit bias for this heartbeat:
        ``None`` (no operand worth building) on fault-free ticks, else
        a float32 ``[slots]`` array that is 0.0 everywhere except the
        victim slots' injected values. Victims outside ``[0, slots)``
        are ignored (a random plan drawn for a wider engine stays
        usable)."""
        specs = self._nonfinite.get(int(tick))
        if not specs:
            return None
        bias = np.zeros(int(slots), np.float32)
        hit = False
        for s in specs:
            if 0 <= s.slot < slots:
                bias[s.slot] = np.float32(s.value)
                hit = True
        if not hit:
            return None
        self.injected_nonfinite += 1
        return bias

    def take_nonfinite(self, tick: int, slot: int) -> Optional[float]:
        """CONSUME the non-finite injection scheduled for ``slot`` at
        this heartbeat, if any, returning its value (the verify call's
        scalar ``fault_bias``) — or None. The speculative scheduler
        calls this for each slot it verifies BEFORE building the decode
        batch's :meth:`decode_bias`, so a victim slot that takes the
        verify path this tick still gets its scheduled injection
        (through the verify program's guard instead of the decode
        program's) and is never double-injected."""
        specs = self._nonfinite.get(int(tick))
        if not specs:
            return None
        for i, s in enumerate(specs):
            if s.slot == int(slot):
                specs.pop(i)
                self.injected_nonfinite += 1
                return float(s.value)
        return None

    def maybe_raise(self, site: str, tick: int) -> None:
        """Raise the :class:`InjectedFault` scheduled for ``site`` at
        this heartbeat, if any — called by the scheduler *instead of*
        the compiled call it guards. The spec is CONSUMED when it
        fires: one scheduled fault is one injection with one victim,
        even when the heartbeat makes several calls at the same site
        (chunk budgets > 1, cold-queue bursts)."""
        spec = self._exceptions.pop((site, int(tick)), None)
        if spec is not None:
            self.injected_exceptions += 1
            raise InjectedFault(
                f"injected transient {site} failure at tick {tick}",
                slot=spec.slot)

    def take_replica_deaths(self, tick: int) -> List[int]:
        """CONSUME the replica deaths scheduled for this ROUTER tick,
        returning the victim replica indices (empty on death-free
        ticks). Called by the :class:`~apex_tpu.serving.Router` once
        per step — each spec fires exactly once, like every other
        injection."""
        specs = self._deaths.pop(int(tick), None)
        if not specs:
            return []
        self.injected_replica_deaths += len(specs)
        return [s.replica for s in specs]

    def take_worker_hangs(self, tick: int) -> List[int]:
        """CONSUME the worker hangs scheduled for this CONTROLLER
        tick, returning the victim replica indices (empty on
        hang-free ticks). Called by the
        :class:`~apex_tpu.serving.FleetController` once per step — a
        hung worker stays alive but stops answering its transport, so
        only the missed-beat heartbeat detector can catch it."""
        specs = self._hangs.pop(int(tick), None)
        if not specs:
            return []
        self.injected_worker_hangs += len(specs)
        return [s.replica for s in specs]

    def maybe_corrupt_swap(self, tick: int, tier) -> bool:
        """CONSUME the ``swap_corruption`` scheduled for this
        heartbeat, if any, by flipping one byte of a deterministically
        chosen entry in ``tier`` (a :class:`~apex_tpu.serving
        .HostTier` — victim = the ``tick``-th resident key in sorted
        order, so replays corrupt the same entry). Called by the
        scheduler once per heartbeat on hierarchical-KV engines. An
        empty arena makes the injection a no-op (nothing swapped yet —
        the spec is still consumed at its tick, like every other
        injection, but not counted as delivered). Returns True when a
        byte actually flipped."""
        spec = self._swap_corruptions.pop(int(tick), None)
        if spec is None:
            return False
        keys = sorted(tier.keys())
        if not keys:
            return False
        tier.corrupt_entry(keys[int(tick) % len(keys)])
        self.injected_swap_corruptions += 1
        return True

    def maybe_corrupt_handoff(self, tick: int, tier) -> bool:
        """CONSUME the ``handoff_corruption`` scheduled for this
        heartbeat, if any, by flipping one byte of a deterministically
        chosen HANDOFF record in ``tier`` — victims are the uid-keyed
        records only (arena keys >= 0; ordinary paged prefixes mint
        negative synthetic keys), so the injection lands on the
        cross-replica transfer path specifically. Rides the exact
        ``swap_corruption`` plumbing: an arena with no handoff records
        makes the injection a no-op (spec still consumed at its tick),
        and a victim whose swap-out is still in flight is armed to rot
        on store. Returns True when a byte actually flipped."""
        spec = self._handoff_corruptions.pop(int(tick), None)
        if spec is None:
            return False
        keys = sorted(k for k in tier.keys() if k >= 0)
        if not keys:
            return False
        tier.corrupt_entry(keys[int(tick) % len(keys)])
        self.injected_handoff_corruptions += 1
        return True

    def maybe_stall(self, tick: int) -> float:
        """Sleep through the stall scheduled for this heartbeat (if
        any); returns the seconds slept (0.0 on stall-free ticks)."""
        spec = self._stalls.get(int(tick))
        if spec is None:
            return 0.0
        self.injected_stalls += 1
        time.sleep(spec.stall_s)
        return spec.stall_s

    def corrupt_page_table(self, page_table: np.ndarray,
                           n_pages: np.ndarray, *, slot: int = 0,
                           entry: int = 0,
                           value: int = -1) -> np.ndarray:
        """Corrupt one entry of a **debug copy** of a page table (the
        auditor-sensitivity probe: a corrupted copy must make
        :meth:`PoolAuditor.audit` raise). Refuses to write through to
        what looks like live engine state — pass
        ``Engine.page_table_snapshot()`` output. Returns the corrupted
        table for chaining."""
        if not page_table.flags.writeable or not page_table.flags.owndata:
            raise ValueError(
                "corrupt_page_table mutates its argument and is meant "
                "for DEBUG COPIES (Engine.page_table_snapshot()) — "
                "refusing a view/read-only array that may be live "
                "engine state")
        if not int(n_pages[slot]):
            raise ValueError(f"slot {slot} holds no pages to corrupt")
        entry = int(entry) % int(n_pages[slot])
        page_table[slot, entry] = value
        return page_table

    def stats(self) -> dict:
        """Injection counts so far (the chaos bench's honesty row)."""
        return {
            "scheduled": len(self.specs),
            "injected_nonfinite": self.injected_nonfinite,
            "injected_exceptions": self.injected_exceptions,
            "injected_stalls": self.injected_stalls,
            "injected_replica_deaths": self.injected_replica_deaths,
            "injected_swap_corruptions": self.injected_swap_corruptions,
            "injected_handoff_corruptions":
                self.injected_handoff_corruptions,
            "injected_worker_hangs": self.injected_worker_hangs,
        }


@dataclasses.dataclass
class FaultPolicy:
    """The scheduler's containment knobs (always on; these defaults are
    the production shape — tests tighten ``audit_every_n`` to 1 and
    zero the backoff for speed).

    - ``max_retries``: transient faults a request may absorb before its
      typed ``FAILED`` terminal status (each fault releases the slot
      and its pages, then requeues).
    - ``backoff_base_s`` / ``backoff_cap_s``: capped exponential
      backoff between retries (``base * 2**(retries-1)``, capped) — a
      requeued request is not re-admitted before its backoff elapses.
    - ``watchdog_budget_s``: wall-clock budget per scheduler heartbeat;
      a breach emits ``serving.watchdog.stall`` (+ the breach duration
      into the ``serving.watchdog.stall_s`` histogram) and invokes
      ``on_stall(elapsed_s)``. ``None`` disables the watchdog.
      Heartbeats that TRACE a compiled program (first contact with
      chunk/decode/prefill/verify) are exempt — their wall time is
      one-off compile latency, observed separately as
      ``serving.watchdog.warmup_s`` — so tiny budgets no longer
      false-trip on tick 0 of a cold engine.
    - ``audit_every_n``: run the :class:`PoolAuditor` every N
      finish/eviction events (1 = every event — the test setting; the
      default samples). ``0`` disables auditing.
    """

    max_retries: int = 2
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 2.0
    watchdog_budget_s: Optional[float] = None
    on_stall: Optional[Callable[[float], None]] = None
    audit_every_n: int = 64

    def backoff_s(self, retries: int) -> float:
        """Backoff before retry number ``retries`` (1-based)."""
        if self.backoff_base_s <= 0:
            return 0.0
        return min(self.backoff_base_s * (2.0 ** (max(int(retries), 1)
                                                  - 1)),
                   self.backoff_cap_s)


class PoolInvariantError(RuntimeError):
    """A page-pool invariant does not hold: leaked pages (refcounted
    above their visible readers), double-frees/dangling references
    (below), free-list corruption, or an out-of-range/sentinel page id
    in a live table. Raised loudly by :meth:`PoolAuditor.audit` —
    this is corruption, not a telemetry event."""


class PoolAuditor:
    """Reconcile a paged engine's :class:`~apex_tpu.serving.PagePool`
    refcounts with everything that can legitimately hold a page: live
    slot page tables and prefix-cache entries (see module docstring).
    O(pages + table entries) of pure numpy/python per audit — cheap
    enough for ``every_n=1`` in tests; sample in production.

    ``maybe_audit`` is the scheduler's hook (counts events, audits
    every ``every_n``-th); ``audit`` is the full check, callable with
    debug-copy overrides so the chaos tests can prove a corrupted
    table is *detected*."""

    def __init__(self, every_n: int = 1, registry=None):
        self.every_n = int(every_n)
        self._registry = registry
        self._events = 0
        self.audits = 0

    def maybe_audit(self, engine) -> Optional[dict]:
        """Count one auditable event (request finish, prefix eviction);
        run :meth:`audit` on every ``every_n``-th. No-op (None) when
        sampling skips this event or auditing is disabled."""
        if self.every_n <= 0:
            return None
        self._events += 1
        if self._events % self.every_n:
            return None
        return self.audit(engine)

    def audit(self, engine, page_table: Optional[np.ndarray] = None,
              n_pages: Optional[np.ndarray] = None) -> dict:
        """Walk the pool and raise :class:`PoolInvariantError` on any
        violation; returns a summary dict when everything reconciles.
        ``page_table``/``n_pages`` override the engine's live tables
        with debug copies (the corruption-detection probe)."""
        if not getattr(engine, "paged", False):
            raise RuntimeError("PoolAuditor audits paged engines only")
        pool = engine.pool
        if page_table is None:
            page_table = engine._page_table
        if n_pages is None:
            n_pages = engine._n_pages
        num_pages = pool.num_pages
        problems: List[str] = []
        expected = np.zeros(num_pages, np.int64)
        for s in range(page_table.shape[0]):
            n = int(n_pages[s])
            for p in page_table[s, :n]:
                p = int(p)
                if not 0 < p < num_pages:
                    problems.append(
                        f"slot {s} table holds page id {p} outside the "
                        f"allocatable range (1, {num_pages}) — corrupt "
                        f"entry or sentinel in the live region")
                else:
                    expected[p] += 1
        pcache = getattr(engine, "prefix_cache", None)
        if pcache is not None:
            for pages in pcache.page_holds():
                for p in pages:
                    p = int(p)
                    if not 0 < p < num_pages:
                        problems.append(
                            f"prefix entry holds out-of-range page id "
                            f"{p}")
                    else:
                        expected[p] += 1
        ref = np.asarray(pool.refcount, np.int64)
        leaked = np.flatnonzero(ref > expected)
        dangling = np.flatnonzero(ref < expected)
        if leaked.size:
            problems.append(
                f"LEAKED pages {leaked.tolist()}: refcount "
                f"{ref[leaked].tolist()} exceeds visible readers "
                f"{expected[leaked].tolist()} — these pages can never "
                f"return to the free list")
        if dangling.size:
            problems.append(
                f"DOUBLE-FREED/dangling pages {dangling.tolist()}: "
                f"visible readers {expected[dangling].tolist()} exceed "
                f"refcount {ref[dangling].tolist()} — a table "
                f"references a page the allocator may reuse")
        free = [int(p) for p in pool.free_list()]
        free_set = set(free)
        if len(free_set) != len(free):
            problems.append("free list holds duplicate page ids")
        if 0 in free_set:
            problems.append("sentinel page 0 is on the free list")
        out_of_range = [p for p in free_set if not 0 <= p < num_pages]
        if out_of_range:
            problems.append(
                f"free list holds out-of-range page ids "
                f"{out_of_range} — a future alloc would hand out a "
                f"page that does not exist")
        bad_free = [p for p in free_set
                    if 0 < p < num_pages and ref[p] != 0]
        if bad_free:
            problems.append(
                f"pages {bad_free} are on the free list with nonzero "
                f"refcounts")
        # conservation against an INDEPENDENT quantity (pages_in_use is
        # derived from the free list, so comparing those two would be a
        # tautology): every allocatable page must be either free or
        # refcounted — a page that is neither has fallen out of the
        # allocator entirely and can never be handed out again
        lost = [p for p in range(1, num_pages)
                if ref[p] == 0 and p not in free_set]
        if lost:
            problems.append(
                f"pages {lost} are neither free nor referenced — lost "
                f"from the allocator (conservation broken)")
        # hierarchical KV: the host-DRAM tier must reconcile with the
        # prefix cache's swapped state — a swapped entry holds no
        # device pages (it already left the `expected` walk above), but
        # swap-in/out must never strand bytes on either side. Three
        # invariants: (1) every swapped index entry is backed by a
        # host-arena record (a dangling entry would swap in nothing —
        # or garbage), (2) every arena record backs a swapped entry
        # (an orphan is host DRAM that can never be read again — the
        # host-side leak), (3) the arena's byte accounting matches its
        # stored arrays and respects its capacity bound.
        tier = getattr(engine, "host_tier", None)
        if tier is not None:
            tier_keys = set(tier.keys())
            if not getattr(engine, "host_tier_shared", False):
                # the two set-inclusion directions are PER-ENGINE
                # invariants only when the engine owns the tier: in a
                # SHARED arena (disaggregated serving) other engines'
                # records legitimately coexist, and a handoff record is
                # momentarily ownerless between the exporter dropping
                # its entry and the importer registering one — the
                # disaggregation test asserts the FLEET-level union
                # equality instead. The byte ledger and capacity bound
                # below are tier-global and hold either way.
                swapped = set(pcache.swapped_keys()) \
                    if pcache is not None else set()
                dangling_swap = sorted(swapped - tier_keys)
                if dangling_swap:
                    problems.append(
                        f"swapped prefix entries {dangling_swap} have "
                        f"no host-tier backing — a hit would find "
                        f"nothing to swap in (dangling swap state)")
                orphaned = sorted(tier_keys - swapped)
                if orphaned:
                    problems.append(
                        f"host-tier entries {orphaned} back no swapped "
                        f"prefix entry — unreachable host bytes "
                        f"(host-side leak)")
            actual = sum(tier.nbytes_of(k) for k in tier_keys)
            if actual != tier.bytes_used:
                problems.append(
                    f"host-tier byte accounting drifted: reports "
                    f"{tier.bytes_used}, stored arrays hold {actual}")
            if tier.bytes_used > tier.capacity_bytes:
                problems.append(
                    f"host tier over capacity: {tier.bytes_used} bytes "
                    f"held against a {tier.capacity_bytes}-byte bound")
        self.audits += 1
        if self._registry is not None:
            self._registry.counter_inc("serving.faults.audits")
        if problems:
            raise PoolInvariantError(
                "page-pool invariant audit failed:\n  - "
                + "\n  - ".join(problems))
        return {
            "pages": num_pages,
            "pages_in_use": pool.pages_in_use,
            "pages_free": len(free),
            "cow_shares": pool.cow_shares,
            "audits": self.audits,
        }
