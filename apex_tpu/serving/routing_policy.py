"""The routing-policy core shared by :class:`~apex_tpu.serving.Router`
(threads in one interpreter) and
:class:`~apex_tpu.serving.FleetController` (one OS process per
replica).

Both fronts make the same two-signal decision — longest probed prefix
first, host-side load as the tie-break, spill across the candidate
order, fleet-level :class:`~apex_tpu.serving.QueueFull` carrying the
MAX of the per-replica ``retry_after_s`` hints — and the decision must
stay IDENTICAL whether the inputs arrived as in-process method calls
or as deserialized wire forms: the fleet's bitwise-parity pin
(`tests/L0/test_fleet.py`) compares token streams across the two
fronts, and any drift in ranking order would silently re-home requests
and break it. So the decision functions live HERE, pure and
host-only: no engine, no scheduler, no socket — just candidate
indices, probed match lengths and :meth:`Scheduler.load_snapshot`
dicts (or their wire forms — the ranking reads only the snapshot's
load keys, which serialization preserves verbatim).

Nothing in this module imports jax, numpy-heavy machinery or the
serving stack: a controller process that never builds an engine can
rank a fleet with only these functions and the snapshots its workers
shipped over.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional, Sequence

__all__ = [
    "PLACEMENTS_CAP",
    "ROUTE_POLICIES",
    "fleet_retry_hint",
    "note_placement",
    "random_order",
    "rank_replicas",
]

#: The routing policies a replica front accepts: ``"affinity"``
#: (longest probed prefix, load tie-break), ``"least_loaded"`` (load
#: only), ``"random"`` (seeded control row).
ROUTE_POLICIES = ("affinity", "least_loaded", "random")

#: Placement-log entries kept (insertion order; re-placement
#: refreshes). Far above any live-request census — the cap only sheds
#: long-finished uids.
PLACEMENTS_CAP = 65536


def rank_replicas(candidates: Sequence[int],
                  match_lens: Mapping[int, int],
                  snapshots: Mapping[int, Mapping],
                  priority: int = 0,
                  adapter_hits: Optional[Mapping[int, int]] = None,
                  ) -> List[int]:
    """The candidate replicas best-first: longest probed prefix match,
    then resident-adapter hit (desc — see below), then free slots
    (desc), queue depth (asc), free pool pages (desc), host-arena
    headroom (desc), index (the deterministic last resort).
    ``snapshots[i]`` is a :meth:`Scheduler.load_snapshot` dict — or its
    wire form: the key set is part of the snapshot's versioned wire
    contract, so both fronts rank on identical fields. ``pages_free``
    / ``host_bytes_free`` may be None (unpaged / no host tier) and
    rank as 0 — absent capacity is not headroom.

    ``priority`` is the routed request's STATIC base priority
    (``SLOConfig.base_priority`` — deterministic arithmetic, no clock,
    so both fronts compute the identical value). For a prioritized
    request (> 0) the pages tie-break counts ``preemptible_pages`` —
    pages a replica could reclaim by preempting lower-priority work —
    as free: a prioritized arrival ranks a preemption-rich replica as
    having that headroom NOW. Priority-0 requests (and snapshots
    predating the field — ``.get`` tolerates both wire v1 and literal
    test dicts) rank exactly as before.

    ``adapter_hits`` is the LoRA-affinity signal: ``adapter_hits[i]``
    is 1 when the routed request's adapter is resident in replica
    ``i``'s device arena (its snapshot's ``resident_adapters``
    membership — a bind there is a hit, elsewhere a swap-in), 0
    otherwise. Ranked right after the prefix match and before free
    slots: re-homing a resident adapter costs a full arena row
    re-place, more than a slot's worth of queueing. None (base-model
    requests, LoRA-less fleets) ranks exactly as before."""
    return sorted(candidates, key=lambda i: (
        -match_lens[i],
        -(adapter_hits[i] if adapter_hits is not None else 0),
        -snapshots[i]["slots_free"],
        snapshots[i]["queue_depth"],
        -((snapshots[i]["pages_free"] or 0)
          + ((snapshots[i].get("preemptible_pages") or 0)
             if priority > 0 else 0)),
        # hierarchical-KV tie-break: of two replicas equal on
        # slots/queue/pages, prefer the one with more host-arena
        # headroom — landing work on a replica whose swap arena is
        # nearly full accelerates its swapped-prefix shedding
        -(snapshots[i]["host_bytes_free"] or 0),
        i))


def random_order(candidates: Sequence[int], rng) -> List[int]:
    """The ``"random"`` policy's seeded shuffle (the bench's control
    row): a plain permutation of the candidates drawn from the
    caller's ``numpy`` Generator, so a front holding the same seed
    routes the same stream identically."""
    return [int(i) for i in rng.permutation(list(candidates))]


def fleet_retry_hint(
        hints: Iterable[Optional[float]]) -> Optional[float]:
    """The fleet-level ``retry_after_s``: the MAX of the per-replica
    hints (the fleet has space when its slowest-to-free replica does);
    None when no replica offered a measured hint — a replica with no
    decode EMA contributes None and never fakes a number."""
    return max((h for h in hints if h is not None), default=None)


def note_placement(placements: Dict[int, int], uid: int,
                   index: int, cap: int = PLACEMENTS_CAP) -> None:
    """Record ``uid`` → replica ``index`` in the bounded placement log
    (observability state — routing never reads it back). Pop-then-set
    refreshes insertion order, so the cap always sheds the
    LONGEST-finished uid first."""
    placements.pop(uid, None)
    placements[uid] = index
    while len(placements) > cap:
        placements.pop(next(iter(placements)))
